"""jepsen_tpu: a TPU-native distributed-systems safety-testing framework.

A Python control plane drives a database cluster with purely functional
operation generators, injects faults, and records an append-only operation
history; a JAX/XLA/Pallas analysis plane checks those histories for
consistency violations on TPU.

Capability reference: seanpm2001/jepsen (jepsen-io/jepsen v0.3.6-SNAPSHOT);
see SURVEY.md at the repo root for the structural map this build follows.
This is a ground-up TPU-first design, not a port: the compute-heavy
checkers (linearizability search, transactional cycle detection) are
batched tensor kernels rather than graph searches over JVM objects.
"""

__version__ = "0.1.0"
