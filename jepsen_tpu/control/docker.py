"""Remote that runs node commands via `docker exec` / `docker cp`.

Capability reference: jepsen/src/jepsen/control/docker.clj — resolve a
container from the conn-spec host (docker.clj:14-28: a host:port maps
to the container publishing that port, a bare name is used directly),
execute with `docker exec ... sh -c cmd` (30-38), transfer files with
`docker cp` (57-75), the Remote record (77-88).

Local subprocess invocation is injectable (`runner`) so suites can run
clusterless against a scripted docker CLI.
"""

from __future__ import annotations

import re
import subprocess
from typing import Callable

from .core import (Action, Remote, RemoteError, Result, Session,
                   wrap_sudo)


def _default_runner(argv, stdin=None, timeout=600.0) -> Result:
    from .core import TransportError

    try:
        proc = subprocess.run(argv, input=stdin, capture_output=True,
                              text=True, timeout=timeout)
    except subprocess.TimeoutExpired as e:
        # same contract as the ssh remote: a timed-out command may
        # still be running — RemoteError, never silently retried
        raise RemoteError(f"{argv[0]} command timed out",
                          cmd=" ".join(argv)) from e
    except OSError as e:  # spawn failure (e.g. no docker/kubectl)
        raise TransportError(f"{argv[0]} spawn failed: {e}",
                             cmd=" ".join(argv)) from e
    return Result(exit=proc.returncode, out=proc.stdout,
                  err=proc.stderr, cmd=" ".join(argv))


def resolve_container_id(host, runner: Callable = _default_runner) -> str:
    """Container id/name for a conn-spec host: 'host:port' finds the
    container publishing that port (docker.clj:14-28); anything else is
    taken as a container name/id directly."""
    host = str(host)
    if ":" in host:
        _addr, port = host.rsplit(":", 1)
        ps = runner(["docker", "ps"]).out
        for line in ps.splitlines()[1:]:
            # PUBLISHED port only (":PORT->"); matching the container-
            # internal side ("->PORT/") would resolve every node to
            # the first container sharing a service port
            if re.search(rf":{re.escape(port)}->", line):
                return line.split()[0]
        raise RemoteError(f"no container publishes port {port}",
                          node=host, cmd="docker ps")
    return host


class DockerSession(Session):
    def __init__(self, container_id: str, runner: Callable):
        self.container_id = container_id
        self.runner = runner

    def execute(self, action: Action) -> Result:
        cmd = wrap_sudo(action)
        argv = ["docker", "exec"]
        if action.stdin is not None:
            argv.append("-i")
        argv += [self.container_id, "sh", "-c", cmd]
        res = self.runner(argv, stdin=action.stdin,
                          timeout=action.timeout)
        return Result(exit=res.exit, out=res.out, err=res.err, cmd=cmd)

    def _cp(self, src: str, dst: str) -> None:
        res = self.runner(["docker", "cp", src, dst])
        if res.exit != 0:
            raise RemoteError("docker cp failed", exit=res.exit,
                              out=res.out, err=res.err, cmd=res.cmd,
                              node=self.container_id)

    def upload(self, local_paths, remote_path) -> None:
        if isinstance(local_paths, str):
            local_paths = [local_paths]
        for p in local_paths:
            self._cp(str(p), f"{self.container_id}:{remote_path}")

    def download(self, remote_paths, local_path) -> None:
        if isinstance(remote_paths, str):
            remote_paths = [remote_paths]
        for p in remote_paths:
            self._cp(f"{self.container_id}:{p}", str(local_path))


class DockerRemote(Remote):
    """docker-exec transport (docker.clj:90-92)."""

    def __init__(self, runner: Callable = _default_runner):
        self.runner = runner

    def connect(self, conn_spec: dict) -> DockerSession:
        cid = resolve_container_id(conn_spec["host"], self.runner)
        return DockerSession(cid, self.runner)
