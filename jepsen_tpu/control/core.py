"""Remote-execution protocol and shell command construction.

Capability reference: jepsen/src/jepsen/control/core.clj (Remote protocol
7-62, shell escaping/env 64-144, sudo wrapping 146-175).
"""

from __future__ import annotations

import shlex
from dataclasses import dataclass, field
from typing import Any, Optional


class RemoteError(Exception):
    """Command failed on a remote node."""

    def __init__(self, message, exit=None, out=None, err=None, cmd=None,
                 node=None):
        self.exit = exit
        self.out = out
        self.err = err
        self.cmd = cmd
        self.node = node
        super().__init__(
            f"{message} (node={node}, cmd={cmd!r}, exit={exit}, "
            f"out={out!r}, err={err!r})")


class TransportError(RemoteError):
    """The transport itself failed (connection refused/dropped, ssh
    exit 255, timeout) — the command may never have run. Safe to retry
    at the remote layer (the reference's ::ssh-failed class,
    control/retry.clj:1-14); a command's own non-zero exit is NOT a
    TransportError."""


@dataclass
class Action:
    """A command to run remotely: argv string, optional stdin, sudo user,
    working dir, and a wall-clock timeout in seconds."""

    cmd: str
    stdin: Optional[str] = None
    sudo: Optional[str] = None
    sudo_password: Optional[str] = None
    dir: Optional[str] = None
    timeout: float = 600.0


@dataclass
class Result:
    exit: int
    out: str
    err: str
    cmd: str


class Remote:
    """Transport for running commands and moving files on nodes
    (control/core.clj:7-62)."""

    def connect(self, conn_spec: dict) -> "Session":
        raise NotImplementedError


class Session:
    def disconnect(self) -> None:
        pass

    def execute(self, action: Action) -> Result:
        raise NotImplementedError

    def upload(self, local_paths, remote_path) -> None:
        raise NotImplementedError

    def download(self, remote_paths, local_path) -> None:
        raise NotImplementedError


class Lit(str):
    """A literal shell fragment that escape() passes through untouched —
    for pipes, redirects, and globs (the reference passes these as bare
    Clojure symbols, which its escaping also leaves alone)."""


def escape(arg: Any) -> str:
    """Shell-escapes a single argument. Keywords/numbers pass through as
    their string form (control/core.clj:64-101)."""
    if isinstance(arg, Lit):
        return str(arg)
    s = str(arg)
    if s and all(c.isalnum() or c in "-_.,/=:+@%^" for c in s):
        return s
    return shlex.quote(s)


def join_cmd(*args) -> str:
    """Builds a shell command string from args, escaping each. Lists are
    flattened; None skipped."""
    parts = []
    for a in args:
        if a is None:
            continue
        if isinstance(a, (list, tuple)):
            parts.extend(escape(x) for x in a)
        else:
            parts.append(escape(a))
    return " ".join(parts)


def env_string(env: dict | None) -> str:
    """FOO=bar A=b prefix string (control/core.clj env, 103-126)."""
    if not env:
        return ""
    return " ".join(f"{k}={escape(v)}" for k, v in env.items()) + " "


def wrap_sudo(action: Action) -> str:
    """Wraps an action's command in sudo -S -u USER sh -c '...'
    (control/core.clj:146-175)."""
    if not action.sudo:
        cmd = action.cmd
    else:
        cmd = (f"sudo -S -u {escape(action.sudo)} bash -c "
               f"{shlex.quote(action.cmd)}")
    if action.dir:
        cmd = f"cd {escape(action.dir)} && {cmd}"
    return cmd


def throw_on_nonzero_exit(node, res: Result) -> Result:
    if res.exit != 0:
        raise RemoteError("command returned non-zero exit status",
                          exit=res.exit, out=res.out, err=res.err,
                          cmd=res.cmd, node=node)
    return res


# Attribute budget for traced commands: enough to identify the command
# in a trace viewer without shipping multi-KB stdin/scripts along.
_TRACE_CMD_CHARS = 200


def traced_execute(session: "Session", action: Action,
                   node=None) -> Result:
    """Runs `action` through `session.execute` inside a 'remote' trace
    span carrying cmd, node, duration, and exit code — one child span
    per remote command under the op that issued it (the tracing layer
    no-ops unless the run opted in and an op context is open on this
    thread). Transport/remote errors close the span with the error
    class; the retry layer stamps its attempt count on the same span
    via tracing.annotate."""
    from .. import tracing

    tr = tracing.get()
    if not tr.enabled:
        return session.execute(action)
    cmd = action.cmd or ""
    name = cmd.split(None, 1)[0] if cmd.split() else "(empty)"
    with tr.span("remote", f"remote.{name}",
                 cmd=cmd[:_TRACE_CMD_CHARS],
                 node=str(node) if node is not None else None,
                 sudo=action.sudo) as rec:
        try:
            res = session.execute(action)
        except RemoteError as e:
            if rec is not None:
                rec.setdefault("attrs", {}).update(
                    error=type(e).__name__, exit=e.exit)
            raise
        if rec is not None:
            rec.setdefault("attrs", {})["exit"] = res.exit
        return res


def traced_transfer(session: "Session", direction: str, paths,
                    dest, node=None):
    """upload/download under a 'remote' trace span (scp commands are
    remote work too — a snarf or data-file push shows up in the op
    trace like any command)."""
    from .. import tracing

    tr = tracing.get()
    fn = getattr(session, direction)
    if not tr.enabled:
        return fn(paths, dest)
    with tr.span("remote", f"remote.scp.{direction}",
                 node=str(node) if node is not None else None) as rec:
        try:
            return fn(paths, dest)
        except RemoteError as e:
            if rec is not None:
                rec.setdefault("attrs", {}).update(
                    error=type(e).__name__, exit=e.exit)
            raise
