"""Remote that runs node commands via `kubectl exec` / `kubectl cp`.

Capability reference: jepsen/src/jepsen/control/k8s.clj — exec into the
pod named by the conn-spec host (k8s.clj:79-92), `kubectl cp` transfers
(30-75), optional --context/--namespace parameters (76-78), and
list_pods (99-111).
"""

from __future__ import annotations

from typing import Callable

from .core import Action, Remote, RemoteError, Result, Session, wrap_sudo
from .docker import _default_runner


class K8sSession(Session):
    def __init__(self, pod: str, flags: list, runner: Callable):
        self.pod = pod
        self.flags = flags
        self.runner = runner

    def execute(self, action: Action) -> Result:
        cmd = wrap_sudo(action)
        argv = ["kubectl", "exec", *self.flags]
        if action.stdin is not None:
            argv.append("-i")
        argv += [self.pod, "--", "sh", "-c", cmd]
        res = self.runner(argv, stdin=action.stdin,
                          timeout=action.timeout)
        return Result(exit=res.exit, out=res.out, err=res.err, cmd=cmd)

    def _cp(self, src: str, dst: str) -> None:
        res = self.runner(["kubectl", "cp", *self.flags, src, dst])
        if res.exit != 0:
            raise RemoteError("kubectl cp failed", exit=res.exit,
                              out=res.out, err=res.err, cmd=res.cmd,
                              node=self.pod)

    def upload(self, local_paths, remote_path) -> None:
        if isinstance(local_paths, str):
            local_paths = [local_paths]
        for p in local_paths:
            self._cp(str(p), f"{self.pod}:{remote_path}")

    def download(self, remote_paths, local_path) -> None:
        if isinstance(remote_paths, str):
            remote_paths = [remote_paths]
        for p in remote_paths:
            self._cp(f"{self.pod}:{p}", str(local_path))


class K8sRemote(Remote):
    """kubectl-exec transport (k8s.clj:79-97)."""

    def __init__(self, context: str | None = None,
                 namespace: str | None = None,
                 runner: Callable = _default_runner):
        self.context = context
        self.namespace = namespace
        self.runner = runner

    def _flags(self) -> list:
        flags = []
        if self.context:
            flags.append(f"--context={self.context}")
        if self.namespace:
            flags.append(f"--namespace={self.namespace}")
        return flags

    def connect(self, conn_spec: dict) -> K8sSession:
        return K8sSession(str(conn_spec["host"]), self._flags(),
                          self.runner)


def list_pods(context: str | None = None, namespace: str | None = None,
              runner: Callable = _default_runner) -> list[str]:
    """Pod names in a context/namespace (k8s.clj:99-111)."""
    flags = K8sRemote(context, namespace)._flags()
    res = runner(["kubectl", "get", "pods", *flags,
                  "-o", "jsonpath={.items[*].metadata.name}"])
    if res.exit != 0:
        raise RemoteError("kubectl get pods failed", exit=res.exit,
                          out=res.out, err=res.err, cmd=res.cmd)
    return [p for p in res.out.split() if p]
