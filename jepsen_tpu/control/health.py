"""Per-node health: transport-failure circuit breakers + quarantine.

A node that dies mid-run used to make every op against it burn the full
reconnect-retry budget (seconds each), and a run whose DB teardown hit
the dead node aborted entirely. This layer gives the control plane the
standard remedy: a circuit breaker per node.

  - CLOSED:    commands flow; consecutive transport failures count up.
  - OPEN:      after `threshold` consecutive transport failures the
               node is quarantined — commands fail IMMEDIATELY with
               TransportError("quarantined"), so client ops crash to
               :info in microseconds instead of stalling workers, and
               the run continues :degraded instead of aborting
               (core.analyze stamps results["degraded"]).
  - HALF-OPEN: after `cooldown_s` one probe command is let through; a
               success closes the circuit (the node healed — maybe the
               nemesis restarted it), a failure re-opens it.

Opt in with test["quarantine?"] = True (core.run builds the registry
and control.remote_for wraps the test's remote). The breaker counts
ONLY TransportError — a command's own non-zero exit means the node is
alive and talking. See doc/robustness.md.
"""

from __future__ import annotations

import logging
import threading
import time as _time

from .. import telemetry
from .core import Action, Remote, Session, TransportError

logger = logging.getLogger(__name__)

DEFAULT_THRESHOLD = 3
DEFAULT_COOLDOWN_S = 10.0


class Quarantined(TransportError):
    """The node's circuit is open: the command was rejected without
    touching the transport. A TransportError subclass so every
    existing crash-to-:info / retry-classification path treats it as
    the node being unreachable (which it is, just cheaply)."""


class CircuitBreaker:
    """One node's breaker. Thread-safe: many workers share a node."""

    def __init__(self, node, threshold: int = DEFAULT_THRESHOLD,
                 cooldown_s: float = DEFAULT_COOLDOWN_S):
        self.node = node
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._lock = threading.Lock()
        self._failures = 0          # consecutive transport failures
        self._open_since: float | None = None
        self._probing = False
        self.opened_count = 0       # times the circuit opened (stats)

    @property
    def is_open(self) -> bool:
        with self._lock:
            return self._open_since is not None

    def state(self) -> str:
        """'closed', 'open', or 'half-open' (cooldown elapsed: the
        next command probes, or one already is) — the per-node badge
        the web run page and the node plane's breaker records surface
        (jepsen_tpu.nodeprobe)."""
        with self._lock:
            if self._open_since is None:
                return "closed"
            if (self._probing
                    or _time.monotonic() - self._open_since
                    >= self.cooldown_s):
                return "half-open"
            return "open"

    def admit(self) -> bool:
        """May a command proceed? False = quarantined (fail fast).
        In the half-open window exactly one caller is admitted as the
        probe; the rest keep failing fast until it reports back."""
        with self._lock:
            if self._open_since is None:
                return True
            if (not self._probing
                    and _time.monotonic() - self._open_since
                    >= self.cooldown_s):
                self._probing = True  # this caller probes
                granted = True
            else:
                granted = False
        if granted:
            # the open -> half-open transition, next to the opened/
            # healed counters (state transitions as telemetry)
            telemetry.count("control.quarantine.half-open")
        return granted

    def success(self) -> None:
        with self._lock:
            was_open = self._open_since is not None
            self._failures = 0
            self._open_since = None
            self._probing = False
        if was_open:
            telemetry.count("control.quarantine.healed")
            logger.info("node %s healed; circuit closed", self.node)

    def abort_probe(self) -> None:
        """The admitted call died for a NON-transport reason (local
        OSError, a bug in the caller): no verdict on the node, but the
        probe slot must free or a half-open circuit wedges forever."""
        with self._lock:
            self._probing = False

    def failure(self) -> None:
        with self._lock:
            self._failures += 1
            self._probing = False
            just_opened = (self._open_since is None
                           and self._failures >= self.threshold)
            if just_opened:
                self._open_since = _time.monotonic()
                self.opened_count += 1
            elif self._open_since is not None:
                self._open_since = _time.monotonic()  # re-arm cooldown
        if just_opened:
            telemetry.count("control.quarantine.opened")
            logger.warning(
                "node %s quarantined after %d consecutive transport "
                "failures; its ops will fail fast (run continues "
                ":degraded)", self.node, self._failures)


class HealthRegistry:
    """The per-test map node -> CircuitBreaker, shared by every session
    to that node (test["health"])."""

    def __init__(self, threshold: int = DEFAULT_THRESHOLD,
                 cooldown_s: float = DEFAULT_COOLDOWN_S):
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._lock = threading.Lock()
        self._breakers: dict = {}
        self._advisories: dict = {}

    @classmethod
    def from_test(cls, test: dict) -> "HealthRegistry":
        q = test.get("quarantine?")
        opts = q if isinstance(q, dict) else {}
        return cls(threshold=int(opts.get("threshold",
                                          DEFAULT_THRESHOLD)),
                   cooldown_s=float(opts.get("cooldown_s",
                                             DEFAULT_COOLDOWN_S)))

    def breaker(self, node) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(node)
            if b is None:
                b = self._breakers[node] = CircuitBreaker(
                    node, self.threshold, self.cooldown_s)
            return b

    def states(self) -> dict:
        """{node: breaker state} for every node a breaker exists for —
        the telemetry view the node plane records as `breaker`
        transitions and the web run page badges."""
        with self._lock:
            breakers = list(self._breakers.values())
        return {b.node: b.state() for b in breakers}

    def advise(self, node, reason: str, value=None) -> None:
        """An ADVISORY health signal from the node observability plane
        (jepsen_tpu.nodeprobe: low memory, cpu saturation). Logged and
        counted, never a breaker verdict — a loaded node is not a dead
        node, and metrics must not trip circuits (transport failures
        alone do that)."""
        with self._lock:
            self._advisories.setdefault(node, {})[str(reason)] = value
        telemetry.count("control.health.advisories")
        telemetry.count(f"control.health.advisory.{reason}")
        logger.warning("node %s health advisory: %s (%r) — advisory "
                       "only, circuit unaffected", node, reason, value)

    def advisories(self) -> dict:
        """{node: {reason: last value}} of advisories received."""
        with self._lock:
            return {n: dict(v) for n, v in self._advisories.items()}

    def quarantined(self) -> list:
        """Nodes whose circuit is currently open."""
        with self._lock:
            breakers = list(self._breakers.values())
        return [b.node for b in breakers if b.is_open]

    def ever_quarantined(self) -> list:
        """Nodes that were quarantined at any point in the run — the
        :degraded marker wants the full story even if a node later
        healed."""
        with self._lock:
            breakers = list(self._breakers.values())
        return [b.node for b in breakers if b.opened_count > 0]


class GuardedSession(Session):
    """A session gated by its node's circuit breaker."""

    def __init__(self, inner: Session, breaker: CircuitBreaker):
        self.inner = inner
        self.breaker = breaker

    def _guarded(self, f):
        if not self.breaker.admit():
            telemetry.count("control.quarantine.rejected")
            raise Quarantined(
                "node is quarantined (circuit open)",
                node=self.breaker.node)
        try:
            res = f()
        except TransportError:
            self.breaker.failure()
            raise
        except BaseException:
            self.breaker.abort_probe()  # no verdict; free the slot
            raise
        self.breaker.success()
        return res

    def execute(self, action: Action):
        return self._guarded(lambda: self.inner.execute(action))

    def upload(self, local_paths, remote_path):
        return self._guarded(
            lambda: self.inner.upload(local_paths, remote_path))

    def download(self, remote_paths, local_path):
        return self._guarded(
            lambda: self.inner.download(remote_paths, local_path))

    def disconnect(self) -> None:
        self.inner.disconnect()


class GuardedRemote(Remote):
    """Wraps another Remote so every session shares the test's health
    registry. Sits OUTSIDE the retry wrapper in the default stack: a
    command first burns its (budgeted) retries, and only the final
    transport verdict feeds the breaker — transient one-retry blips
    don't open circuits."""

    def __init__(self, remote: Remote, registry: HealthRegistry):
        self.remote = remote
        self.registry = registry

    def connect(self, conn_spec: dict) -> Session:
        breaker = self.registry.breaker(conn_spec.get("host"))
        if not breaker.admit():
            telemetry.count("control.quarantine.rejected")
            raise Quarantined("node is quarantined (circuit open)",
                              node=breaker.node)
        try:
            inner = self.remote.connect(conn_spec)
        except TransportError:
            breaker.failure()
            raise
        except BaseException:
            breaker.abort_probe()  # no verdict; free the slot
            raise
        # a returned session is NOT a success verdict: the default
        # stack's RetryingRemote.connect just constructs lazily (no
        # network I/O), so crediting it would reset the failure count
        # before every command and the circuit would never open. The
        # first command's real transport outcome decides.
        breaker.abort_probe()
        return GuardedSession(inner, breaker)


class LazyConnectSession(Session):
    """Placeholder for a node whose session could not open (dead at
    run start, or died and was disconnected): every use retries the
    connect through the guarded stack, so a healed node springs back
    and a dead one fails fast once its circuit opens. This is what
    lets control.open_sessions keep a run alive when a node is down —
    the node's ops crash to :info instead of the whole run aborting."""

    def __init__(self, remote: Remote, conn_spec: dict):
        self.remote = remote
        self.conn_spec = conn_spec
        self._lock = threading.Lock()
        self._inner: Session | None = None

    def _sess(self) -> Session:
        with self._lock:
            if self._inner is None:
                self._inner = self.remote.connect(self.conn_spec)
            return self._inner

    def _drop(self) -> None:
        with self._lock:
            inner, self._inner = self._inner, None
        if inner is not None:
            try:
                inner.disconnect()
            except Exception:  # noqa: BLE001 — already failing
                pass

    def _via(self, f):
        try:
            return f(self._sess())
        except TransportError:
            self._drop()  # reconnect on the next use
            raise

    def execute(self, action: Action):
        return self._via(lambda s: s.execute(action))

    def upload(self, local_paths, remote_path):
        return self._via(lambda s: s.upload(local_paths, remote_path))

    def download(self, remote_paths, local_path):
        return self._via(lambda s: s.download(remote_paths, local_path))

    def disconnect(self) -> None:
        self._drop()


def probe(test: dict, node) -> bool:
    """One cheap liveness command against `node` through the guarded
    stack; True = the node answered (and the breaker saw a success).
    Used by explicit health sweeps and tests."""
    from . import with_session

    try:
        with with_session(test, node) as sess:
            sess.execute(Action(cmd="true", timeout=10.0))
        return True
    except TransportError:
        return False


def probe_all(test: dict) -> dict:
    """{node: alive?} across the test's nodes, in parallel."""
    from .. import util
    from . import on_nodes  # noqa: F401 — doc pointer

    nodes = list(test.get("nodes") or [])
    return dict(zip(nodes, util.real_pmap(
        lambda n: probe(test, n), nodes)))
