"""Auto-reconnecting, retrying wrapper around any Remote.

Capability reference: jepsen/src/jepsen/control/retry.clj:35-72 — SSH
client stacks fail spuriously; their commands can almost always be
retried. The wrapper keeps the underlying session in a reconnect
wrapper (jepsen_tpu.reconnect) and retries TRANSPORT failures (the
analog of the reference's ::ssh-failed — never a command's own
non-zero exit, which comes back as a Result) with jittered backoff,
cycling the session between attempts.
"""

from __future__ import annotations

import random
import time

from .. import reconnect, tracing
from .core import Action, Remote, Result, Session, TransportError

RETRIES = 5
BACKOFF_S = 0.1


class RetryingSession(Session):
    def __init__(self, remote: Remote, conn_spec: dict):
        self.conn_spec = conn_spec
        self.wrapper = reconnect.Wrapper(
            open=lambda: remote.connect(conn_spec),
            close=lambda s: s.disconnect(),
            name=("control", conn_spec.get("host")))
        self.wrapper.open()

    def _with_retry(self, f):
        tries = RETRIES
        while True:
            try:
                # cycle the session ONLY on transport failures: a
                # command's own error (nonzero exit, missing file on
                # scp) must not tear down the shared ControlMaster and
                # kill other threads' in-flight multiplexed commands
                with self.wrapper.with_conn(
                        cycle_on=TransportError) as sess:
                    return f(sess)
            except TransportError as e:
                if tries <= 0:
                    raise
                tries -= 1
                # stamp the attempt count on the ambient 'remote'
                # trace span (control.traced_execute opened it around
                # this whole retry loop), so a command that limped
                # through on attempt 3 carries retries=3
                tracing.annotate(retries=RETRIES - tries)
                tracing.event("remote-retry",
                              node=self.conn_spec.get("host"),
                              attempt=RETRIES - tries,
                              error=str(e)[:160])
                time.sleep(BACKOFF_S / 2 + random.random() * BACKOFF_S)

    def execute(self, action: Action) -> Result:
        return self._with_retry(lambda s: s.execute(action))

    def upload(self, local_paths, remote_path) -> None:
        return self._with_retry(
            lambda s: s.upload(local_paths, remote_path))

    def download(self, remote_paths, local_path) -> None:
        return self._with_retry(
            lambda s: s.download(remote_paths, local_path))

    def disconnect(self) -> None:
        self.wrapper.close()


class RetryingRemote(Remote):
    """Wraps another Remote so transport failures reconnect + retry
    (retry.clj `remote`, 67-72)."""

    def __init__(self, remote: Remote):
        self.remote = remote

    def connect(self, conn_spec: dict) -> RetryingSession:
        return RetryingSession(self.remote, conn_spec)
