"""Auto-reconnecting, retrying wrapper around any Remote.

Capability reference: jepsen/src/jepsen/control/retry.clj:35-72 — SSH
client stacks fail spuriously; their commands can almost always be
retried. The wrapper keeps the underlying session in a reconnect
wrapper (jepsen_tpu.reconnect) and retries TRANSPORT failures (the
analog of the reference's ::ssh-failed — never a command's own
non-zero exit, which comes back as a Result) with decorrelated-jitter
backoff, cycling the session between attempts.

Two safeguards against retry storms (doc/robustness.md):

  - *Decorrelated jitter* (the AWS architecture-blog algorithm): each
    sleep is uniform(BACKOFF_S, 3 * previous_sleep), capped. A fixed
    backoff synchronizes every worker's reconnect attempts against a
    recovering node into thundering-herd waves; decorrelation spreads
    them.
  - *Per-session retry budget*: a session may spend at most
    SESSION_RETRY_BUDGET retries between successes (a successful
    command refunds the budget — the node answered). A genuinely dead
    node otherwise costs every command its full per-command retry
    count forever; once the budget is gone, transport failures
    propagate immediately (and the quarantine breaker, when enabled,
    starts rejecting in microseconds).
"""

from __future__ import annotations

import random
import threading
import time

from .. import reconnect, telemetry, tracing
from .core import Action, Remote, Result, Session, TransportError

RETRIES = 5
BACKOFF_S = 0.1
BACKOFF_CAP_S = 3.0
SESSION_RETRY_BUDGET = 64


class RetryBudget:
    """Thread-safe retry allowance shared by all commands on one
    session."""

    def __init__(self, limit: int = SESSION_RETRY_BUDGET):
        self.limit = limit
        self._lock = threading.Lock()
        self._spent = 0

    def try_spend(self) -> bool:
        """Takes one retry from the budget; False = exhausted (the
        caller must give up instead of sleeping + retrying)."""
        with self._lock:
            if self._spent >= self.limit:
                return False
            self._spent += 1
            return True

    def refund(self) -> None:
        """A command SUCCEEDED: the node is alive, so spent retries
        replenish. Without this, routine nemesis partition windows in
        a multi-hour run drain the lifetime budget and late-run
        transient blips fail fast forever — the budget should only
        starve sessions to nodes that never answer."""
        with self._lock:
            self._spent = 0

    @property
    def spent(self) -> int:
        with self._lock:
            return self._spent

    @property
    def exhausted(self) -> bool:
        with self._lock:
            return self._spent >= self.limit


def decorrelated_jitter(prev_s: float, base_s: float | None = None,
                        cap_s: float | None = None,
                        rng=None) -> float:
    """The next backoff sleep: uniform(base, 3 * prev), capped.
    base/cap default to the module knobs at CALL time so tests (and
    operators) can tune them with a monkeypatch/assignment."""
    rng = rng or random
    if base_s is None:
        base_s = BACKOFF_S
    if cap_s is None:
        cap_s = BACKOFF_CAP_S
    return min(cap_s, base_s + rng.random() * max(3 * prev_s - base_s,
                                                  0.0))


class RetryingSession(Session):
    def __init__(self, remote: Remote, conn_spec: dict,
                 budget: RetryBudget | None = None):
        self.conn_spec = conn_spec
        self.budget = budget if budget is not None else RetryBudget()
        self.wrapper = reconnect.Wrapper(
            open=lambda: remote.connect(conn_spec),
            close=lambda s: s.disconnect(),
            name=("control", conn_spec.get("host")))
        self.wrapper.open()

    def _with_retry(self, f):
        tries = RETRIES
        sleep_s = BACKOFF_S
        while True:
            try:
                # cycle the session ONLY on transport failures: a
                # command's own error (nonzero exit, missing file on
                # scp) must not tear down the shared ControlMaster and
                # kill other threads' in-flight multiplexed commands
                with self.wrapper.with_conn(
                        cycle_on=TransportError) as sess:
                    res = f(sess)
                self.budget.refund()  # the node answered
                return res
            except TransportError as e:
                if tries <= 0:
                    raise
                if not self.budget.try_spend():
                    # budget exhausted: this session has retried enough
                    # for one lifetime — fail fast and let the caller
                    # (worker crash-to-:info, quarantine breaker)
                    # handle a node that is actually down
                    telemetry.count("control.retry.budget-exhausted")
                    tracing.event("remote-retry-budget-exhausted",
                                  node=self.conn_spec.get("host"))
                    raise
                tries -= 1
                # stamp the attempt count on the ambient 'remote'
                # trace span (control.traced_execute opened it around
                # this whole retry loop), so a command that limped
                # through on attempt 3 carries retries=3
                tracing.annotate(retries=RETRIES - tries)
                tracing.event("remote-retry",
                              node=self.conn_spec.get("host"),
                              attempt=RETRIES - tries,
                              error=str(e)[:160])
                sleep_s = decorrelated_jitter(sleep_s)
                time.sleep(sleep_s)

    def execute(self, action: Action) -> Result:
        return self._with_retry(lambda s: s.execute(action))

    def upload(self, local_paths, remote_path) -> None:
        return self._with_retry(
            lambda s: s.upload(local_paths, remote_path))

    def download(self, remote_paths, local_path) -> None:
        return self._with_retry(
            lambda s: s.download(remote_paths, local_path))

    def disconnect(self) -> None:
        self.wrapper.close()


class RetryingRemote(Remote):
    """Wraps another Remote so transport failures reconnect + retry
    (retry.clj `remote`, 67-72). budget_limit bounds retries per
    session (see SESSION_RETRY_BUDGET)."""

    def __init__(self, remote: Remote, budget_limit: int | None = None):
        self.remote = remote
        self.budget_limit = budget_limit

    def connect(self, conn_spec: dict) -> RetryingSession:
        budget = (RetryBudget(self.budget_limit)
                  if self.budget_limit is not None else RetryBudget())
        return RetryingSession(self.remote, conn_spec, budget=budget)
