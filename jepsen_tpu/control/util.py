"""Scripting helpers for node installs and daemon management.

Capability reference: jepsen/src/jepsen/control/util.clj — await-tcp-port
(14-30), file?/exists?/ls (32-63), tmp-file!/tmp-dir!/write-file!
(65-106), wget family + cache (108-196), install-archive! (198-264),
ensure-user! (266-273), grepkill! (275-301), start-daemon!/stop-daemon!/
daemon-running?/signal! (303-408).
"""

from __future__ import annotations

import base64
import logging
import os.path
import random
import re

from .. import util as jutil
from . import cd, current_node, exec_, exec_result
from .core import Lit, RemoteError, env_string, escape

logger = logging.getLogger(__name__)

TMP_DIR_BASE = "/tmp/jepsen"
WGET_CACHE_DIR = TMP_DIR_BASE + "/wget-cache"

STD_WGET_OPTS = ["--tries", "20", "--waitretry", "60",
                 "--retry-connrefused", "--dns-timeout", "60",
                 "--connect-timeout", "60", "--read-timeout", "60"]


def await_tcp_port(port, retry_interval: float = 1.0,
                   log_interval: float = 10.0,
                   timeout_secs: float = 60.0) -> None:
    """Blocks until a local TCP port is bound (control/util.clj:14-30)."""
    jutil.await_fn(lambda: exec_("nc", "-z", "localhost", port),
                   retry_interval=retry_interval,
                   log_interval=log_interval, timeout_secs=timeout_secs,
                   log_message=f"Waiting for port {port} ...")


def file_p(filename) -> bool:
    """Is filename a regular file? (control/util.clj file?)"""
    try:
        exec_("test", "-f", filename)
        return True
    except RemoteError:
        return False


def exists_p(path) -> bool:
    """Is a path present? (control/util.clj exists?)"""
    try:
        exec_("stat", path)
        return True
    except RemoteError:
        return False


def ls(directory: str = ".") -> list:
    """Directory entries, without . and .. (control/util.clj:50-56)."""
    out = exec_("ls", "-A", directory)
    return [line for line in out.split("\n") if line.strip()]


def ls_full(directory: str) -> list:
    if not directory.endswith("/"):
        directory += "/"
    return [directory + e for e in ls(directory)]


def tmp_file() -> str:
    """Creates a random temp file under TMP_DIR_BASE, returning its path
    (control/util.clj tmp-file!). Atomic: noclobber create instead of a
    probe-then-touch race (which also loops forever against remotes
    whose stat always succeeds, like the dummy)."""
    exec_("mkdir", "-p", TMP_DIR_BASE)
    while True:
        path = f"{TMP_DIR_BASE}/{random.randrange(2 ** 31)}"
        try:
            exec_("bash", "-c", f"set -C; : > {path}")
            return path
        except RemoteError:
            continue


def tmp_dir() -> str:
    """Creates a random temp dir under TMP_DIR_BASE
    (control/util.clj tmp-dir!). Atomic: bare mkdir fails if present."""
    exec_("mkdir", "-p", TMP_DIR_BASE)
    while True:
        path = f"{TMP_DIR_BASE}/{random.randrange(2 ** 31)}"
        try:
            exec_("mkdir", path)
            return path
        except RemoteError:
            continue


def write_file(string: str, filename) -> str:
    """Writes a string to a remote file via stdin
    (control/util.clj write-file!)."""
    exec_("cat", Lit(">"), filename, stdin=string)
    return filename


def _wget_helper(*args) -> str:
    """wget with retries on network errors (exit 4)
    (control/util.clj wget-helper!)."""
    tries = 5
    while True:
        try:
            return exec_("wget", *args)
        except RemoteError as e:
            if e.exit == 4 and tries > 0:
                tries -= 1
                continue
            raise


def wget(url: str, force: bool = False, user: str | None = None,
         pw: str | None = None) -> str:
    """Downloads url into the cwd unless present; returns the filename
    (control/util.clj wget!)."""
    filename = os.path.basename(url)
    opts = list(STD_WGET_OPTS)
    if user:
        assert pw is not None, "wget auth needs both user and pw"
        opts += ["--user", user, "--password", pw]
    if force:
        exec_("rm", "-f", filename)
    if not exists_p(filename):
        _wget_helper(*opts, url)
    return filename


def cached_wget(url: str, force: bool = False, user: str | None = None,
                pw: str | None = None) -> str:
    """Downloads url into the wget cache keyed by base64(url) — version
    changes in the URL can't silently alias — returning the local path
    (control/util.clj cached-wget!)."""
    encoded = base64.b64encode(url.encode()).decode()
    dest = f"{WGET_CACHE_DIR}/{encoded}"
    opts = list(STD_WGET_OPTS) + ["-O", dest]
    if user:
        assert pw is not None, "wget auth needs both user and pw"
        opts += ["--user", user, "--password", pw]
    if force:
        logger.info("Clearing cached copy of %s", url)
        exec_("rm", "-rf", dest)
    if not exists_p(dest):
        logger.info("Downloading %s", url)
        exec_("mkdir", "-p", WGET_CACHE_DIR)
        with cd(WGET_CACHE_DIR):
            _wget_helper(*opts, url)
    return dest


def expand_path(path: str) -> str:
    if path.startswith("~"):
        return exec_("readlink", "-f", path)
    return path


def install_archive(url: str, dest: str, force: bool = False,
                    user: str | None = None, pw: str | None = None,
                    _retrying: bool = False) -> str:
    """Fetches a tarball/zip (http(s):// via the wget cache, or file://
    on the node), extracts it, and moves its contents to dest
    (control/util.clj install-archive!). A single top-level directory is
    unwrapped: foolib-1.2.3/my.file becomes dest/my.file."""
    m = re.match(r"file://(.+)", url)
    local_file = m.group(1) if m else None
    archive = local_file or cached_wget(url, force=force, user=user, pw=pw)
    tmpdir = tmp_dir()
    dest = expand_path(dest)
    exec_("rm", "-rf", dest)
    parent = exec_("dirname", dest)
    exec_("mkdir", "-p", parent)
    try:
        with cd(tmpdir):
            if re.search(r"\.zip$", url):
                exec_("unzip", archive)
            else:
                exec_("tar", "--no-same-owner", "--no-same-permissions",
                      "--extract", "--file", archive)
            from . import _sudo
            if _sudo.get() == "root":
                exec_("chown", "-R", "root:root", ".")
            roots = ls(tmpdir)
            assert roots, "Archive contained no files"
            if len(roots) == 1:
                exec_("mv", f"{tmpdir}/{roots[0]}", dest)
            else:
                exec_("mv", tmpdir, dest)
    except RemoteError as e:
        err = e.err or ""
        corrupt = ("tar: Unexpected EOF" in err
                   or "This does not look like a tar archive" in err
                   or "cannot find zipfile directory" in err)
        if corrupt:
            if local_file or _retrying:
                raise RuntimeError(
                    f"Local archive {archive} on node {current_node()} "
                    f"is corrupt: {err}") from e
            logger.info("Retrying corrupt archive download")
            exec_("rm", "-rf", archive)
            return install_archive(url, dest, force=True, user=user,
                                   pw=pw, _retrying=True)
        raise
    finally:
        exec_("rm", "-rf", tmpdir)
    return dest


def ensure_user(username: str) -> str:
    """Makes sure a user exists (control/util.clj ensure-user!)."""
    from . import su
    try:
        with su():
            exec_("adduser", "--disabled-password", "--gecos", Lit("''"),
                  username)
    except RemoteError as e:
        if "already exists" not in str(e):
            raise
    return username


def grepkill(pattern, signal="9") -> None:
    """Kills processes matching a pattern. pgrep --ignore-ancestors keeps
    the sudo/bash wrapper running this very command out of the match set
    (control/util.clj grepkill!)."""
    sig = str(signal)
    if not sig.isdigit():
        sig = sig.upper()
    try:
        exec_("pgrep", "-f", "--ignore-ancestors", pattern, Lit("|"),
              "xargs", "--no-run-if-empty", "kill", f"-{sig}")
    except RemoteError as e:
        if e.exit == 0:
            return
        if e.exit == 123 and "No such process" in (e.err or ""):
            return  # process exited between pgrep and kill
        raise


def start_daemon(opts: dict, bin, *args) -> str:
    """Starts a daemon via start-stop-daemon, appending stdout+stderr to
    opts['logfile'] (control/util.clj start-daemon!). Returns 'started'
    or 'already-running'.

    opts: env, background (default True), chdir, exec, logfile,
    make_pidfile (default True), match_executable (default True),
    match_process_name (default False), pidfile, process_name."""
    env = env_string(opts.get("env"))
    ssd: list = ["--start"]
    if opts.get("background", True):
        ssd += ["--background", "--no-close"]
    if opts.get("pidfile") and opts.get("make_pidfile", True):
        ssd += ["--make-pidfile"]
    if opts.get("match_executable", True):
        ssd += ["--exec", opts.get("exec") or bin]
    if opts.get("match_process_name", False):
        ssd += ["--name",
                opts.get("process_name") or os.path.basename(str(bin))]
    if opts.get("pidfile"):
        ssd += ["--pidfile", opts["pidfile"]]
    ssd += ["--chdir", opts["chdir"], "--startas", bin, "--",
            *args, Lit(">>"), opts["logfile"], Lit("2>&1")]
    logger.info("Starting %s", os.path.basename(str(bin)))
    exec_("echo", Lit("`date +'%Y-%m-%d %H:%M:%S'`"),
          f"Jepsen starting {env}{bin} {' '.join(str(a) for a in args)}",
          Lit(">>"), opts["logfile"])
    try:
        exec_(Lit(env.strip()) if env else None, "start-stop-daemon", *ssd)
        return "started"
    except RemoteError as e:
        if e.exit == 1:
            return "already-running"
        raise


def stop_daemon(cmd_or_pidfile, pidfile=None) -> None:
    """Kills a daemon by pidfile, or by command name + pidfile cleanup
    (control/util.clj stop-daemon!)."""
    if pidfile is None and not isinstance(cmd_or_pidfile, tuple):
        pf = cmd_or_pidfile
        if exists_p(pf):
            logger.info("Stopping %s", pf)
            pid = int(exec_("cat", pf))
            jutil.meh(lambda: exec_("kill", "-9", pid))
            jutil.meh(lambda: exec_("rm", "-rf", pf))
        return
    cmd = cmd_or_pidfile
    logger.info("Stopping %s", cmd)
    jutil.meh(lambda: exec_("killall", "-9", "-w", cmd, timeout=30.0))
    if pidfile:
        jutil.meh(lambda: exec_("rm", "-rf", pidfile))


def daemon_running(pidfile) -> bool | None:
    """True if pidfile exists and its process is alive; None if absent;
    False if present but dead (control/util.clj daemon-running?)."""
    try:
        pid = exec_("cat", pidfile)
    except RemoteError:
        return None
    try:
        exec_("ps", "-o", "pid=", "-p", pid)
        return True
    except RemoteError:
        return False


def signal(process_name, sig) -> str:
    """Sends a signal to a named process (control/util.clj signal!)."""
    jutil.meh(lambda: exec_("pkill", "--signal", sig, process_name))
    return "signaled"
