"""Sudo-aware file-transfer wrapper around any Remote.

Capability reference: jepsen/src/jepsen/control/scp.clj:82-146. The
reference wraps a command-capable remote so uploads/downloads work even
when the ambient `su` user differs from the connection user: uploads go
to a world-writable tmpfile, then chown + mv as root; downloads of
files the connection user can't read are hardlinked (or copied) to a
tmpfile, chowned readable, then fetched. Our SSH session already shells
out to scp for the fast path (ssh.py), so this wrapper adds only the
privilege dance, reading the ambient sudo user from control.su().
"""

from __future__ import annotations

import os
import random
from contextlib import contextmanager

from .core import (Action, Remote, RemoteError, Session, join_cmd,
                   throw_on_nonzero_exit)

TMP_DIR = "/tmp/jepsen/scp"


def _coll(paths):
    if isinstance(paths, (str, os.PathLike)):
        return [paths]
    return list(paths)


def _safe_basename(path) -> str:
    """Basename for the remote tmp path. Legacy scp passes the remote
    path through a shell, so anything beyond clearly-safe characters
    falls back to a neutral name (the destination keeps the real name —
    mv takes it from remote_path or the directory form)."""
    name = os.path.basename(str(path))
    if name and all(c.isalnum() or c in "-_.,+@%" for c in name):
        return name
    return "file"


def _ambient_sudo():
    from . import _sudo
    return _sudo.get()


class ScpSession(Session):
    """Delegates commands to the base session; transfers grow a
    become-another-user path (scp.clj upload!/download!, 98-146)."""

    def __init__(self, base: Session, conn_spec: dict):
        self.base = base
        self.user = conn_spec.get("username", "root")
        self.node = conn_spec.get("host")
        self._tmp_dir_ready = False

    def execute(self, action: Action):
        return self.base.execute(action)

    def disconnect(self) -> None:
        self.base.disconnect()

    def _exec(self, *args, sudo="root", check=True):
        res = self.base.execute(Action(cmd=join_cmd(*args), sudo=sudo))
        if check:
            throw_on_nonzero_exit(self.node, res)
        return res

    def _ensure_tmp_dir(self) -> None:
        # One round-trip per session, not per transfer (the reference
        # instead retries the whole body after mkdir on first failure,
        # scp.clj:28-40 — same effect, different bookkeeping)
        if not self._tmp_dir_ready:
            self._exec("install", "-d", "-m", "0777", TMP_DIR)
            self._tmp_dir_ready = True

    @contextmanager
    def _tmp_file(self, basename: str):
        # The tmpfile keeps the source's basename inside a fresh random
        # subdir, so multi-file transfers into a directory destination
        # land under their real names instead of the tmp name
        self._ensure_tmp_dir()
        sub = f"{TMP_DIR}/{random.randrange(2**31)}"
        # World-writable in one round-trip: the dir is created as root
        # but the scp itself runs as the connection user
        self._exec("install", "-d", "-m", "0777", sub)
        try:
            yield f"{sub}/{basename}"
        finally:
            try:
                self._exec("rm", "-rf", sub, check=False)
            except RemoteError:
                # Cleanup is best-effort: a transport drop here must
                # not mask the body's real error (or turn a
                # deterministic failure into a retryable one)
                pass

    def upload(self, local_paths, remote_path) -> None:
        sudo = _ambient_sudo()
        if sudo is None or sudo == self.user:
            return self.base.upload(local_paths, remote_path)
        # Upload as the connection user, then chown + move into place
        # as root (scp.clj:98-111). With several sources the
        # destination is a directory; mv each under its real basename
        # (the exec path escapes properly, unlike scp's remote path).
        srcs = _coll(local_paths)
        for src in srcs:
            name = os.path.basename(str(src))
            with self._tmp_file(_safe_basename(src)) as tmp:
                self.base.upload(src, tmp)
                self._exec("chown", sudo, tmp)
                # A directory destination must receive the REAL
                # basename even when the tmp name was sanitized; the
                # exec path escapes arbitrary names safely. With one
                # source we can't assume dest is a dir — probe only in
                # the rare sanitized case.
                if len(srcs) > 1:
                    dest = f"{remote_path}/{name}"
                elif (name != _safe_basename(src)
                      and self._is_dir(remote_path)):
                    dest = f"{remote_path}/{name}"
                else:
                    dest = remote_path
                self._exec("mv", tmp, dest)

    def download(self, remote_paths, local_path) -> None:
        sudo = _ambient_sudo()
        if sudo is None or sudo == self.user:
            return self.base.download(remote_paths, local_path)
        for src in _coll(remote_paths):
            if self._readable(src):
                self.base.download(src, local_path)
                continue
            # Copy the file somewhere we can chown it readable, then
            # fetch that (scp.clj:113-146). The reference hardlinks
            # first (ln -L) for speed, but chowning a hardlink chowns
            # the shared inode — permanently mutating the source file
            # on the system under test — so we always pay the copy.
            name = os.path.basename(str(src))
            with self._tmp_file(_safe_basename(src)) as tmp:
                self._exec("cp", src, tmp)
                self._exec("chown", self.user, tmp)
                self.base.download(tmp, local_path)
                # Into a local directory, a sanitized tmp name lands
                # as "file": restore the real basename (local rename —
                # no escaping concerns)
                if (name != _safe_basename(src)
                        and os.path.isdir(local_path)):
                    got = os.path.join(str(local_path),
                                       _safe_basename(src))
                    if os.path.exists(got):
                        os.replace(got, os.path.join(str(local_path),
                                                     name))

    def _is_dir(self, path) -> bool:
        res = self.base.execute(
            Action(cmd=join_cmd("test", "-d", path), sudo="root"))
        return res.exit == 0

    def _readable(self, path) -> bool:
        # Ordinary "can't read" comes back as a nonzero-exit Result;
        # exceptions here are transport failures and must propagate to
        # the retry layer, not masquerade as an unreadable file
        res = self.base.execute(
            Action(cmd=join_cmd("head", "-c", 1, path)))
        return res.exit == 0


class ScpRemote(Remote):
    """Wraps a Remote so transfers honor the ambient su() user
    (scp.clj remote, 148-152)."""

    def __init__(self, remote: Remote):
        self.remote = remote

    def connect(self, conn_spec: dict) -> ScpSession:
        return ScpSession(self.remote.connect(conn_spec), conn_spec)
