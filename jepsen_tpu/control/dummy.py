"""A no-op remote: every control action silently succeeds.

Capability reference: the clj-ssh remote's :dummy? mode
(jepsen/src/jepsen/control/clj_ssh.clj:43-85), which is how the reference
runs its entire lifecycle clusterless in tests.
"""

from __future__ import annotations

from .core import Action, Remote, Result, Session


class DummySession(Session):
    def __init__(self, node):
        self.node = node
        self.log: list = []  # actions recorded for test assertions

    def execute(self, action: Action) -> Result:
        self.log.append(action)
        return Result(exit=0, out="", err="", cmd=action.cmd)

    def upload(self, local_paths, remote_path) -> None:
        self.log.append(("upload", local_paths, remote_path))

    def download(self, remote_paths, local_path) -> None:
        self.log.append(("download", remote_paths, local_path))


class DummyRemote(Remote):
    def connect(self, conn_spec: dict) -> DummySession:
        return DummySession(conn_spec.get("host"))


dummy = DummyRemote()
