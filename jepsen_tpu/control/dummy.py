"""A no-op remote: every control action silently succeeds.

Capability reference: the clj-ssh remote's :dummy? mode
(jepsen/src/jepsen/control/clj_ssh.clj:43-85), which is how the reference
runs its entire lifecycle clusterless in tests.

Tests that need command *output* (e.g. `getent ahostsv4` for IP
resolution, `ip -o link show` for device discovery) pass a `responder`:
a callable `(node, action) -> str | Result | None` consulted before the
default empty success.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

from .core import Action, Remote, Result, Session

Responder = Callable[[object, Action], Union[str, Result, None]]


class DummySession(Session):
    def __init__(self, node, responder: Optional[Responder] = None):
        self.node = node
        self.responder = responder
        self.log: list = []  # actions recorded for test assertions

    def execute(self, action: Action) -> Result:
        self.log.append(action)
        if self.responder is not None:
            r = self.responder(self.node, action)
            if isinstance(r, Result):
                return r
            if r is not None:
                return Result(exit=0, out=r, err="", cmd=action.cmd)
        return Result(exit=0, out="", err="", cmd=action.cmd)

    def upload(self, local_paths, remote_path) -> None:
        self.log.append(("upload", local_paths, remote_path))

    def download(self, remote_paths, local_path) -> None:
        self.log.append(("download", remote_paths, local_path))


class DummyRemote(Remote):
    def __init__(self, responder: Optional[Responder] = None):
        self.responder = responder

    def connect(self, conn_spec: dict) -> DummySession:
        return DummySession(conn_spec.get("host"), self.responder)


dummy = DummyRemote()
