"""SSH remote via the system OpenSSH binaries.

Capability reference: jepsen/src/jepsen/control/sshj.clj (default SSHJ
remote, 111-207). The reference links an SSH library into the JVM; here
we drive the `ssh`/`scp` binaries with a ControlMaster multiplexed
connection per node, which gives the same persistent-session semantics
without bundling a crypto stack.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import tempfile
import threading
from typing import Optional

from .. import tracing
from .core import (Action, Remote, RemoteError, Result, Session,
                   TransportError, wrap_sudo)


_SSH_FAILURE_MARKERS = (
    "ssh:", "connection closed", "connection refused",
    "connection reset", "connection timed out", "broken pipe",
    "lost connection", "kex_exchange", "permission denied",
    "host key verification", "no route to host", "operation timed out",
    "mux_client", "control socket")


def _looks_like_ssh_failure(stderr: str) -> bool:
    s = (stderr or "").lower()
    return any(m in s for m in _SSH_FAILURE_MARKERS)


class SshSession(Session):
    def __init__(self, spec: dict, concurrency_limit: int = 6):
        self.spec = spec
        self.host = spec["host"]
        self.user = spec.get("username", "root")
        self.port = spec.get("port", 22)
        self.key = spec.get("private_key_path")
        self.strict = spec.get("strict_host_key_checking", False)
        self._sem = threading.Semaphore(concurrency_limit)
        self._ctl_dir = tempfile.mkdtemp(prefix="jt-ssh-")
        self._ctl_path = os.path.join(self._ctl_dir, "ctl")

    def _base_args(self) -> list:
        args = ["-o", "BatchMode=yes",
                "-o", f"ControlPath={self._ctl_path}",
                "-o", "ControlMaster=auto",
                "-o", "ControlPersist=60",
                "-p", str(self.port)]
        if not self.strict:
            args += ["-o", "StrictHostKeyChecking=no",
                     "-o", "UserKnownHostsFile=/dev/null",
                     "-o", "LogLevel=ERROR"]
        if self.key:
            args += ["-i", self.key]
        return args

    def _dest(self) -> str:
        return f"{self.user}@{self.host}"

    def execute(self, action: Action) -> Result:
        cmd = wrap_sudo(action)
        argv = ["ssh", *self._base_args(), self._dest(), cmd]
        try:
            with self._sem:
                proc = subprocess.run(
                    argv, input=action.stdin, capture_output=True,
                    text=True, timeout=action.timeout)
        except subprocess.TimeoutExpired as e:
            # NOT a TransportError: the command started and may still
            # be running remotely — retrying would double-execute it
            tracing.event("ssh-timeout", node=self.host,
                          timeout_s=action.timeout)
            raise RemoteError("ssh command timed out", cmd=cmd,
                              node=self.host) from e
        except OSError as e:  # spawn failure (e.g. no ssh binary)
            raise TransportError(f"ssh spawn failed: {e}", cmd=cmd,
                                 node=self.host) from e
        if proc.returncode == 255 and _looks_like_ssh_failure(
                proc.stderr):
            # 255 with a client-side error message is ssh's own failure
            # (connect/auth/channel): retryable. A remote command that
            # itself exits 255 without such a message passes through as
            # a Result, preserving exec_result's no-raise contract.
            tracing.event("ssh-transport-failed", node=self.host,
                          stderr=(proc.stderr or "")[:160])
            raise TransportError("ssh transport failed", exit=255,
                                 out=proc.stdout, err=proc.stderr,
                                 cmd=cmd, node=self.host)
        return Result(exit=proc.returncode, out=proc.stdout,
                      err=proc.stderr, cmd=cmd)

    def upload(self, local_paths, remote_path) -> None:
        if isinstance(local_paths, (str, os.PathLike)):
            local_paths = [local_paths]
        argv = self._scp_args(local_paths, f"{self._dest()}:{remote_path}")
        self._run_scp(argv)

    def download(self, remote_paths, local_path) -> None:
        if isinstance(remote_paths, (str, os.PathLike)):
            remote_paths = [remote_paths]
        srcs = [f"{self._dest()}:{p}" for p in remote_paths]
        argv = self._scp_args(srcs, str(local_path))
        self._run_scp(argv)

    def _scp_args(self, srcs, dst) -> list:
        args = ["scp", "-P", str(self.port),
                "-o", "BatchMode=yes",
                "-o", f"ControlPath={self._ctl_path}",
                "-o", "ControlMaster=auto",
                "-o", "ControlPersist=60"]
        if not self.strict:
            args += ["-o", "StrictHostKeyChecking=no",
                     "-o", "UserKnownHostsFile=/dev/null",
                     "-o", "LogLevel=ERROR"]
        if self.key:
            args += ["-i", self.key]
        return args + [*map(str, srcs), dst]

    def _run_scp(self, argv, timeout: float = 600.0) -> None:
        try:
            with self._sem:
                proc = subprocess.run(argv, capture_output=True,
                                      text=True, timeout=timeout)
        except subprocess.TimeoutExpired as e:
            raise RemoteError("scp timed out", cmd=" ".join(argv),
                              node=self.host) from e
        except OSError as e:
            raise TransportError(f"scp spawn failed: {e}",
                                 cmd=" ".join(argv),
                                 node=self.host) from e
        # Only exit 255 is the ssh client's own failure; marker
        # matching on other exits would misread remote-file errors
        # ("scp: /x: Permission denied", exit 1) as transport trouble
        # and pointlessly retry-cycle the shared ControlMaster.
        if proc.returncode == 255:
            raise TransportError("scp transport failed",
                                 exit=proc.returncode, out=proc.stdout,
                                 err=proc.stderr, cmd=" ".join(argv),
                                 node=self.host)
        if proc.returncode != 0:
            raise RemoteError("scp failed", exit=proc.returncode,
                              out=proc.stdout, err=proc.stderr,
                              cmd=" ".join(argv), node=self.host)

    def disconnect(self) -> None:
        try:
            subprocess.run(["ssh", "-o", f"ControlPath={self._ctl_path}",
                            "-O", "exit", self._dest()],
                           capture_output=True, timeout=10)
        except Exception:  # noqa: BLE001
            pass
        shutil.rmtree(self._ctl_dir, ignore_errors=True)


class SshRemote(Remote):
    def __init__(self, concurrency_limit: int = 6):
        self.concurrency_limit = concurrency_limit

    def connect(self, conn_spec: dict) -> SshSession:
        return SshSession(conn_spec, self.concurrency_limit)
