"""Node control DSL: ambient per-thread sessions + parallel node maps.

Where the reference uses Clojure dynamic vars (*session*, *host*, ...)
rebound around node operations (jepsen/src/jepsen/control.clj:43-57,
130-150, on-nodes), we use contextvars carried into worker threads.

Usage:

    with control.with_session(test, node):
        control.exec_("echo", "hi")

    control.on_nodes(test, lambda test, node: control.exec_("date"))
"""

from __future__ import annotations

import contextvars
import logging
from concurrent.futures import ThreadPoolExecutor
from contextlib import contextmanager
from typing import Any, Callable

from .core import (Action, Remote, RemoteError, Result, Session, escape,
                   join_cmd, throw_on_nonzero_exit, traced_execute,
                   traced_transfer, wrap_sudo)
from .dummy import DummyRemote, dummy

logger = logging.getLogger(__name__)

_session: contextvars.ContextVar = contextvars.ContextVar(
    "control_session", default=None)
_node: contextvars.ContextVar = contextvars.ContextVar(
    "control_node", default=None)
_sudo: contextvars.ContextVar = contextvars.ContextVar(
    "control_sudo", default=None)
_dir: contextvars.ContextVar = contextvars.ContextVar(
    "control_dir", default=None)


def conn_spec(test: dict, node) -> dict:
    """SSH connection options for a node (control.clj session opts)."""
    ssh = dict(test.get("ssh") or {})
    return {
        "host": node,
        "username": ssh.get("username", "root"),
        "password": ssh.get("password"),
        "port": ssh.get("port", 22),
        "private_key_path": ssh.get("private_key_path"),
        "strict_host_key_checking": ssh.get("strict_host_key_checking", False),
        "sudo_password": ssh.get("sudo_password"),
    }


def remote_for(test: dict, guarded: bool = True) -> Remote:
    r = test.get("remote")
    if r is None:
        r = dummy if (test.get("ssh") or {}).get("dummy") else _default_ssh()
    hr = test.get("health")
    if hr is not None and guarded:
        # per-node circuit breakers (control/health.py): commands to a
        # quarantined node fail fast instead of burning retry budgets,
        # and the run continues :degraded instead of aborting.
        # guarded=False bypasses the wrapper for OBSERVERS (the node
        # probe): background traffic must neither trip a breaker nor
        # reset its consecutive-failure count — only real work feeds
        # the circuit (the advisory-only contract, doc/observability.md)
        from .health import GuardedRemote
        r = GuardedRemote(r, hr)
    return r


def _default_ssh() -> Remote:
    # ssh wrapped for sudo-aware transfers, then auto-reconnect +
    # retry of transport failures, like the reference's default
    # scp-in-retry stack (control.clj with-remote + control/retry.clj
    # + control/scp.clj)
    from .retry import RetryingRemote
    from .scp import ScpRemote
    from .ssh import SshRemote
    return RetryingRemote(ScpRemote(SshRemote()))


def session(test: dict, node, guarded: bool = True) -> Session:
    return remote_for(test, guarded=guarded).connect(
        conn_spec(test, node))


def disconnect(sess: Session) -> None:
    sess.disconnect()


@contextmanager
def with_session(test: dict, node, sess: Session | None = None):
    """Binds the ambient session/node for the current thread."""
    own = sess is None
    if sess is None:
        sessions = test.get("sessions") or {}
        sess = sessions.get(node)
        own = sess is None
        if sess is None:
            sess = session(test, node)
    t_s = _session.set(sess)
    t_n = _node.set(node)
    try:
        yield sess
    finally:
        _session.reset(t_s)
        _node.reset(t_n)
        if own:
            sess.disconnect()


def current_session() -> Session:
    s = _session.get()
    if s is None:
        raise RuntimeError("no ambient control session; use with_session "
                           "or on_nodes")
    return s


def current_node():
    return _node.get()


@contextmanager
def su(user: str = "root"):
    """Evaluates body with all commands run as user (control.clj su)."""
    tok = _sudo.set(user)
    try:
        yield
    finally:
        _sudo.reset(tok)


@contextmanager
def cd(directory: str):
    cur = _dir.get()
    if cur and not str(directory).startswith("/"):
        # nested relative cd joins, like a shell: cd(a) inside cd(b)
        # means b/a, not a-relative-to-the-login-dir
        directory = f"{cur}/{directory}"
    tok = _dir.set(directory)
    try:
        yield
    finally:
        _dir.reset(tok)


def exec_(*args, stdin: str | None = None, check: bool = True,
          timeout: float = 600.0) -> str:
    """Runs a shell command on the current node, returning trimmed stdout
    (control.clj exec)."""
    cmd = join_cmd(*args)
    action = Action(cmd=cmd, stdin=stdin, sudo=_sudo.get(), dir=_dir.get(),
                    timeout=timeout)
    res = traced_execute(current_session(), action, node=current_node())
    if check:
        throw_on_nonzero_exit(current_node(), res)
    return res.out.strip()


def exec_result(*args, stdin: str | None = None,
                timeout: float = 600.0) -> Result:
    """Like exec_ but returns the full Result without raising."""
    cmd = join_cmd(*args)
    action = Action(cmd=cmd, stdin=stdin, sudo=_sudo.get(), dir=_dir.get(),
                    timeout=timeout)
    return traced_execute(current_session(), action, node=current_node())


def upload(local_paths, remote_path) -> None:
    traced_transfer(current_session(), "upload", local_paths,
                    remote_path, node=current_node())


def download(remote_paths, local_path) -> None:
    traced_transfer(current_session(), "download", remote_paths,
                    local_path, node=current_node())


def on_nodes(test: dict, f: Callable[[dict, Any], Any],
             nodes=None) -> dict:
    """Runs (f test node) in parallel on each node with an ambient session
    bound; returns {node: result} (control.clj on-nodes)."""
    if nodes is None:
        nodes = test.get("nodes") or []
    nodes = list(nodes)
    if not nodes:
        return {}

    from .. import tracing

    # capture the calling thread's trace context so the pooled
    # per-node commands record under the op that issued them
    trace_parent = tracing.get().current()

    def run_one(node):
        ctx = contextvars.copy_context()

        def body():
            with tracing.get().attach(trace_parent):
                with with_session(test, node):
                    return f(test, node)

        return ctx.run(body)

    with ThreadPoolExecutor(max_workers=len(nodes)) as pool:
        results = list(pool.map(run_one, nodes))
    return dict(zip(nodes, results))


def open_sessions(test: dict) -> dict:
    """Opens one session per node in parallel; returns test with
    :sessions {node: session} (core.clj with-sessions, 266-286).
    With quarantine enabled (test["health"]), a node that is dead at
    open time gets a lazy placeholder session instead of aborting the
    run: its commands retry the connect (feeding the circuit breaker)
    and fail fast once quarantined (control/health.py)."""
    from .. import util as _util

    nodes = list(test.get("nodes") or [])
    hr = test.get("health")

    def open_one(n):
        try:
            return session(test, n)
        except RemoteError:
            if hr is None:
                raise
            from .health import LazyConnectSession

            logger.warning(
                "couldn't open a session to %s; deferring (quarantine "
                "active — the run continues :degraded)", n)
            return LazyConnectSession(remote_for(test),
                                      conn_spec(test, n))

    sessions = _util.real_pmap(open_one, nodes)
    test = dict(test)
    test["sessions"] = dict(zip(nodes, sessions))
    return test


def close_sessions(test: dict) -> None:
    for sess in (test.get("sessions") or {}).values():
        try:
            sess.disconnect()
        except Exception:  # noqa: BLE001
            logger.exception("error disconnecting session")
