"""Stateful wrappers for automatically reconnecting network clients.

Capability reference: jepsen/src/jepsen/reconnect.clj:17-94 — a wrapper
holds an open/close function pair plus the current connection;
`with_conn` hands the live connection to a body and, when the body
throws, closes and reopens the connection before re-raising so the next
caller gets a fresh one. Open/close/reopen serialize on a lock while
many threads may use the current connection concurrently.
"""

from __future__ import annotations

import logging
import threading
from contextlib import contextmanager
from typing import Any, Callable, Optional

logger = logging.getLogger(__name__)


class Wrapper:
    """See module docstring. Options mirror reconnect.clj `wrapper`:
    open() -> conn, close(conn), name (for logs), log ('minimal',
    True, or False)."""

    def __init__(self, open: Callable[[], Any],
                 close: Callable[[Any], None],
                 name: Any = None, log: Any = "minimal"):
        self._open = open
        self._close = close
        self.name = name
        self.log = log
        self._lock = threading.RLock()
        self._conn: Optional[Any] = None

    def conn(self):
        """The active connection, if one exists."""
        return self._conn

    def open(self) -> "Wrapper":
        """Opens a connection; no-op if already open."""
        with self._lock:
            if self._conn is None:
                c = self._open()
                if c is None:
                    raise RuntimeError(
                        f"reconnect wrapper {self.name!r}'s open "
                        "returned None instead of a connection")
                self._conn = c
        return self

    def close(self) -> "Wrapper":
        with self._lock:
            if self._conn is not None:
                try:
                    self._close(self._conn)
                finally:
                    self._conn = None
        return self

    def reopen(self) -> "Wrapper":
        """Closes (ignoring errors) and opens a fresh connection."""
        with self._lock:
            if self._conn is not None:
                try:
                    self._close(self._conn)
                except Exception:  # noqa: BLE001 — old conn may be dead
                    pass
                self._conn = None
            return self.open()

    def _handle_failure(self, conn, exc) -> None:
        """After a body failure: if the failing connection is still
        current, replace it (another thread may have already done
        so)."""
        if self.log == "minimal":
            logger.info("reconnect %r: error %r; reopening",
                        self.name, exc)
        elif self.log:
            logger.exception("reconnect %r: error; reopening",
                             self.name)
        # reconnects are span events on the op whose failure forced
        # them (or context-free during setup) — an op that limped
        # through a connection cycle carries the evidence
        from . import tracing

        tracing.event("reconnect", wrapper=str(self.name),
                      error=repr(exc)[:160])
        with self._lock:
            if self._conn is conn:
                try:
                    self.reopen()
                except Exception:  # noqa: BLE001 — reopen may also fail;
                    pass           # the next with_conn will retry it

    @contextmanager
    def with_conn(self, cycle_on: type | tuple = Exception):
        """Yields the current connection (opening if needed); when the
        body raises an exception matching cycle_on, cycles the
        connection before re-raising (other exceptions pass through
        with the connection intact)."""
        with self._lock:
            if self._conn is None:
                self.open()
            c = self._conn
        try:
            yield c
        except Exception as e:
            if isinstance(e, cycle_on):
                self._handle_failure(c, e)
            raise

    def call(self, f: Callable[[Any], Any]):
        with self.with_conn() as c:
            return f(c)
