"""Reusable DB wrappers (the reference keeps these in db.clj itself)."""
