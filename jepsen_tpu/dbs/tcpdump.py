"""A DB wrapper that captures packets from setup to teardown.

Capability reference: jepsen/src/jepsen/db.clj tcpdump (88-156): runs a
tcpdump daemon per node, filters by ports/clients/custom expression,
and exposes the capture + log via log_files.
"""

from __future__ import annotations

import logging
import time

from .. import control, net, util
from ..control import util as cu
from ..control.core import RemoteError
from ..db import DB

logger = logging.getLogger(__name__)

DIR = "/tmp/jepsen/tcpdump"
LOG_FILE = f"{DIR}/log"
CAP_FILE = f"{DIR}/tcpdump"
PID_FILE = f"{DIR}/pid"


class Tcpdump(DB):
    """Options: ports (list), clients_only (bool), filter (str)."""

    def __init__(self, ports=(), clients_only: bool = False,
                 filter: str | None = None):
        self.ports = list(ports)
        self.clients_only = clients_only
        self.filter = filter

    def _filter_str(self) -> str:
        filters = []
        if self.ports:
            filters.append(" or ".join(f"port {p}" for p in self.ports))
        if self.clients_only:
            filters.append(f"host {net.control_ip()}")
        if self.filter:
            filters.append(self.filter)
        return " and ".join(filters)

    def setup(self, test, node):
        with control.su():
            control.exec_("mkdir", "-p", DIR)
            cu.start_daemon(
                {"logfile": LOG_FILE, "pidfile": PID_FILE, "chdir": DIR},
                "/usr/bin/tcpdump", "-w", CAP_FILE, "-s", 65535,
                "-B", 16384, "-U", self._filter_str())

    def teardown(self, test, node):
        with control.su():
            try:
                pid = control.exec_("cat", PID_FILE)
            except RemoteError:
                pid = None
            if pid:
                # SIGINT first so tcpdump flushes its capture
                util.meh(lambda: control.exec_("kill", "-s", "INT", pid))
                while True:
                    try:
                        control.exec_("ps", "-p", pid)
                    except RemoteError:
                        break
                    logger.info("Waiting for tcpdump %s to exit", pid)
                    time.sleep(0.05)
            cu.stop_daemon("tcpdump", PID_FILE)
            control.exec_("rm", "-rf", DIR)

    def log_files(self, test, node):
        return {LOG_FILE: "tcpdump.log", CAP_FILE: "tcpdump.pcap"}


def tcpdump(ports=(), clients_only: bool = False,
            filter: str | None = None) -> Tcpdump:
    return Tcpdump(ports, clients_only, filter)
