"""Helpers for mucking around with stored tests interactively.

Capability reference: jepsen/src/jepsen/repl.clj (latest-test) plus
the store/report access patterns suites use from a REPL
(jepsen/src/jepsen/store.clj:108-134 load, web.clj fast reads).

    >>> from jepsen_tpu import repl
    >>> t = repl.latest_test()
    >>> repl.summary(t)
    >>> [op for op in t["history"] if op.type == "fail"][:3]
"""

from __future__ import annotations

from . import store


def latest_test(name: str | None = None) -> dict | None:
    """The most recently run test, with history and results loaded
    (repl.clj latest-test). With a name, the latest run of that test
    only."""
    runs = list(store.tests(name))
    if not runs:
        return None
    latest = max(runs, key=lambda d: d.name)
    return store.load(latest)


def summary(test: dict | None) -> dict:
    """A terse, print-friendly view of a loaded test."""
    if test is None:
        return {}
    hist = test.get("history") or []
    results = test.get("results") or {}
    by_type: dict = {}
    for op in hist:
        by_type[op.type] = by_type.get(op.type, 0) + 1
    return {
        "name": test.get("name"),
        "start_time": str(test.get("start_time", "")),
        "valid?": results.get("valid?"),
        "ops": len(hist),
        "by-type": by_type,
        "checkers": sorted(k for k in results
                           if not k.endswith("?")),
    }
