"""Coverage atlas: cross-run fault × workload × anomaly observability.

The observability PRs made a single run deeply inspectable; this module
answers the fleet-level question the campaign runner (ROADMAP item 5)
needs first: which fault × workload × anomaly cells has this framework
EVER exercised, and where are the blind spots? AccelSync (PAPERS.md,
arXiv:2605.07881) frames this as coverage *verification* — a test
framework that cannot report its own coverage cannot claim it; the
per-key/per-segment decomposition (arXiv:1504.00204) is what makes
per-cell attribution well defined in the first place.

Three layers:

  *Taxonomy + per-run record.* Every nemesis declares structured fault
  kinds for the op fs it speaks (`Nemesis.fault_kinds`, threaded through
  nemesis/core.py, combined.py, membership.py, time.py; chaos.py's
  harness faults report as `harness-*` kinds) and every checker verdict
  carries `anomaly-classes` — one outcome per class it CHECKS, with
  explicit negative results ("fault fired, anomaly class checked, none
  found" is a `clean` cell, not a missing one). The run pipeline writes
  a schema-validated `coverage.json` per run: fault activations with
  time windows, the workload signature, generator-schedule features,
  and anomaly outcomes with op-index provenance (joinable to the per-op
  trace like every other anomaly artifact).

  *Cross-run atlas.* `store/coverage_atlas.jsonl` accumulates one line
  per analyzed run (append order; torn tail tolerated like every jsonl
  artifact here). Merge semantics: lines are keyed by run id and the
  LAST line per run wins, so `analyze --resume` re-analysis replaces a
  run's contribution instead of double-counting it, and concurrent runs
  append distinct ids. `aggregate()` folds the deduplicated entries
  into per-cell stats: run counts, witnessed/clean/unknown splits,
  first/last-seen timestamps, witnessing run ids.

  *Surfacing.* `python -m jepsen_tpu coverage` (matrix table + gap
  report + `--suggest` ranked gap-filling configs — the campaign
  runner's input hook), web.py's `/coverage/` heatmap deep-linking
  cells to runs, and Prometheus samples on the existing `/metrics`
  endpoint.
"""

from __future__ import annotations

import hashlib
import json
import logging
import threading
import time
from pathlib import Path
from typing import Any, Iterable

logger = logging.getLogger(__name__)

SCHEMA = 1
RECORD_FILE = "coverage.json"
ATLAS_FILE = "coverage_atlas.jsonl"

# Outcomes an anomaly class can take in one run's verdict.
OUTCOMES = ("witnessed", "clean", "unknown")

# The canonical fault-kind taxonomy. Nemeses may declare kinds beyond
# this list (they still aggregate); these are the axes the gap report
# reasons about. "none" is the implicit baseline cell for runs without
# any fault activation.
FAULT_KINDS = (
    "partition", "packet", "db-kill", "db-pause", "process-pause",
    "clock-bump", "clock-strobe", "clock-reset", "file-bitflip",
    "file-truncate", "file-lost-writes", "membership",
)

# Offline fallback: op f -> (kind, phase) for histories whose live
# activations were lost (run predates coverage, crashed before the
# record landed). Bare start/stop is the tutorial-grade partitioner
# cycle (nemesis.start_stop_cycle) — the one ambiguity, documented in
# doc/observability.md; live recording via Validate resolves it
# precisely from the nemesis's own declaration.
F_KINDS = {
    "start": ("partition", "begin"),
    "stop": ("partition", "end"),
    "start-partition": ("partition", "begin"),
    "stop-partition": ("partition", "end"),
    "start-packet": ("packet", "begin"),
    "stop-packet": ("packet", "end"),
    "kill": ("db-kill", "begin"),
    "pause": ("db-pause", "begin"),
    "resume": ("db-pause", "end"),
    "bitflip": ("file-bitflip", "pulse"),
    "truncate": ("file-truncate", "pulse"),
    "lose-unfsynced-writes": ("file-lost-writes", "pulse"),
    "bump": ("clock-bump", "pulse"),
    "bump-clock": ("clock-bump", "pulse"),
    "strobe": ("clock-strobe", "pulse"),
    "strobe-clock": ("clock-strobe", "pulse"),
    "reset": ("clock-reset", "pulse"),
    "reset-clock": ("clock-reset", "pulse"),
}


def default_kinds(fs: Iterable) -> dict:
    """{f: (kind, phase)} for the fs a nemesis declares, from the
    fallback registry — the default Nemesis.fault_kinds() body, so any
    custom nemesis speaking the standard fs is covered automatically."""
    out = {}
    for f in fs:
        k = F_KINDS.get(f)
        if k is not None:
            out[f] = k
    return out


# ---------------------------------------------------------------------------
# Run-scoped activation recorder
# ---------------------------------------------------------------------------

class Recorder:
    """Collects fault activations for the run in progress. Thread-safe;
    reset by core.run alongside telemetry (same per-run scoping)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._acts: list[dict] = []
        self._harness: dict[str, int] = {}

    def record(self, kind: str, f, phase: str, t0: int,
               t1: int | None = None) -> None:
        if kind is None:
            return
        rec = {"kind": str(kind), "f": f, "phase": phase,
               "t0": int(t0)}
        if t1 is not None:
            rec["t1"] = int(t1)
        with self._lock:
            self._acts.append(rec)

    def record_harness(self, kind: str, n: int = 1) -> None:
        """Harness chaos faults (jepsen_tpu.chaos) have no op window —
        they count per injection under a `harness-` kind."""
        name = f"harness-{kind}"
        with self._lock:
            self._harness[name] = self._harness.get(name, 0) + int(n)

    def activations(self) -> list[dict]:
        with self._lock:
            return list(self._acts)

    def harness_counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self._harness)

    def reset(self) -> None:
        with self._lock:
            self._acts = []
            self._harness = {}


_global = Recorder()


def get() -> Recorder:
    return _global


def record_fault(kind, f, phase, t0, t1=None) -> None:
    _global.record(kind, f, phase, t0, t1)


def record_harness(kind, n: int = 1) -> None:
    _global.record_harness(kind, n)


def reset() -> None:
    _global.reset()


# ---------------------------------------------------------------------------
# Fault folding: activations -> per-kind counts + windows
# ---------------------------------------------------------------------------

def fold_faults(activations: Iterable[dict],
                harness: dict | None = None) -> list[dict]:
    """[{kind, count, windows}] from raw activations, kind-sorted.
    Windows pair begin/end activations per kind ([t_begin, t_end]);
    a pulse is a degenerate window; a begin never closed stays open
    ([t, None] — the fault outlived the op log)."""
    by_kind: dict[str, dict] = {}
    for a in sorted(activations, key=lambda a: a.get("t0", 0)):
        kind = a.get("kind")
        if not kind:
            continue
        st = by_kind.setdefault(kind, {"count": 0, "windows": [],
                                       "open": None})
        phase = a.get("phase", "pulse")
        t0 = a.get("t0", 0)
        t1 = a.get("t1", t0)
        if phase == "begin":
            st["count"] += 1
            if st["open"] is None:
                st["open"] = t0
        elif phase == "end":
            if st["open"] is not None:
                st["windows"].append([st["open"], t1])
                st["open"] = None
        else:  # pulse
            st["count"] += 1
            st["windows"].append([t0, t1])
    out = []
    for kind in sorted(by_kind):
        st = by_kind[kind]
        if st["open"] is not None:
            st["windows"].append([st["open"], None])
        out.append({"kind": kind, "count": st["count"],
                    "windows": st["windows"]})
    for kind in sorted(harness or {}):
        out.append({"kind": kind, "count": int(harness[kind]),
                    "windows": []})
    return out


def faults_from_history(hist) -> list[dict]:
    """Offline fallback: fault activations derived from a history's
    nemesis ops via the F_KINDS registry (`:info` ops on non-integer
    processes). Less precise than live recording — Validate knows the
    nemesis's own kind declaration — but good enough to re-cover a run
    whose live record was lost.

    The interpreter journals each nemesis op TWICE (the dispatch
    invocation and its completion, both type info on the same process
    with the same f): the toggle below records only the first of each
    pair, so counts match the live recorder's one-per-activation. An
    unmatched invocation (the nemesis died mid-fault) still counts."""
    acts = []
    open_pairs: set = set()
    for op in hist or []:
        proc = getattr(op, "process", None)
        if isinstance(proc, int):
            continue
        f = getattr(op, "f", None)
        got = F_KINDS.get(f)
        if got is None:
            continue
        key = (proc, f)
        if key in open_pairs:
            open_pairs.discard(key)  # the pair's completion
            continue
        open_pairs.add(key)
        kind, phase = got
        acts.append({"kind": kind, "f": f, "phase": phase,
                     "t0": getattr(op, "time", 0) or 0})
    return fold_faults(acts)


# ---------------------------------------------------------------------------
# Anomaly outcomes: results -> per-class outcomes with provenance
# ---------------------------------------------------------------------------

def _merge_outcome(a: str, b: str) -> str:
    """witnessed dominates, then unknown, else clean — the merge_valid
    analog for a class reported by several checkers in one run."""
    if "witnessed" in (a, b):
        return "witnessed"
    if "unknown" in (a, b):
        return "unknown"
    return "clean"


def _class_indices(res: dict, cls: str) -> list[int]:
    """Best-effort op-index provenance for one witnessed class out of a
    checker result: elle anomalies[cls] records, the wgl
    counterexample's op-indices, or set-full's lost-op-indices."""
    idxs: set[int] = set()
    recs = (res.get("anomalies") or {}).get(cls)
    for rec in recs or []:
        if isinstance(rec, dict):
            idxs.update(int(i) for i in rec.get("op-indices") or [])
    if not idxs and res.get("op-indices"):
        idxs.update(int(i) for i in res["op-indices"])
    lost = res.get("lost-op-indices")
    if not idxs and isinstance(lost, dict):
        idxs.update(int(i) for v in lost.values() for i in v)
    return sorted(idxs)[:64]


def anomaly_outcomes(results, checker: str = "",
                     depth: int = 0) -> list[dict]:
    """[{class, checker, outcome, op-indices?}] for every anomaly class
    a results map reports having checked (the `anomaly-classes` entries
    the checkers attach — including explicit negatives), one entry per
    class with outcomes merged across checkers of the same class."""
    found: dict[str, dict] = {}

    def walk(res, path, depth):
        if not isinstance(res, dict) or depth > 5:
            return
        classes = res.get("anomaly-classes")
        if isinstance(classes, dict):
            for cls, outcome in classes.items():
                if outcome not in OUTCOMES:
                    outcome = "unknown"
                cur = found.get(cls)
                if cur is None:
                    cur = found[cls] = {"class": cls, "checker": path,
                                        "outcome": outcome}
                else:
                    cur["outcome"] = _merge_outcome(cur["outcome"],
                                                    outcome)
                if outcome == "witnessed":
                    cur["checker"] = path
                    idxs = _class_indices(res, cls)
                    if idxs:
                        cur["op-indices"] = idxs
                    # where in the history the anomaly localized
                    # (the wgl/elle search explorer's witness
                    # percentile): the earliest-localization signal
                    # `coverage --suggest` and ROADMAP-3's early-exit
                    # rank configs by
                    s = res.get("search")
                    frac = (s or {}).get("witness-position") \
                        if isinstance(s, dict) else None
                    if isinstance(frac, (int, float)):
                        prev = cur.get("witness-frac")
                        cur["witness-frac"] = (
                            float(frac) if prev is None
                            else min(prev, float(frac)))
        for k, v in res.items():
            if isinstance(v, dict) and k != "anomalies":
                walk(v, f"{path}/{k}" if path else str(k), depth + 1)

    walk(results if isinstance(results, dict) else {}, checker, depth)
    # the online watchdog rides next to the checker verdicts and is a
    # checked class of its own (its hits are mid-run witnesses)
    wd = (results or {}).get("watchdog") if isinstance(results, dict) \
        else None
    if isinstance(wd, dict) and "count" in wd:
        found["watchdog"] = {
            "class": "watchdog", "checker": "watchdog",
            "outcome": "witnessed" if wd.get("count") else "clean"}
    return [found[c] for c in sorted(found)]


def outcome(witnessed: bool, valid=None) -> str:
    """The per-class outcome for a checker that just ran: `witnessed`
    when it found instances of the class, `unknown` when the check
    itself was indeterminate, else the explicit negative `clean`."""
    if witnessed:
        return "witnessed"
    if valid == "unknown":
        return "unknown"
    return "clean"


# ---------------------------------------------------------------------------
# Per-run record
# ---------------------------------------------------------------------------

def _run_id(test: dict) -> str:
    d = test.get("store_dir")
    if d:
        p = Path(d)
        return f"{p.parent.name}/{p.name}"
    return str(test.get("name") or "unnamed")


def _workload_name(test: dict) -> str:
    spec = test.get("spec")
    if isinstance(spec, dict) and spec.get("workload"):
        return str(spec["workload"])
    return str(test.get("workload") or test.get("name") or "unknown")


def _schedule_features(test: dict, hist) -> dict:
    """Generator-schedule features worth comparing across runs: op and
    nemesis-op volume, concurrency, and the coarse knobs the spec
    carries (rate/time-limit/ops)."""
    n_client = n_nem = 0
    t_last = 0
    open_nem: set = set()  # invoke/completion pairs count once
    for op in hist or []:
        proc = getattr(op, "process", None)
        if not isinstance(proc, int):
            key = (proc, getattr(op, "f", None))
            if key in open_nem:
                open_nem.discard(key)
            else:
                open_nem.add(key)
                n_nem += 1
        elif getattr(op, "type", None) == "invoke":
            n_client += 1
        t = getattr(op, "time", None)
        if isinstance(t, int):
            t_last = max(t_last, t)
    feats = {"client-ops": n_client, "nemesis-ops": n_nem,
             "duration-ns": t_last,
             "concurrency": test.get("concurrency")}
    spec_opts = (test.get("spec") or {}).get("opts") \
        if isinstance(test.get("spec"), dict) else None
    for k in ("rate", "time_limit", "ops", "nemesis"):
        v = (spec_opts or {}).get(k, test.get(k))
        if isinstance(v, (int, float, str)):
            feats[k] = v
    return feats


def build_record(test: dict, recorder: Recorder | None = None) -> dict:
    """The per-run coverage record: fault activations (live recorder
    first, history fallback), workload signature, and anomaly outcomes
    from the analyzed results."""
    rec = recorder if recorder is not None else _global
    hist = test.get("history")
    faults = fold_faults(rec.activations(), rec.harness_counts())
    if not faults:
        faults = faults_from_history(hist)
    results = test.get("results") if isinstance(test.get("results"),
                                                dict) else {}
    return {
        "schema": SCHEMA,
        "run": _run_id(test),
        "ts": round(time.time(), 3),
        "workload": _workload_name(test),
        "signature": _schedule_features(test, hist),
        "faults": faults,
        "anomalies": anomaly_outcomes(results),
        "valid": results.get("valid?", "unknown"),
    }


def validate_record(rec) -> int:
    """Schema check for a coverage.json document (the
    ledger.validate_entries analog, run in tier-1): required keys,
    fault entries with non-negative counts and 2-element windows,
    anomaly entries with known outcomes. Returns fault + anomaly entry
    count; raises ValueError on the first violation."""
    if not isinstance(rec, dict):
        raise ValueError("coverage record must be a dict")
    for key in ("schema", "run", "ts", "workload", "faults",
                "anomalies", "valid"):
        if key not in rec:
            raise ValueError(f"coverage record missing {key!r}")
    if rec["schema"] != SCHEMA:
        raise ValueError(f"unknown schema {rec['schema']!r}")
    if not isinstance(rec["run"], str) or not rec["run"]:
        raise ValueError(f"bad run id {rec['run']!r}")
    if not isinstance(rec["ts"], (int, float)) or rec["ts"] < 0:
        raise ValueError(f"bad ts {rec['ts']!r}")
    n = 0
    if not isinstance(rec["faults"], list):
        raise ValueError("faults must be a list")
    for i, f in enumerate(rec["faults"]):
        if not isinstance(f, dict) or not f.get("kind"):
            raise ValueError(f"fault {i}: missing kind: {f!r}")
        if not isinstance(f.get("count"), int) or f["count"] < 0:
            raise ValueError(f"fault {i}: bad count: {f!r}")
        for w in f.get("windows", []):
            if (not isinstance(w, list) or len(w) != 2
                    or not isinstance(w[0], int)
                    or not (w[1] is None or isinstance(w[1], int))):
                raise ValueError(f"fault {i}: bad window {w!r}")
        n += 1
    if not isinstance(rec["anomalies"], list):
        raise ValueError("anomalies must be a list")
    for i, a in enumerate(rec["anomalies"]):
        if not isinstance(a, dict) or not a.get("class"):
            raise ValueError(f"anomaly {i}: missing class: {a!r}")
        if a.get("outcome") not in OUTCOMES:
            raise ValueError(f"anomaly {i}: bad outcome: {a!r}")
        idxs = a.get("op-indices")
        if idxs is not None and not (
                isinstance(idxs, list)
                and all(isinstance(x, int) for x in idxs)):
            raise ValueError(f"anomaly {i}: bad op-indices: {a!r}")
        frac = a.get("witness-frac")
        if frac is not None and not (
                isinstance(frac, (int, float)) and 0 <= frac <= 1):
            raise ValueError(f"anomaly {i}: bad witness-frac: {a!r}")
        n += 1
    return n


def write_record(test: dict, recorder: Recorder | None = None
                 ) -> dict | None:
    """Builds, validates, and writes <run>/coverage.json; returns the
    record (None without a store dir)."""
    d = test.get("store_dir")
    if not d:
        return None
    rec = build_record(test, recorder)
    validate_record(rec)
    with open(Path(d) / RECORD_FILE, "w") as f:
        json.dump(rec, f, indent=1)
    return rec


def load_record(d) -> dict | None:
    p = Path(d) / RECORD_FILE
    if not p.exists():
        return None
    try:
        with open(p) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# ---------------------------------------------------------------------------
# Atlas: the cross-run journal + aggregation
# ---------------------------------------------------------------------------

def _digest(entry: dict) -> str:
    """Content fingerprint of an atlas entry's cell contribution —
    identical re-analysis appends nothing."""
    view = {k: entry[k] for k in ("run", "workload", "faults",
                                  "anomalies", "valid")
            if k in entry}
    return hashlib.sha1(
        json.dumps(view, sort_keys=True).encode()).hexdigest()[:16]


def atlas_entry(rec: dict) -> dict:
    """One atlas line from a per-run record: the compact per-run cell
    contribution (fault kinds + anomaly outcomes; windows dropped)."""
    entry = {
        "run": rec["run"],
        "ts": rec["ts"],
        "workload": rec["workload"],
        "faults": {f["kind"]: f["count"] for f in rec["faults"]},
        "anomalies": {a["class"]: a["outcome"]
                      for a in rec["anomalies"]},
        "valid": rec.get("valid"),
    }
    fracs = {a["class"]: a["witness-frac"] for a in rec["anomalies"]
             if isinstance(a.get("witness-frac"), (int, float))}
    if fracs:
        # witness-position percentiles per witnessed class (not part
        # of the digest view: they're a deterministic function of the
        # same results the digested outcomes come from)
        entry["witness-frac"] = fracs
    entry["digest"] = _digest(entry)
    return entry


def read_atlas(path) -> list[dict]:
    """Atlas entries in append order; torn trailing line dropped."""
    p = Path(path)
    if not p.exists():
        return []
    out = []
    with open(p) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except ValueError:
                break
            if isinstance(e, dict) and e.get("run"):
                out.append(e)
    return out


def dedup_entries(entries: Iterable[dict]) -> dict[str, dict]:
    """{run id: newest entry} — the atlas merge rule. Appending a
    re-analysis of the same run REPLACES its contribution; cell counts
    cannot double."""
    out: dict[str, dict] = {}
    for e in entries:
        out[str(e.get("run"))] = e
    return out


def _append_if_new(path: Path, have: dict, entry: dict) -> bool:
    """The one merge rule: append `entry` unless the newest entry for
    its run already carries the same digest (then it IS the atlas
    state and re-appending would only bloat the journal). `have` is
    the preloaded newest-per-run index, updated in place."""
    latest = have.get(entry["run"])
    if latest is not None and latest.get("digest") == entry["digest"]:
        return False
    path.parent.mkdir(parents=True, exist_ok=True)
    # one os.write on an O_APPEND fd (ledger.atomic_append_line):
    # concurrent runs appending to the shared atlas can interleave
    # LINES but never bytes — newest-line-wins stays sound because no
    # reader can ever see a spliced line
    from . import ledger as jledger

    jledger.atomic_append_line(path, json.dumps(entry))
    have[entry["run"]] = entry
    return True


def append_run(base, rec: dict) -> dict | None:
    """Appends a run's atlas entry under store base `base`, skipping
    the write when the newest entry for that run already carries the
    same digest (analyze --resume over an unchanged run is a no-op).
    Returns the entry (written or matched)."""
    path = Path(base) / ATLAS_FILE
    entry = atlas_entry(rec)
    have = dedup_entries(read_atlas(path))
    if not _append_if_new(path, have, entry):
        return have[entry["run"]]
    return entry


def sync_store(base) -> int:
    """Folds every stored run's coverage.json into the atlas (runs
    whose live append was missed — crashed before it landed, analyzed
    elsewhere, copied in). Returns the number of entries appended."""
    from . import store as jstore

    base = Path(base)
    n = 0
    path = base / ATLAS_FILE
    have = dedup_entries(read_atlas(path))
    for td in jstore.tests(base=base):
        rec = load_record(td)
        if rec is None:
            continue
        try:
            validate_record(rec)
        except ValueError as e:
            logger.warning("skipping invalid coverage record %s: %s",
                           td, e)
            continue
        if _append_if_new(path, have, atlas_entry(rec)):
            n += 1
    return n


def validate_atlas(entries) -> int:
    """Schema check for atlas entries (tier-1): run/ts/workload/
    faults/anomalies/digest shapes. Returns the entry count."""
    n = 0
    for i, e in enumerate(entries):
        if not isinstance(e, dict):
            raise ValueError(f"entry {i}: not a dict")
        for key in ("run", "ts", "workload", "faults", "anomalies",
                    "digest"):
            if key not in e:
                raise ValueError(f"entry {i} missing {key!r}")
        if not isinstance(e["faults"], dict) or not all(
                isinstance(v, int) for v in e["faults"].values()):
            raise ValueError(f"entry {i}: bad faults {e['faults']!r}")
        if not isinstance(e["anomalies"], dict) or not all(
                v in OUTCOMES for v in e["anomalies"].values()):
            raise ValueError(
                f"entry {i}: bad anomalies {e['anomalies']!r}")
        wf = e.get("witness-frac")
        if wf is not None and (not isinstance(wf, dict) or not all(
                isinstance(v, (int, float)) and 0 <= v <= 1
                for v in wf.values())):
            raise ValueError(
                f"entry {i}: bad witness-frac {wf!r}")
        n += 1
    return n


def aggregate(entries: Iterable[dict]) -> dict[tuple, dict]:
    """{(fault, workload, anomaly): cell} over deduplicated atlas
    entries. A run with no fault activations contributes its anomaly
    outcomes under the baseline fault "none" — the healthy-path
    column. Cell: {runs, witnessed, clean, unknown, first-seen,
    last-seen, witnesses (run ids, capped)}."""
    cells: dict[tuple, dict] = {}
    for e in dedup_entries(entries).values():
        kinds = sorted(e.get("faults") or {}) or ["none"]
        wl = str(e.get("workload") or "unknown")
        ts = e.get("ts") or 0
        fracs = e.get("witness-frac") or {}
        for kind in kinds:
            for cls, out in sorted((e.get("anomalies") or {}).items()):
                key = (kind, wl, cls)
                c = cells.get(key)
                if c is None:
                    c = cells[key] = {
                        "runs": 0, "witnessed": 0, "clean": 0,
                        "unknown": 0, "first-seen": ts,
                        "last-seen": ts, "witnesses": [],
                        "earliest-witness-frac": None}
                c["runs"] += 1
                c[out if out in OUTCOMES else "unknown"] += 1
                c["first-seen"] = min(c["first-seen"], ts)
                c["last-seen"] = max(c["last-seen"], ts)
                if out == "witnessed" and len(c["witnesses"]) < 16:
                    c["witnesses"].append(str(e.get("run")))
                # how early the anomaly localizes in this cell — the
                # config-ranking signal for early-exit work
                frac = fracs.get(cls)
                if isinstance(frac, (int, float)):
                    prev = c["earliest-witness-frac"]
                    c["earliest-witness-frac"] = (
                        float(frac) if prev is None
                        else min(prev, float(frac)))
    return cells


# ---------------------------------------------------------------------------
# Matrix, gaps, suggestions
# ---------------------------------------------------------------------------

def _axes(cells: dict[tuple, dict],
          all_workloads: Iterable[str] | None = None,
          all_faults: Iterable[str] | None = None) -> tuple[list, list]:
    faults = sorted({k for k, _w, _a in cells}
                    | set(all_faults or FAULT_KINDS) | {"none"})
    wls = sorted({w for _k, w, _a in cells} | set(all_workloads or ()))
    return faults, wls


def cell_status(cells: dict[tuple, dict], fault: str,
                workload: str) -> str:
    """'gap' (never exercised), 'witnessed', 'clean', or 'unknown' for
    one fault × workload cell, folded over its anomaly classes."""
    status = "gap"
    for (k, w, _a), c in cells.items():
        if k != fault or w != workload:
            continue
        if c["witnessed"]:
            return "witnessed"
        if c["clean"]:
            status = "clean"
        elif status == "gap":
            status = "unknown"
    return status


_STATUS_CHAR = {"gap": "·", "clean": "o", "witnessed": "X",
                "unknown": "?"}


def matrix_text(cells: dict[tuple, dict],
                all_workloads: Iterable[str] | None = None) -> str:
    """The fault × workload matrix: one row per workload, one column
    per fault kind; X = anomaly witnessed, o = checked clean,
    ? = indeterminate only, · = never exercised."""
    faults, wls = _axes(cells, all_workloads)
    if not wls:
        return "(empty atlas — run some tests first)"
    wname = max(len(w) for w in wls + ["workload"])
    head = "workload".ljust(wname) + "  " + "  ".join(
        f"{i:>2d}" for i in range(len(faults)))
    lines = [head, "-" * len(head)]
    for w in wls:
        row = [f"{_STATUS_CHAR[cell_status(cells, k, w)]:>2s}"
               for k in faults]
        lines.append(w.ljust(wname) + "  " + "  ".join(row))
    lines.append("")
    for i, k in enumerate(faults):
        lines.append(f"  {i:>2d} = {k}")
    lines.append("")
    lines.append("  X witnessed   o checked clean   ? indeterminate   "
                 "· never exercised")
    return "\n".join(lines)


def gaps(cells: dict[tuple, dict],
         all_workloads: Iterable[str] | None = None,
         all_faults: Iterable[str] | None = None) -> list[tuple]:
    """Never-exercised (fault, workload) cells, deterministic order."""
    faults, wls = _axes(cells, all_workloads, all_faults)
    return [(k, w) for w in wls for k in faults
            if cell_status(cells, k, w) == "gap"]


# fault kind -> the bundled-CLI nemesis flag that injects it
# clusterlessly; kinds with no demo package fall back to a
# nemesis_package faults hint (the suite-level combined.py option)
SUGGEST_PACKAGES = {
    "partition": "--nemesis partition",
    "process-pause": "--nemesis hammer",
    "none": "",
}

# fault kind -> the combined.nemesis_package faults option that
# injects it on a real cluster
PACKAGE_FAULTS = {
    "partition": "partition", "packet": "packet",
    "db-kill": "kill", "db-pause": "pause",
    "clock-bump": "clock", "clock-strobe": "clock",
    "clock-reset": "clock", "file-bitflip": "file-corruption",
    "file-truncate": "file-corruption",
    "file-lost-writes": "file-corruption",
    "membership": "membership",
}


def suggest(cells: dict[tuple, dict],
            all_workloads: Iterable[str] | None = None,
            limit: int = 8) -> list[dict]:
    """Ranked gap-filling configs — the campaign runner's input hook.
    Greedy diversified ranking: each pick prefers the least-exercised
    fault kind and workload, then penalizes both so consecutive
    suggestions spread across the matrix instead of marching down one
    dark column; ties break on names, so the ranking is deterministic
    for a given atlas. Each suggestion names a runnable config: the
    bundled CLI line when the fault has a clusterless package, a
    nemesis_package faults hint otherwise."""
    fault_runs: dict[str, int] = {}
    wl_runs: dict[str, int] = {}
    for (k, w, _a), c in cells.items():
        fault_runs[k] = fault_runs.get(k, 0) + c["runs"]
        wl_runs[w] = wl_runs.get(w, 0) + c["runs"]
    remaining = gaps(cells, all_workloads)
    picked_f: dict[str, int] = {}
    picked_w: dict[str, int] = {}
    out = []
    while remaining and len(out) < limit:
        kind, wl = min(remaining, key=lambda kw: (
            picked_f.get(kw[0], 0), fault_runs.get(kw[0], 0),
            picked_w.get(kw[1], 0), wl_runs.get(kw[1], 0),
            kw[0], kw[1]))
        remaining.remove((kind, wl))
        picked_f[kind] = picked_f.get(kind, 0) + 1
        picked_w[wl] = picked_w.get(wl, 0) + 1
        pkg = SUGGEST_PACKAGES.get(kind)
        if pkg is not None:
            config = (f"python -m jepsen_tpu test --no-ssh "
                      f"--workload {wl} {pkg}").strip()
        else:
            hint = PACKAGE_FAULTS.get(kind, kind)
            config = (f"suite run: workload={wl} "
                      f"nemesis_package(faults=['{hint}'])")
        out.append({"fault": kind, "workload": wl, "config": config,
                    "fault-runs": fault_runs.get(kind, 0),
                    "workload-runs": wl_runs.get(wl, 0)})
    return out


def coverage_text(cells: dict[tuple, dict],
                  all_workloads: Iterable[str] | None = None,
                  n_suggest: int = 0) -> str:
    """The `coverage` CLI body: matrix + per-cell detail for witnessed
    cells + gap summary (+ suggestions when asked)."""
    lines = [matrix_text(cells, all_workloads), ""]
    witnessed = [(key, c) for key, c in sorted(cells.items())
                 if c["witnessed"]]
    if witnessed:
        lines.append("# Witnessed anomalies")
        for (k, w, a), c in witnessed:
            runs = ", ".join(c["witnesses"][:3])
            more = (f" (+{len(c['witnesses']) - 3} more)"
                    if len(c["witnesses"]) > 3 else "")
            frac = c.get("earliest-witness-frac")
            at = (f" (earliest witness at {frac * 100:.0f}% of the "
                  "history)" if isinstance(frac, (int, float))
                  else "")
            lines.append(f"  {k} × {w} × {a}: {c['witnessed']}/"
                         f"{c['runs']} runs — {runs}{more}{at}")
        lines.append("")
    gs = gaps(cells, all_workloads)
    lines.append(f"# Gaps: {len(gs)} fault × workload cells never "
                 "exercised")
    if n_suggest:
        lines.append("")
        lines.append("# Suggested configs (largest gaps first)")
        for s in suggest(cells, all_workloads, limit=n_suggest):
            lines.append(f"  {s['fault']} × {s['workload']}: "
                         f"{s['config']}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Prometheus exposition (web.py /metrics)
# ---------------------------------------------------------------------------

def _prom_label(v) -> str:
    """Label-value sanitization (the reports/profile.py span-label
    rule): workload names come from arbitrary test names, and one
    stray quote must not invalidate the whole /metrics scrape."""
    return str(v).replace("\\", "_").replace('"', "_")


def prometheus_lines(cells: dict[tuple, dict]) -> list[str]:
    """Atlas-level Prometheus samples for the existing /metrics
    endpoint: per-cell run counters plus the cell-status summary the
    fleet dashboards alert on."""
    lines = ["# TYPE jepsen_tpu_coverage_runs counter"]
    for (k, w, a), c in sorted(cells.items()):
        k, w, a = _prom_label(k), _prom_label(w), _prom_label(a)
        lines.append(
            f'jepsen_tpu_coverage_runs{{fault="{k}",workload="{w}",'
            f'anomaly="{a}"}} {c["runs"]}')
        if c["witnessed"]:
            lines.append(
                f'jepsen_tpu_coverage_witnessed{{fault="{k}",'
                f'workload="{w}",anomaly="{a}"}} {c["witnessed"]}')
    counts = {"witnessed": 0, "clean": 0, "unknown": 0}
    pairs = {}
    for (k, w, _a) in cells:
        pairs[(k, w)] = cell_status(cells, k, w)
    for st in pairs.values():
        if st in counts:
            counts[st] += 1
    lines.append("# TYPE jepsen_tpu_coverage_cells gauge")
    for st, n in sorted(counts.items()):
        lines.append(f'jepsen_tpu_coverage_cells{{status="{st}"}} {n}')
    return lines
