"""RabbitMQ test suite: a mirrored durable queue checked for message
conservation (enqueue/dequeue/drain -> total-queue).

Capability reference: rabbitmq/src/jepsen/rabbitmq.clj — DB: deb
install + shared erlang cookie + stop_app/join_cluster/start_app into
the primary with a synchronize barrier between phases, ha-mode
mirroring policy on "jepsen." queues (25-99); client: declare a
durable queue, enqueue with publisher confirms, dequeue where an empty
queue or timeout is a :fail (re-delivery makes that sound), and a
drain that loops dequeues until empty (103-174); checked with
total-queue (the reference wires checker/total-queue in its test).
The reference links the langohr AMQP client into the JVM; here ops go
through `rabbitmqadmin -f raw_json` on the node (management plugin),
keeping the control host driver-free like the zookeeper/postgres
suites.
"""

from __future__ import annotations

import json
import logging
import time

from .. import checker as chk
from .. import cli, client as jclient, control, core, db as jdb
from .. import generator as gen
from .. import nemesis as jnemesis
from .. import testing
from ..control import util as cu
from ..control.core import RemoteError
from ..core import primary
from ..os_setup import debian

logger = logging.getLogger(__name__)

VERSION = "3.8.9"
QUEUE = "jepsen.queue"
COOKIE = "jepsen-rabbitmq"
ADMIN = "/usr/local/bin/rabbitmqadmin"
MGMT_PORT = 15672
LOGFILE = "/var/log/rabbitmq/rabbit.log"


class RabbitDB(jdb.DB):
    """deb-installed rabbit joined into one mirrored cluster
    (rabbitmq.clj db, 25-99)."""

    supports_kill = True

    def __init__(self, version: str = VERSION):
        self.version = version

    def setup(self, test, node):
        logger.info("%s installing rabbitmq %s", node, self.version)
        deb = f"rabbitmq-server_{self.version}-1_all.deb"
        url = (f"https://github.com/rabbitmq/rabbitmq-server/releases/"
               f"download/v{self.version}/{deb}")
        with control.su():
            debian.install(["erlang-nox"])
            path = cu.cached_wget(url)
            # apt resolves the deb's dependencies, unlike bare dpkg -i
            control.exec_("apt-get", "install", "-y", path)
            # Same erlang cookie everywhere, set before clustering
            control.exec_("service", "rabbitmq-server", "stop",
                          check=False)
            control.exec_("sh", "-c",
                          "echo " + COOKIE +
                          " > /var/lib/rabbitmq/.erlang.cookie")
            control.exec_("chmod", "400",
                          "/var/lib/rabbitmq/.erlang.cookie")
            control.exec_("chown", "rabbitmq:rabbitmq",
                          "/var/lib/rabbitmq/.erlang.cookie")
            control.exec_("service", "rabbitmq-server", "start")
            control.exec_("rabbitmq-plugins", "enable",
                          "rabbitmq_management")
            # The management plugin serves its own CLI; the deb does
            # not ship rabbitmqadmin on PATH
            cu.await_tcp_port(MGMT_PORT, timeout_secs=60)
            control.exec_("wget", "-q", "-O", ADMIN,
                          f"http://localhost:{MGMT_PORT}"
                          f"/cli/rabbitmqadmin")
            control.exec_("chmod", "+x", ADMIN)
            if node != primary(test):
                control.exec_("rabbitmqctl", "stop_app")
        # everyone's daemon is up (or stopped-app) before joins begin
        core.synchronize(test)
        with control.su():
            if node != primary(test):
                logger.info("%s joining %s", node, primary(test))
                control.exec_("rabbitmqctl", "join_cluster",
                              f"rabbit@{primary(test)}")
                control.exec_("rabbitmqctl", "start_app")
        core.synchronize(test)
        with control.su():
            # Mirror jepsen. queues across a majority with auto sync
            control.exec_(
                "rabbitmqctl", "set_policy", "ha-maj", "jepsen.",
                '{"ha-mode": "exactly", "ha-params": 3, '
                '"ha-sync-mode": "automatic"}')
        logger.info("%s rabbit ready", node)

    def teardown(self, test, node):
        logger.info("%s nuking rabbit", node)
        with control.su():
            control.exec_("killall", "-9", "beam.smp", "epmd",
                          check=False)
            control.exec_("rm", "-rf", "/var/lib/rabbitmq/mnesia/")
            control.exec_("service", "rabbitmq-server", "stop",
                          check=False)

    def kill(self, test, node):
        with control.su():
            control.exec_("killall", "-9", "beam.smp", check=False)
        return "killed"

    def start(self, test, node):
        with control.su():
            control.exec_("service", "rabbitmq-server", "start")
        return "started"

    def log_files(self, test, node):
        return [LOGFILE]


# ---------------------------------------------------------------------------
# Client over rabbitmqadmin
# ---------------------------------------------------------------------------

class RabbitAdmin:
    """Runs rabbitmqadmin on the node; split out so tests can stub
    `run`."""

    def __init__(self, test, node, timeout: float = 8.0):
        self.test = test
        self.node = node
        self.timeout = timeout
        self.sess = control.session(test, node)

    def run(self, *args) -> str:
        with control.with_session(self.test, self.node, self.sess):
            return control.exec_(ADMIN, "-f", "raw_json", *args,
                                 timeout=self.timeout)

    def close(self):
        control.disconnect(self.sess)


class RabbitQueueClient(jclient.Client):
    """Queue ops (rabbitmq.clj QueueClient, 128-174): enqueue is a
    routed-checked publish; dequeue fetches with ack_requeue_false
    (an EMPTY reply is a definite :fail, an errored request :info —
    the server may have consumed the message before the reply was
    lost); drain loops until :empty, keeping collected values even if
    a later fetch errors."""

    def __init__(self, admin_factory=RabbitAdmin):
        self.admin_factory = admin_factory
        self.admin = None

    def open(self, test, node):
        c = RabbitQueueClient(self.admin_factory)
        c.admin = self.admin_factory(test, node)
        return c

    def setup(self, test):
        self.admin.run("declare", "queue", f"name={QUEUE}",
                       "durable=true", "auto_delete=false")
        return self

    def close(self, test):
        if self.admin is not None:
            self.admin.close()

    def _dequeue(self, op):
        out = self.admin.run("get", f"queue={QUEUE}",
                             "ackmode=ack_requeue_false", "count=1")
        msgs = json.loads(out) if out.strip() else []
        if not msgs:
            return op.copy(type="fail", error="empty")
        return op.copy(type="ok", value=int(msgs[0]["payload"]))

    def invoke(self, test, op):
        values = []  # survives a drain that dies mid-loop
        try:
            if op.f == "enqueue":
                out = self.admin.run("publish",
                                     "exchange=amq.default",
                                     f"routing_key={QUEUE}",
                                     f"payload={int(op.value)}")
                # rabbitmqadmin exits 0 even when the message routed
                # nowhere ("Message published but NOT routed"): that
                # message was never enqueued — a definite :fail, not
                # a spurious total-queue loss
                if "not routed" in out.lower():
                    return op.copy(type="fail", error="not routed")
                return op.copy(type="ok")
            if op.f == "dequeue":
                return self._dequeue(op)
            if op.f == "drain":
                # Transient fetch errors must not end the drain as
                # :ok — messages left in the queue would read as lost.
                # Retry (up to 5 CONSECUTIVE failures; post-heal
                # drains make errors rare). But any errored get may
                # also have consumed a message whose reply was lost
                # (ack_requeue_false removes server-side), so a drain
                # that saw ANY error is indeterminate: complete :info
                # keeping fetched values (acked messages are really
                # gone) so the conservation checker sees an aborted
                # drain, never a definite empty-queue claim.
                consecutive, any_error, last_err = 0, False, ""
                while True:
                    try:
                        r = self._dequeue(op)
                    except RemoteError as e:
                        consecutive += 1
                        any_error = True
                        last_err = (f"{e.err or ''} "
                                    f"{e.out or ''}").strip()[:200]
                        if consecutive >= 5:
                            return op.copy(type="info", value=values,
                                           error=last_err)
                        time.sleep(0.2 * consecutive)
                        continue
                    consecutive = 0
                    if r.type != "ok":
                        if any_error:
                            return op.copy(type="info", value=values,
                                           error=last_err)
                        return op.copy(type="ok", value=values)
                    values.append(r.value)
            raise ValueError(f"unknown f {op.f!r}")
        except RemoteError as e:
            err = f"{e.err or ''} {e.out or ''}".strip()[:200]
            if op.f == "dequeue":
                # get-with-ack REMOVES the message when the server
                # processes the request, so a lost response may have
                # consumed one: indeterminate, never a definite :fail
                return op.copy(type="info", error=err)
            # an unconfirmed publish may still have landed
            return op.copy(type="info", error=err)


# ---------------------------------------------------------------------------
# Test
# ---------------------------------------------------------------------------

def queue_workload(opts: dict) -> dict:
    """Enqueue/dequeue mix + a drain kept as a SEPARATE phase, so the
    test can heal the network before draining (the workload bundle in
    workloads/queue.py runs drain immediately after the mix; under a
    nemesis the drain must come after recovery or conservation fails
    spuriously on still-partitioned messages)."""
    import itertools

    counter = itertools.count()
    mix = gen.mix([lambda: {"f": "enqueue", "value": next(counter)},
                   lambda: {"f": "dequeue", "value": None}])
    return {
        "client": RabbitQueueClient(),
        "mix": gen.limit(opts.get("ops", 500), mix),
        "drain": gen.each_thread(gen.once(
            lambda: {"f": "drain", "value": None})),
        "checker": chk.compose({"total-queue": chk.total_queue(),
                                "stats": chk.stats()}),
    }


WORKLOADS = {"queue": queue_workload}


def rabbitmq_test(opts: dict) -> dict:
    name = opts.get("workload", "queue")
    w = WORKLOADS[name](opts)
    test = testing.noop_test()
    test.update(
        name=f"rabbitmq-{name}",
        os=debian.os,
        db=RabbitDB(opts.get("version", VERSION)),
        ssh=opts["ssh"],
        nodes=opts["nodes"],
        concurrency=opts["concurrency"],
        client=w["client"],
        nemesis=jnemesis.partition_random_halves(),
        checker=chk.compose({"workload": w["checker"],
                             "perf": chk.perf(),
                             "timeline": chk.timeline()}),
        generator=gen.phases(
            gen.time_limit(
                opts.get("time_limit", 30),
                gen.clients(
                    gen.stagger(1.0 / opts.get("rate", 20),
                                w["mix"]),
                    jnemesis.start_stop_cycle(10.0))),
            gen.nemesis(gen.once({"type": "info", "f": "stop"})),
            gen.sleep(opts.get("recovery_time", 5)),
            gen.clients(w["drain"])))
    return test


def _opts(p):
    p.add_argument("--workload", default="queue",
                   help="Workload. " + cli.one_of(WORKLOADS))
    p.add_argument("--version", default=VERSION,
                   help="rabbitmq-server version to install.")
    p.add_argument("--rate", type=float, default=20)
    return p


def main(argv=None) -> None:
    commands = {}
    commands.update(cli.single_test_cmd(rabbitmq_test,
                                        parser_fn=_opts))
    commands.update(cli.serve_cmd())
    cli.run_cli(commands, argv)


if __name__ == "__main__":
    main()
