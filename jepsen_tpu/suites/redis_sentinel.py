"""Redis + Sentinel test suite: a CAS register across sentinel-driven
failover.

Capability reference: the original aphyr/jepsen redis test
(redis/src/jepsen/redis.clj and the "Redis" Jepsen post) — one master,
N-1 replicas, a sentinel quorum promoting a replica when the master is
partitioned away, and a linearizable-register workload that catches
the split-brain window where acknowledged writes to the old master are
discarded on failover. The reference drives carmine from the JVM; here
ops run `redis-cli` on the node over the control plane (the raftis
suite's transport pattern), with CAS made atomic server-side via a
tiny EVAL script — redis has no native CAS, and a WATCH/MULTI pair
over two CLI invocations would not be one operation.

Clients discover the current master through their LOCAL sentinel
(`SENTINEL get-master-addr-by-name`), re-resolving once when a command
bounces off a READONLY replica — exactly how a sentinel-aware client
library behaves.
"""

from __future__ import annotations

import logging
import random

from .. import checker as chk
from .. import cli, client as jclient, control, db as jdb
from .. import generator as gen
from .. import nemesis as jnemesis
from .. import testing
from ..checker import models
from ..control import util as cu
from ..control.core import RemoteError
from ..os_setup import debian

logger = logging.getLogger(__name__)

DIR = "/opt/redis-sentinel"
CONF = f"{DIR}/redis.conf"
SENTINEL_CONF = f"{DIR}/sentinel.conf"
LOGFILE = f"{DIR}/redis.log"
SENTINEL_LOG = f"{DIR}/sentinel.log"
PIDFILE = f"{DIR}/redis.pid"
SENTINEL_PID = f"{DIR}/sentinel.pid"
PORT = 6379
SENTINEL_PORT = 26379
MASTER_NAME = "jepsen"

# server-side CAS: atomic because EVAL runs exclusively
CAS_LUA = ("if redis.call('GET', KEYS[1]) == ARGV[1] then "
           "redis.call('SET', KEYS[1], ARGV[2]); return 1 "
           "else return 0 end")


def primary_node(test):
    return str(test["nodes"][0])


class RedisSentinelDB(jdb.DB):
    """apt install + a replica-of-the-first-node topology + one
    sentinel per node monitoring it (redis.clj db): the sentinels form
    the failover quorum the partitions attack."""

    supports_kill = True

    def _start(self, test, node):
        cu.start_daemon(
            {"logfile": LOGFILE, "pidfile": PIDFILE, "chdir": DIR},
            "/usr/bin/redis-server", CONF)
        cu.start_daemon(
            {"logfile": SENTINEL_LOG, "pidfile": SENTINEL_PID,
             "chdir": DIR},
            "/usr/bin/redis-server", SENTINEL_CONF, "--sentinel")

    def setup(self, test, node):
        logger.info("%s installing redis + sentinel", node)
        primary = primary_node(test)
        quorum = len(test["nodes"]) // 2 + 1
        with control.su():
            debian.install(["redis-server", "redis-sentinel",
                            "redis-tools"])
            # the distro units would fight our daemons for the ports
            control.exec_("systemctl", "stop", "redis-server",
                          check=False)
            control.exec_("systemctl", "stop", "redis-sentinel",
                          check=False)
            control.exec_("mkdir", "-p", DIR)
            conf = [f"port {PORT}", "bind 0.0.0.0",
                    "protected-mode no", f"dir {DIR}",
                    "appendonly yes", "appendfsync everysec"]
            if str(node) != primary:
                conf.append(f"replicaof {primary} {PORT}")
            cu.write_file("\n".join(conf) + "\n", CONF)
            sent = [f"port {SENTINEL_PORT}", "bind 0.0.0.0",
                    "protected-mode no", f"dir {DIR}",
                    f"sentinel monitor {MASTER_NAME} {primary} "
                    f"{PORT} {quorum}",
                    f"sentinel down-after-milliseconds {MASTER_NAME} "
                    "5000",
                    f"sentinel failover-timeout {MASTER_NAME} 10000",
                    f"sentinel parallel-syncs {MASTER_NAME} 1"]
            cu.write_file("\n".join(sent) + "\n", SENTINEL_CONF)
            self._start(test, node)
        cu.await_tcp_port(PORT, timeout_secs=60)
        cu.await_tcp_port(SENTINEL_PORT, timeout_secs=60)

    def teardown(self, test, node):
        logger.info("%s tearing down redis + sentinel", node)
        with control.su():
            cu.stop_daemon("/usr/bin/redis-server", SENTINEL_PID)
            cu.stop_daemon("/usr/bin/redis-server", PIDFILE)
            control.exec_("rm", "-rf", DIR)

    def kill(self, test, node):
        with control.su():
            cu.grepkill("redis-server")
        return "killed"

    def start(self, test, node):
        with control.su():
            self._start(test, node)
        return "started"

    def log_files(self, test, node):
        return [LOGFILE, SENTINEL_LOG]


# ---------------------------------------------------------------------------
# redis-cli transport with sentinel master discovery
# ---------------------------------------------------------------------------

class SentinelCli:
    """redis-cli against the CURRENT master, resolved through the
    node's local sentinel. Split out so tests can stub `run`.
    Non-retrying session: SET/EVAL are not idempotent (the raftis
    RedisCli rationale)."""

    def __init__(self, test, node, timeout: float = 5.0):
        self.test = test
        self.node = node
        self.timeout = timeout
        self.master = None  # (host, port), lazily resolved
        self.sess = self._session(test, node)

    @staticmethod
    def _session(test, node):
        if test.get("remote") is not None or \
                (test.get("ssh") or {}).get("dummy"):
            return control.session(test, node)
        from ..control.scp import ScpRemote
        from ..control.ssh import SshRemote

        return ScpRemote(SshRemote()).connect(
            control.conn_spec(test, node))

    def _cli(self, host, port, *args) -> str:
        with control.with_session(self.test, self.node, self.sess):
            return control.exec_("redis-cli", "-h", str(host), "-p",
                                 str(port), *args,
                                 timeout=self.timeout)

    def resolve_master(self) -> tuple:
        out = self._cli(self.node, SENTINEL_PORT, "SENTINEL",
                        "get-master-addr-by-name", MASTER_NAME)
        lines = [ln.strip() for ln in out.splitlines() if ln.strip()]
        if len(lines) < 2:
            raise RemoteError("sentinel knows no master", exit=0,
                              out=out, err="", cmd="SENTINEL",
                              node=self.node)
        self.master = (lines[0], int(lines[1]))
        return self.master

    def run(self, *args) -> str:
        if self.master is None:
            self.resolve_master()
        return self._cli(self.master[0], self.master[1], *args)

    def forget_master(self) -> None:
        self.master = None

    def close(self):
        control.disconnect(self.sess)


_DEFINITE = ("connection refused", "could not connect", "no route",
             "name or service not known", "knows no master")

_ERROR_PREFIXES = ("(error)", "ERR ", "-ERR", "WRONGTYPE", "LOADING",
                   "MASTERDOWN", "NOAUTH", "READONLY", "NOREPLICAS")


class _ErrorReply(Exception):
    """The server REJECTED the command — it definitely did not
    apply."""


def _reply(out: str) -> str:
    s = out.strip()
    if s.startswith(_ERROR_PREFIXES):
        raise _ErrorReply(s)
    return s


def _classify(op, e: Exception):
    if isinstance(e, _ErrorReply):
        return op.copy(type="fail", error=str(e)[:200])
    msg = f"{getattr(e, 'err', '')} {getattr(e, 'out', '')} {e}".lower()
    if op.f == "read" or any(m in msg for m in _DEFINITE):
        # reads are safe to fail; refused connections never applied
        return op.copy(type="fail", error=msg.strip()[:200])
    return op.copy(type="info", error=msg.strip()[:200])


class SentinelRegisterClient(jclient.Client):
    """CAS register at key "r" on the sentinel-resolved master. A
    command bouncing off a READONLY replica (stale master view after a
    failover) re-resolves ONCE and retries — still one history op,
    because the READONLY bounce provably did not apply."""

    def __init__(self, cli_factory=SentinelCli):
        self.cli_factory = cli_factory
        self.cli = None

    def open(self, test, node):
        c = SentinelRegisterClient(self.cli_factory)
        c.cli = self.cli_factory(test, node)
        return c

    def close(self, test):
        if self.cli is not None:
            self.cli.close()

    def _run(self, *args) -> str:
        try:
            return _reply(self.cli.run(*args))
        except _ErrorReply as e:
            if not str(e).startswith("READONLY"):
                raise
            # stale master: the replica REFUSED the write (nothing
            # applied), so one re-resolve + retry is sound
            self.cli.forget_master()
            return _reply(self.cli.run(*args))

    def invoke(self, test, op):
        try:
            if op.f == "read":
                out = self._run("GET", "r")
                return op.copy(type="ok",
                               value=int(out) if out else None)
            if op.f == "write":
                out = self._run("SET", "r", str(op.value))
                if out != "OK":
                    raise RemoteError("unexpected SET reply", exit=0,
                                      out=out, err="", cmd="SET",
                                      node=None)
                return op.copy(type="ok")
            if op.f == "cas":
                frm, to = op.value
                out = self._run("EVAL", CAS_LUA, "1", "r", str(frm),
                                str(to))
                if out not in ("0", "1"):
                    raise RemoteError("unexpected EVAL reply", exit=0,
                                      out=out, err="", cmd="EVAL",
                                      node=None)
                return op.copy(type="ok" if out == "1" else "fail")
            raise ValueError(f"unknown f {op.f!r}")
        except (RemoteError, _ErrorReply) as e:
            return _classify(op, e)


# ---------------------------------------------------------------------------
# Workloads / test
# ---------------------------------------------------------------------------

def register_workload(opts: dict) -> dict:
    rng = random.Random(opts.get("seed"))

    def one():
        r = rng.random()
        if r < 0.4:
            return {"f": "read", "value": None}
        if r < 0.7:
            return {"f": "write", "value": rng.randrange(5)}
        return {"f": "cas", "value": [rng.randrange(5),
                                      rng.randrange(5)]}

    return {
        "client": SentinelRegisterClient(),
        "generator": gen.limit(opts.get("ops", 500), one),
        "checker": chk.linearizable(
            {"model": models.cas_register()}),
    }


WORKLOADS = {"register": register_workload}


def redis_sentinel_test(opts: dict) -> dict:
    name = opts.get("workload") or "register"
    w = WORKLOADS[name](opts)
    test = testing.noop_test()
    test.update(
        name=f"redis-sentinel-{name}",
        os=debian.os,
        db=RedisSentinelDB(),
        ssh=opts["ssh"],
        nodes=opts["nodes"],
        concurrency=opts["concurrency"],
        client=w["client"],
        # the reference's shape: partition the master away from the
        # sentinel majority and watch the failover window
        nemesis=jnemesis.partition_random_halves(),
        checker=chk.compose({"workload": w["checker"],
                             "stats": chk.stats(),
                             "perf": chk.perf(),
                             "timeline": chk.timeline()}),
        generator=gen.time_limit(
            opts.get("time_limit", 30),
            gen.clients(
                gen.stagger(1.0 / opts.get("rate", 20),
                            w["generator"]),
                jnemesis.start_stop_cycle(10.0))))
    return test


def _opts(p):
    p.add_argument("--workload", default=None,
                   help="Workload (default register). "
                        + cli.one_of(WORKLOADS))
    p.add_argument("--rate", type=float, default=20)
    return p


def main(argv=None) -> None:
    commands = {}
    commands.update(cli.single_test_cmd(redis_sentinel_test,
                                        parser_fn=_opts))
    commands.update(cli.serve_cmd())
    commands.update(cli.coverage_cmd(list(WORKLOADS)))
    cli.run_cli(commands, argv)


if __name__ == "__main__":
    main()
