"""RethinkDB test suite: single-document CAS under tunable write-acks
and read-mode, the reference's document workload.

Capability reference: jepsen's rethinkdb test (aphyr/jepsen
rethinkdb/src/jepsen/rethinkdb.clj + document.clj) — apt install +
/etc/rethinkdb/instances.d config with `join` lines and a per-node
server name, a `jepsen.cas` table created from the primary and
reconfigured to the requested write_acks, and a read/write/cas client
over ReQL whose `read_mode`/`write_acks` pair states the consistency
claim (majority/majority is the linearizable configuration; anything
weaker is expected to — and does, in the reference's findings — lose
the linearizability check under partitions).

The reference drives ReQL through the JVM driver; here ops run a small
query helper (QUERY_SCRIPT, uploaded at setup) with the python driver
installed on the node — the same node-side CLI transport pattern as
the raftis/disque suites, so tests stub the transport with a scripted
in-memory document.
"""

from __future__ import annotations

import logging
import random

from .. import checker as chk
from .. import cli, client as jclient, control, db as jdb
from .. import generator as gen
from .. import nemesis as jnemesis
from .. import testing
from ..checker import models
from ..control import util as cu
from ..control.core import RemoteError
from ..os_setup import debian

logger = logging.getLogger(__name__)

VERSION = "2.4.4"
CLIENT_PORT = 28015
CLUSTER_PORT = 29015
CONF = "/etc/rethinkdb/instances.d/jepsen.conf"
DATA_DIR = "/var/lib/rethinkdb/jepsen"
LOGFILE = "/var/log/rethinkdb.log"
QUERY = "/opt/jepsen/rethink_query.py"
DB = "jepsen"
TABLE = "cas"
DOC_ID = 0

# The node-side query helper: one op per invocation, one reply line on
# stdout. Speaking a fixed little protocol (VAL/NONE/OK/CAS n/ERR msg)
# keeps the client's classification independent of driver versions.
QUERY_SCRIPT = '''\
import sys
try:
    from rethinkdb import r
except ImportError:
    import rethinkdb as r
op = sys.argv[1]
read_mode, write_acks = sys.argv[2], sys.argv[3]
try:
    conn = r.connect("localhost", {client_port})
    t = r.db("{db}").table("{table}", read_mode=read_mode)
    if op == "setup":
        try:
            r.db_create("{db}").run(conn)
        except Exception:
            pass
        try:
            r.db("{db}").table_create(
                "{table}", replicas=int(sys.argv[4])).run(conn)
        except Exception:
            pass
        r.db("{db}").table("{table}").config().update(
            {{"write_acks": write_acks}}).run(conn)
        r.db("{db}").table("{table}").wait().run(conn)
        print("OK")
    elif op == "read":
        row = t.get({doc_id}).run(conn)
        print("NONE" if row is None else "VAL %d" % row["val"])
    elif op == "write":
        res = t.insert({{"id": {doc_id}, "val": int(sys.argv[4])}},
                       conflict="replace").run(conn)
        if res.get("errors"):
            print("ERR %s" % res.get("first_error", "write error"))
        else:
            print("OK")
    elif op == "cas":
        old, new = int(sys.argv[4]), int(sys.argv[5])
        res = t.get({doc_id}).update(
            lambda row: r.branch(row["val"].eq(old),
                                 {{"val": new}}, r.error("abort")),
            return_changes=False).run(conn)
        if res.get("errors"):
            err = res.get("first_error", "")
            # only OUR precondition abort is a definite no-apply; any
            # other update error (ack/contact failures) may have
            # applied and must classify as indeterminate, not CAS 0
            if "abort" in err:
                print("CAS 0")
            else:
                print("ERR %s" % (err or "cas error"))
        else:
            print("CAS %d" % res.get("replaced", 0))
    else:
        print("ERR unknown op %s" % op)
except Exception as e:
    print("ERR %s" % e)
'''.format(client_port=CLIENT_PORT, db=DB, table=TABLE, doc_id=DOC_ID)


def conf_body(test, node) -> str:
    """The instance config (rethinkdb.clj db setup): bind everywhere,
    a stable server name, and a join line per peer."""
    lines = ["bind=all",
             f"server-name={str(node).replace('.', '_')}",
             f"directory={DATA_DIR}",
             f"log-file={LOGFILE}",
             f"driver-port={CLIENT_PORT}",
             f"cluster-port={CLUSTER_PORT}"]
    lines += [f"join={n}:{CLUSTER_PORT}" for n in test["nodes"]
              if str(n) != str(node)]
    return "\n".join(lines) + "\n"


class RethinkDB(jdb.DB):
    """apt install + instance config + service, table setup from the
    primary (rethinkdb.clj db, document.clj table create)."""

    supports_kill = True
    supports_primaries = True

    def __init__(self, version: str = VERSION,
                 write_acks: str = "majority",
                 read_mode: str = "majority"):
        self.version = version
        self.write_acks = write_acks
        self.read_mode = read_mode

    def setup(self, test, node):
        logger.info("%s installing rethinkdb %s", node, self.version)
        with control.su():
            debian.install(["rethinkdb", "python3-pip"])
            # the query helper's driver, node-side only (the control
            # process never imports it)
            control.exec_("pip3", "install", "-q", "rethinkdb")
            control.exec_("mkdir", "-p", "/opt/jepsen")
            cu.write_file(QUERY_SCRIPT, QUERY)
            control.exec_("mkdir", "-p", DATA_DIR.rsplit("/", 1)[0])
            cu.write_file(conf_body(test, node), CONF)
            control.exec_("service", "rethinkdb", "restart")
        cu.await_tcp_port(CLIENT_PORT, timeout_secs=120)

    def setup_primary(self, test, node):
        """Creates the db/table with one replica per node and the
        requested write_acks (document.clj:25-40)."""
        with control.with_session(test, node):
            control.exec_("python3", QUERY, "setup", self.read_mode,
                          self.write_acks,
                          str(len(test["nodes"])), timeout=120.0)

    def teardown(self, test, node):
        logger.info("%s tearing down rethinkdb", node)
        with control.su():
            try:
                control.exec_("service", "rethinkdb", "stop")
            except RemoteError:
                pass
            control.exec_("rm", "-rf", DATA_DIR, CONF)

    def kill(self, test, node):
        with control.su():
            cu.grepkill("rethinkdb")
        return "killed"

    def start(self, test, node):
        with control.su():
            control.exec_("service", "rethinkdb", "restart")
        return "started"

    def primaries(self, test):
        """Nodes hosting the table's primary replica, via the table
        status on the first reachable node (rethinkdb.clj primaries)."""
        for node in test["nodes"]:
            try:
                with control.with_session(test, node):
                    out = control.exec_(
                        "python3", "-c",
                        "from rethinkdb import r; "
                        f"c=r.connect('localhost',{CLIENT_PORT}); "
                        f"print(r.db('{DB}').table('{TABLE}')"
                        ".status()['shards'][0]['primary_replicas']"
                        ".run(c))", timeout=30.0)
                import re as _re

                # exact-token match: 'n1' must not match inside
                # "['n10']" (server names are dot-mangled node names)
                toks = set(_re.findall(r"[A-Za-z0-9_.-]+", out))
                return [n for n in test["nodes"]
                        if str(n).replace(".", "_") in toks]
            except RemoteError:
                continue
        return []

    def log_files(self, test, node):
        return [LOGFILE]


# ---------------------------------------------------------------------------
# Query transport
# ---------------------------------------------------------------------------

class RethinkCli:
    """One query-helper invocation on the node. Split out so tests can
    stub `run`. Non-retrying session: a CAS whose connection dropped
    after the broker applied it must surface as indeterminate, not be
    silently re-run (the raftis RedisCli rationale)."""

    def __init__(self, test, node, timeout: float = 10.0):
        self.test = test
        self.node = node
        self.timeout = timeout
        self.sess = self._session(test, node)

    @staticmethod
    def _session(test, node):
        if test.get("remote") is not None or \
                (test.get("ssh") or {}).get("dummy"):
            return control.session(test, node)
        from ..control.scp import ScpRemote
        from ..control.ssh import SshRemote

        return ScpRemote(SshRemote()).connect(
            control.conn_spec(test, node))

    def run(self, *args) -> str:
        with control.with_session(self.test, self.node, self.sess):
            return control.exec_("python3", QUERY, *args,
                                 timeout=self.timeout)

    def close(self):
        control.disconnect(self.sess)


# Error messages proving the op was definitely NOT applied
# (document.clj maps "lost contact with primary" to :fail).
_DEFINITE = ("cannot perform read", "cannot perform write",
             "lost contact with primary", "primary replica",
             "table.*does not exist", "connection refused")


class _ErrReply(Exception):
    pass


def _reply(out: str) -> str:
    s = out.strip()
    if s.startswith("ERR"):
        raise _ErrReply(s[3:].strip())
    return s


def _classify(op, e: Exception):
    import re as _re

    msg = f"{e} {getattr(e, 'err', '')} {getattr(e, 'out', '')}" \
        .strip().lower()
    if op.f == "read":
        # an unanswered read changed nothing: always a definite fail
        return op.copy(type="fail", error=msg[:200])
    if isinstance(e, _ErrReply) and any(
            _re.search(m, msg) for m in _DEFINITE):
        return op.copy(type="fail", error=msg[:200])
    return op.copy(type="info", error=msg[:200])


class RethinkCasClient(jclient.Client):
    """read/write/cas on the single document (document.clj client).
    The read_mode/write_acks pair rides on every query — it IS the
    consistency configuration under test."""

    def __init__(self, cli_factory=RethinkCli,
                 read_mode: str = "majority",
                 write_acks: str = "majority"):
        self.cli_factory = cli_factory
        self.read_mode = read_mode
        self.write_acks = write_acks
        self.cli = None

    def open(self, test, node):
        c = RethinkCasClient(self.cli_factory, self.read_mode,
                             self.write_acks)
        c.cli = self.cli_factory(test, node)
        return c

    def close(self, test):
        if self.cli is not None:
            self.cli.close()

    def _run(self, *args) -> str:
        return _reply(self.cli.run(*args))

    def invoke(self, test, op):
        modes = (self.read_mode, self.write_acks)
        try:
            if op.f == "read":
                out = self._run("read", *modes)
                if out == "NONE":
                    return op.copy(type="ok", value=None)
                if out.startswith("VAL "):
                    return op.copy(type="ok", value=int(out[4:]))
                raise RemoteError("unexpected read reply", exit=0,
                                  out=out, err="", cmd="read",
                                  node=None)
            if op.f == "write":
                out = self._run("write", *modes, str(op.value))
                if out != "OK":
                    raise RemoteError("unexpected write reply",
                                      exit=0, out=out, err="",
                                      cmd="write", node=None)
                return op.copy(type="ok")
            if op.f == "cas":
                old, new = op.value
                out = self._run("cas", *modes, str(old), str(new))
                if out == "CAS 1":
                    return op.copy(type="ok")
                if out == "CAS 0":
                    return op.copy(type="fail",
                                   error="precondition failed")
                raise RemoteError("unexpected cas reply", exit=0,
                                  out=out, err="", cmd="cas",
                                  node=None)
            raise ValueError(f"unknown f {op.f!r}")
        except (RemoteError, _ErrReply) as e:
            return _classify(op, e)


# ---------------------------------------------------------------------------
# Workloads / test
# ---------------------------------------------------------------------------

def register_workload(opts: dict) -> dict:
    """The document CAS register: the reference's r/w/cas mix checked
    for linearizability (document.clj workload)."""
    from ..workloads.register import cas_op_mix

    rng = random.Random(opts.get("seed"))
    return {
        "client": RethinkCasClient(
            read_mode=opts.get("read_mode", "majority"),
            write_acks=opts.get("write_acks", "majority")),
        "generator": gen.limit(opts.get("ops", 500),
                               lambda: cas_op_mix(rng)),
        "checker": chk.linearizable(
            {"model": models.cas_register()}),
    }


WORKLOADS = {"register": register_workload}


def rethinkdb_test(opts: dict) -> dict:
    name = opts.get("workload") or "register"
    w = WORKLOADS[name](opts)
    test = testing.noop_test()
    test.update(
        name=f"rethinkdb-{name}",
        os=debian.os,
        db=RethinkDB(opts.get("version", VERSION),
                     write_acks=opts.get("write_acks", "majority"),
                     read_mode=opts.get("read_mode", "majority")),
        ssh=opts["ssh"],
        nodes=opts["nodes"],
        concurrency=opts["concurrency"],
        client=w["client"],
        nemesis=jnemesis.partition_random_halves(),
        checker=chk.compose({"workload": w["checker"],
                             "stats": chk.stats(),
                             "perf": chk.perf(),
                             "timeline": chk.timeline()}),
        generator=gen.time_limit(
            opts.get("time_limit", 30),
            gen.clients(
                gen.stagger(1.0 / opts.get("rate", 20),
                            w["generator"]),
                jnemesis.start_stop_cycle(10.0))))
    return test


def _opts(p):
    p.add_argument("--workload", default=None,
                   help="Workload (default register). "
                        + cli.one_of(WORKLOADS))
    p.add_argument("--version", default=VERSION,
                   help="rethinkdb version to install.")
    p.add_argument("--write-acks", dest="write_acks",
                   default="majority", choices=["single", "majority"],
                   help="Table write-acks mode under test.")
    p.add_argument("--read-mode", dest="read_mode",
                   default="majority",
                   choices=["single", "majority", "outdated"],
                   help="Per-read consistency mode under test.")
    p.add_argument("--rate", type=float, default=20)
    return p


def main(argv=None) -> None:
    commands = {}
    commands.update(cli.single_test_cmd(rethinkdb_test,
                                        parser_fn=_opts))
    commands.update(cli.serve_cmd())
    commands.update(cli.coverage_cmd(list(WORKLOADS)))
    cli.run_cli(commands, argv)


if __name__ == "__main__":
    main()
