"""Dgraph test suite: upsert uniqueness, indexed deletes, linearizable
registers, sets, sequential consistency, bank, long-fork, and elle
rw-register against a zero+alpha cluster, with per-op trace spans.

Capability reference: dgraph/src/jepsen/dgraph/
  core.clj:28-40    — the workload map this suite mirrors
  support.clj       — /opt/dgraph layout, zero/alpha daemons + ports
                      (23-50), node-idx raft ids, --peer/--zero wiring
  client.clj        — txn lifecycle with conflict-as-fail; upsert =
                      query-then-insert-unless-exists
  upsert.clj        — at most one ok upsert per key; reads see <= 1 uid
  delete.clj        — upsert/delete/read per key; index must never
                      show more than one record
  linearizable_register.clj, set.clj, sequential.clj, bank.clj,
  long_fork.clj, wr.clj — workload semantics (generators + checkers
                      live in jepsen_tpu.workloads)
  trace.clj         — per-op tracing spans (here: a jsonl span log
                      in the store dir instead of a jaeger exporter)

Transport: dgraph's public HTTP API on the alpha (mutate with upsert
blocks and conditional mutations, query, and the startTs/commit txn
protocol), driven through `curl` on each node. Clients depend only on
the semantic DgraphHTTP interface, so the clusterless tests substitute
an in-memory implementation with real txn-conflict behavior.
"""

from __future__ import annotations

import json
import logging
import time

from .. import checker as chk
from .. import cli, client as jclient, control, db as jdb
from .. import generator as gen
from .. import independent, testing
from ..checker import models
from ..control import util as cu
from ..control.core import RemoteError
from ..os_setup import debian
from ..workloads import bank as bank_wl
from ..workloads import long_fork as lf_wl
from ..workloads import sequential as seq_wl
from ..workloads import sets as sets_wl
from ..workloads import txn_wr as wr_wl
from ..workloads import upsert as upsert_wl

logger = logging.getLogger(__name__)

DIR = "/opt/dgraph"
VERSION = "23.1.0"
URL = ("https://github.com/dgraph-io/dgraph/releases/download/"
       f"v{VERSION}/dgraph-linux-amd64.tar.gz")
ZERO_PORT = 5080
ZERO_HTTP = 6080
ALPHA_INTERNAL = 7080
ALPHA_HTTP = 8080
ZERO = (f"{DIR}/zero.log", f"{DIR}/zero.pid")
ALPHA = (f"{DIR}/alpha.log", f"{DIR}/alpha.pid")


def node_idx(test, node) -> int:
    """1-based raft index (support.clj node-idx)."""
    return test["nodes"].index(node) + 1


class DgraphDB(jdb.DB):
    """Installs and runs a zero+alpha per node (support.clj db)."""

    supports_kill = True

    def __init__(self, version: str = VERSION, replicas: int = 3):
        self.version = version
        self.replicas = replicas

    def setup(self, test, node):
        with control.su():
            cu.install_archive(URL, DIR)
        self._start_zero(test, node)
        time.sleep(2)
        self._start_alpha(test, node)
        cu.await_tcp_port(ALPHA_HTTP, timeout_secs=120)

    def _start_zero(self, test, node):
        idx = node_idx(test, node)
        peer = [] if idx == 1 else \
            ["--peer", f"{test['nodes'][0]}:{ZERO_PORT}"]
        with control.su():
            cu.start_daemon(
                {"chdir": DIR, "logfile": ZERO[0], "pidfile": ZERO[1]},
                f"{DIR}/dgraph", "zero", "--raft",
                f"idx={idx}", "--my", f"{node}:{ZERO_PORT}",
                "--replicas", str(self.replicas), *peer)

    def _start_alpha(self, test, node):
        with control.su():
            cu.start_daemon(
                {"chdir": DIR, "logfile": ALPHA[0],
                 "pidfile": ALPHA[1]},
                f"{DIR}/dgraph", "alpha", "--my",
                f"{node}:{ALPHA_INTERNAL}", "--zero",
                f"{test['nodes'][0]}:{ZERO_PORT}",
                "--security", "whitelist=0.0.0.0/0")

    def teardown(self, test, node):
        self.kill(test, node)
        with control.su():
            control.exec_("rm", "-rf", f"{DIR}/p", f"{DIR}/w",
                          f"{DIR}/zw", ZERO[0], ALPHA[0], check=False)

    def log_files(self, test, node):
        return [ZERO[0], ALPHA[0]]

    def kill(self, test, node):
        with control.su():
            cu.grepkill("dgraph")
            control.exec_("rm", "-rf", ZERO[1], ALPHA[1], check=False)

    def start(self, test, node):
        self._start_zero(test, node)
        self._start_alpha(test, node)


# ---------------------------------------------------------------------------
# Transport
# ---------------------------------------------------------------------------


class TxnConflict(Exception):
    """Commit-time conflict (client.clj with-conflict-as-fail)."""


class DgraphHTTP:
    """Semantic operations over the alpha HTTP API. Real transport is
    curl on the node; the clusterless tests swap this class out."""

    def __init__(self, test, node, timeout: float = 10.0):
        self.node = node
        self.base = f"http://localhost:{ALPHA_HTTP}"
        self.timeout = timeout

    def _post(self, path: str, body: str,
              content_type: str = "application/json") -> dict:
        out = control.exec_(
            "curl", "-sf", "--max-time", str(int(self.timeout)),
            "-XPOST", f"{self.base}{path}",
            "-H", f"Content-Type: {content_type}", "-d", body)
        resp = json.loads(out)
        errors = resp.get("errors")
        if errors:
            msg = json.dumps(errors)
            if "conflict" in msg.lower() or "aborted" in msg.lower():
                raise TxnConflict(msg)
            raise RemoteError("dgraph error", exit=1, out=out, err=msg,
                              cmd=path, node=self.node)
        return resp

    def alter_schema(self, schema: str) -> None:
        # /alter takes the raw schema text as its body
        self._post("/alter", schema, "application/dql")

    def _upsert_block(self, query: str, mutations: list[tuple]) -> str:
        """The textual upsert-block format application/rdf implies:
        upsert { query {...} mutation @if(...) { set/delete {...} } }
        (one mutation clause per (cond, verb, nquads) tuple)."""
        parts = [f"upsert {{\n  query {query}\n"]
        for cond, verb, nquads in mutations:
            cond_s = f" {cond}" if cond else ""
            parts.append(
                f"  mutation{cond_s} {{ {verb} {{ {nquads} }} }}\n")
        parts.append("}")
        return "".join(parts)

    def upsert_unless_exists(self, pred: str, key, extra: dict
                             ) -> str | None:
        """Insert-unless-exists via an upsert block with a conditional
        mutation (client.clj upsert!): returns the created uid, or
        None when a record already existed."""
        nquads = " ".join(
            f'_:u <{p}> "{v}" .' for p, v in
            dict(extra, **{pred: key}).items())
        body = self._upsert_block(
            f'{{ q(func: eq({pred}, "{key}")) {{ v as uid }} }}',
            [("@if(eq(len(v), 0))", "set", nquads)])
        resp = self._post("/mutate?commitNow=true", body,
                          "application/rdf")
        uids = resp.get("data", {}).get("uids") or {}
        return next(iter(uids.values()), None)

    def delete_where(self, pred: str, key) -> int:
        """Delete every record matching pred=key (delete.clj)."""
        body = self._upsert_block(
            f'{{ q(func: eq({pred}, "{key}")) {{ v as uid }} }}',
            [(None, "delete", "uid(v) * * .")])
        resp = self._post("/mutate?commitNow=true", body,
                          "application/rdf")
        return len(resp.get("data", {}).get("uids") or {})

    def query_eq(self, pred: str, key, want=("uid",)) -> list[dict]:
        fields = "\n".join(want)
        q = f'{{ q(func: eq({pred}, "{key}")) {{ {fields} }} }}'
        resp = self._post("/query", q, "application/dql")
        return resp.get("data", {}).get("q", [])

    def write_value(self, pred: str, key, vpred: str, value) -> None:
        """Upsert pred=key record and set vpred=value on it, in one
        atomic upsert block (linearizable_register.clj write). Two
        conditional mutations: update-in-place when the record exists,
        create only when it doesn't — an unconditional _:new would
        accumulate a duplicate record on EVERY write."""
        body = self._upsert_block(
            f'{{ q(func: eq({pred}, "{key}")) {{ v as uid }} }}',
            [("@if(gt(len(v), 0))", "set",
              f'uid(v) <{vpred}> "{value}" .'),
             ("@if(eq(len(v), 0))", "set",
              f'_:new <{pred}> "{key}" . '
              f'_:new <{vpred}> "{value}" .')])
        self._post("/mutate?commitNow=true", body, "application/rdf")

    # -- explicit transactions (startTs/commit protocol) ---------------

    def txn_begin(self) -> dict:
        return {"start_ts": None, "keys": [], "preds": []}

    def _merge_ctx(self, txn: dict, resp: dict) -> None:
        ext = resp.get("extensions", {}).get("txn", {})
        if ext.get("start_ts"):
            txn["start_ts"] = ext["start_ts"]
        txn["keys"] += ext.get("keys", [])
        txn["preds"] += ext.get("preds", [])

    def txn_query(self, txn: dict, pred: str, key,
                  want=("uid",)) -> list[dict]:
        ts = f"?startTs={txn['start_ts']}" if txn["start_ts"] else ""
        fields = "\n".join(want)
        q = f'{{ q(func: eq({pred}, "{key}")) {{ {fields} }} }}'
        resp = self._post(f"/query{ts}", q, "application/dql")
        self._merge_ctx(txn, resp)
        return resp.get("data", {}).get("q", [])

    def txn_set(self, txn: dict, nquads: str) -> None:
        ts = f"?startTs={txn['start_ts']}" if txn["start_ts"] else ""
        resp = self._post(f"/mutate{ts}",
                          f"{{ set {{ {nquads} }} }}",
                          "application/rdf")
        self._merge_ctx(txn, resp)

    def txn_commit(self, txn: dict) -> None:
        if txn["start_ts"] is None:
            return
        self._post(f"/commit?startTs={txn['start_ts']}",
                   json.dumps({"keys": txn["keys"],
                               "preds": txn["preds"]}))


# ---------------------------------------------------------------------------
# Per-op tracing (trace.clj analog)
# ---------------------------------------------------------------------------


class TraceClient(jclient.Client):
    """Wraps a client, appending one span per invocation (name, node,
    wall-clock start/end, result type) to <store_dir>/trace.jsonl —
    the role trace.clj's jaeger spans play for the reference."""

    def __init__(self, inner: jclient.Client, path=None):
        self.inner = inner
        self.path = path
        self.node = None

    def open(self, test, node):
        path = self.path
        if path is None and isinstance(test, dict) \
                and test.get("store_dir"):
            path = f"{test['store_dir']}/trace.jsonl"
        c = TraceClient(self.inner.open(test, node), path)
        c.node = node
        return c

    def setup(self, test):
        self.inner.setup(test)
        return self

    def close(self, test):
        self.inner.close(test)

    def invoke(self, test, op):
        t0 = time.time()
        out = self.inner.invoke(test, op)
        if self.path:
            span = {"f": op.f, "node": self.node,
                    "process": op.process, "start": t0,
                    "end": time.time(),
                    "type": getattr(out, "type", None)}
            try:
                with open(self.path, "a") as f:
                    f.write(json.dumps(span) + "\n")
            except OSError:
                pass
        return out


# ---------------------------------------------------------------------------
# Clients
# ---------------------------------------------------------------------------


class _DgClient(jclient.Client):
    http_factory = DgraphHTTP
    schema = None

    def __init__(self, http_factory=None):
        if http_factory is not None:
            self.http_factory = http_factory
        self.http = None

    def open(self, test, node):
        c = type(self)(self.http_factory)
        c.http = self.http_factory(test, node)
        return c

    def setup(self, test):
        if self.schema and self.http is not None:
            self.http.alter_schema(self.schema)
        return self

    def close(self, test):
        self.http = None

    def _guard(self, op, fn, indeterminate=("upsert", "delete",
                                            "write", "transfer")):
        try:
            return fn()
        except TxnConflict as e:
            return op.copy(type="fail", error=f"conflict: {e}")
        except RemoteError as e:
            t = "info" if op.f in indeterminate else "fail"
            return op.copy(type=t, error=str(e))


class UpsertClient(_DgClient):
    """upsert.clj client: upsert by indexed email; ok iff created."""

    schema = "email: string @index(exact) @upsert ."

    def invoke(self, test, op):
        k, _v = op.value

        def go():
            if op.f == "upsert":
                uid = self.http.upsert_unless_exists("email", k, {})
                if uid is None:
                    return op.copy(type="fail", error="present")
                return op.copy(type="ok", value=(k, uid))
            uids = sorted(r["uid"] for r in
                          self.http.query_eq("email", k))
            return op.copy(type="ok", value=(k, uids))

        return self._guard(op, go)


class DeleteClient(_DgClient):
    """delete.clj client: upsert/delete/read one indexed key."""

    schema = "key: int @index(int) @upsert ."

    def invoke(self, test, op):
        k, _v = op.value

        def go():
            if op.f == "upsert":
                uid = self.http.upsert_unless_exists("key", k, {})
                if uid is None:
                    return op.copy(type="fail", error="present")
                return op.copy(type="ok", value=(k, uid))
            if op.f == "delete":
                n = self.http.delete_where("key", k)
                return op.copy(type="ok" if n else "fail",
                               value=(k, n))
            rows = self.http.query_eq("key", k, want=("uid", "key"))
            return op.copy(type="ok", value=(k, rows))

        return self._guard(op, go)


class RegisterClient(_DgClient):
    """linearizable_register.clj client over independent keys:
    read/write (cas unsupported by the reference client either)."""

    schema = ("key: int @index(int) @upsert .\n"
              "val: int .")

    def invoke(self, test, op):
        k, v = op.value

        def go():
            if op.f == "read":
                rows = self.http.query_eq("key", k,
                                          want=("uid", "val"))
                vals = [r.get("val") for r in rows if "val" in r]
                return op.copy(type="ok",
                               value=(k, vals[0] if vals else None))
            self.http.write_value("key", k, "val", v)
            return op.copy(type="ok")

        return self._guard(op, go)


class SetClient(_DgClient):
    """set.clj client: add unique ints, read them all back."""

    schema = ("type: string @index(exact) .\n"
              "value: int @index(int) .")

    def invoke(self, test, op):
        def go():
            if op.f == "add":
                self.http.upsert_unless_exists(
                    "value", op.value, {"type": "element"})
                return op.copy(type="ok")
            rows = self.http.query_eq("type", "element",
                                      want=("value",))
            return op.copy(type="ok", value=sorted(
                int(r["value"]) for r in rows if "value" in r))

        return self._guard(op, go, indeterminate=("add",))


class SequentialClient(_DgClient):
    """sequential.clj client: each subkey insert is its own txn;
    reads walk the subkeys in reverse (workloads.sequential)."""

    schema = "skey: string @index(exact) ."

    def __init__(self, http_factory=None, key_count: int = 5):
        super().__init__(http_factory)
        self.key_count = key_count

    def open(self, test, node):
        c = super().open(test, node)
        c.key_count = self.key_count
        return c

    def invoke(self, test, op):
        key_count = self.key_count

        def go():
            if op.f == "write":
                for sk in seq_wl.subkeys(key_count, op.value):
                    self.http.upsert_unless_exists("skey", sk, {})
                return op.copy(type="ok")
            obs = []
            for sk in reversed(seq_wl.subkeys(key_count, op.value)):
                rows = self.http.query_eq("skey", sk)
                obs.append(sk if rows else None)
            return op.copy(type="ok", value=(op.value, obs))

        return self._guard(op, go, indeterminate=("write",))


class BankClient(_DgClient):
    """bank.clj client: accounts are records keyed by account id;
    transfer moves amount inside one explicit txn (conflict=fail)."""

    schema = ("acct: int @index(int) @upsert .\n"
              "amount: int .")
    accounts = tuple(range(8))
    initial = 10

    def setup(self, test):
        super().setup(test)
        if self.http is not None:
            for a in self.accounts:
                try:
                    self.http.upsert_unless_exists(
                        "acct", a, {"amount": self.initial})
                except (TxnConflict, RemoteError):
                    pass
        return self

    def _balances(self, txn=None) -> dict:
        out = {}
        for a in self.accounts:
            rows = (self.http.txn_query(txn, "acct", a,
                                        want=("uid", "amount"))
                    if txn is not None else
                    self.http.query_eq("acct", a,
                                       want=("uid", "amount")))
            if rows:
                out[a] = int(rows[0].get("amount", 0))
        return out

    def invoke(self, test, op):
        def go():
            if op.f == "read":
                # startTs-pinned txn: 8 per-account queries at ONE
                # timestamp, not 8 independent snapshots
                txn = self.http.txn_begin()
                return op.copy(type="ok", value=self._balances(txn))
            frm, to, amt = (op.value["from"], op.value["to"],
                            op.value["amount"])
            txn = self.http.txn_begin()
            bal = self._balances(txn)
            if bal.get(frm, 0) - amt < 0:
                return op.copy(type="fail", error="insufficient")
            if to not in bal:
                # destination record absent (setup raced a fault):
                # definite no-op, not a crash
                return op.copy(type="fail", error="no such account")
            rows_f = self.http.txn_query(txn, "acct", frm,
                                         want=("uid",))
            rows_t = self.http.txn_query(txn, "acct", to,
                                         want=("uid",))
            if not rows_f or not rows_t:
                return op.copy(type="fail", error="no such account")
            self.http.txn_set(
                txn,
                f'<{rows_f[0]["uid"]}> <amount> '
                f'"{bal[frm] - amt}" .\n'
                f'<{rows_t[0]["uid"]}> <amount> '
                f'"{bal[to] + amt}" .')
            self.http.txn_commit(txn)
            return op.copy(type="ok")

        return self._guard(op, go)


class TxnClient(_DgClient):
    """wr.clj / long_fork.clj client: [f, k, v] micro-ops in one
    explicit txn; reads fill in values, conflicts fail the txn."""

    schema = ("tkey: int @index(int) @upsert .\n"
              "tval: int .")

    def invoke(self, test, op):
        def go():
            txn = self.http.txn_begin()
            out = []
            wrote = False
            for f, k, v in op.value:
                if f == "r":
                    rows = self.http.txn_query(
                        txn, "tkey", k, want=("uid", "tval"))
                    vals = [r["tval"] for r in rows if "tval" in r]
                    out.append([f, k, vals[0] if vals else None])
                else:  # w
                    rows = self.http.txn_query(txn, "tkey", k,
                                               want=("uid",))
                    if rows:
                        self.http.txn_set(
                            txn, f'<{rows[0]["uid"]}> <tval> "{v}" .')
                    else:
                        self.http.txn_set(
                            txn, f'_:n <tkey> "{k}" .\n'
                                 f'_:n <tval> "{v}" .')
                    wrote = True
                    out.append([f, k, v])
            self.http.txn_commit(txn)
            return op.copy(type="ok", value=out)

        try:
            return go()
        except TxnConflict as e:
            return op.copy(type="fail", error=f"conflict: {e}")
        except RemoteError as e:
            return op.copy(type="info", error=str(e))


# ---------------------------------------------------------------------------
# Workloads (core.clj:28-40)
# ---------------------------------------------------------------------------


def _with_client(w: dict, client) -> dict:
    w["client"] = client
    return w


def upsert(opts):
    return _with_client(upsert_wl.workload(opts), UpsertClient())


def delete(opts):
    """upsert/delete/read per independent key; no read may ever see
    more than one record for a key (delete.clj checker)."""
    o = dict(opts or {})
    keys = o.get("keys", list(range(o.get("key_count", 8))))

    def check(test, hist, copts):
        bad = [op for op in hist
               if op.type == "ok" and op.f == "read"
               and isinstance(op.value, (list, tuple))
               and len(op.value) > 1]
        return {"valid?": not bad,
                "bad-reads": [o_.to_dict() for o_ in bad[:8]]}

    def key_gen(k, kopts):
        import random as _r

        rng = _r.Random(None if o.get("seed") is None
                        else repr((o.get("seed"), k)))

        def one():
            f = rng.choice(["upsert", "delete", "read"])
            return {"f": f, "value": None}

        return gen.limit(o.get("ops_per_key", 30), one)

    return {
        "generator": independent.concurrent_generator(
            o.get("group_size", 3), keys, lambda k: key_gen(k, o)),
        "checker": independent.checker(chk.checker(check)),
        "client": DeleteClient(),
    }


def linearizable_register(opts):
    o = dict(opts or {})
    from ..workloads import register as register_wl

    w = register_wl.workload(dict(o, initial=None))
    # dgraph's reference client has no cas; restrict the mix
    keys = o.get("keys", list(range(8)))

    def key_gen(k):
        import random as _r

        rng = _r.Random(None if o.get("seed") is None
                        else repr((o.get("seed"), k)))

        def one():
            if rng.random() < 0.5:
                return {"f": "read", "value": None}
            return {"f": "write", "value": rng.randrange(5)}

        return gen.limit(o.get("ops_per_key", 60), one)

    w["generator"] = independent.concurrent_generator(
        o.get("group_size", 4), keys, key_gen)
    return _with_client(w, RegisterClient())


def set_workload(opts):
    return _with_client(sets_wl.workload(opts), SetClient())


def sequential(opts):
    o = dict(opts or {})
    return _with_client(
        seq_wl.workload(o),
        SequentialClient(key_count=o.get("key-count", 5)))


def bank(opts):
    return _with_client(bank_wl.workload(opts), BankClient())


def long_fork(opts):
    return _with_client(lf_wl.workload(opts), TxnClient())


def wr(opts):
    return _with_client(wr_wl.workload(opts), TxnClient())


WORKLOADS = {
    "upsert": upsert,
    "delete": delete,
    "linearizable-register": linearizable_register,
    "set": set_workload,
    "sequential": sequential,
    "bank": bank,
    "long-fork": long_fork,
    "wr": wr,
}


def nemesis_for(opts: dict, db) -> dict:
    from ..nemesis import combined

    faults = set(opts.get("faults") or ("partition", "kill"))
    o = dict(opts)
    o.update(db=db, faults=faults,
             interval=opts.get("nemesis_interval", 15))
    return combined.compose_packages(combined.nemesis_packages(o))


def dgraph_test(opts: dict) -> dict:
    name = opts.get("workload") or "upsert"
    w = WORKLOADS[name](opts)
    db = DgraphDB(version=opts.get("version", VERSION),
                  replicas=opts.get("replicas", 3))
    pkg = nemesis_for(opts, db)
    client = w["client"]
    if opts.get("trace"):
        client = TraceClient(client)
    test = testing.noop_test()
    test.update(
        name=f"dgraph-{name}",
        os=debian.os,
        db=db,
        ssh=opts["ssh"],
        nodes=opts["nodes"],
        concurrency=opts["concurrency"],
        client=client,
        nemesis=pkg["nemesis"],
        checker=chk.compose({"workload": w["checker"],
                             "stats": chk.stats(),
                             "perf": chk.perf(),
                             "timeline": chk.timeline()}),
        generator=_suite_generator(opts, w, pkg))
    for extra in ("total-amount", "accounts"):
        if extra in w:
            test[extra] = w[extra]
    return test


def _suite_generator(opts, w, pkg):
    nemesis_gen = pkg.get("generator")
    client_part = gen.stagger(1.0 / opts.get("rate", 15),
                              w["generator"])
    mix = gen.time_limit(
        opts.get("time_limit", 60),
        gen.clients(client_part, nemesis_gen)
        if nemesis_gen is not None else gen.clients(client_part))
    parts = [mix]
    final = w.get("final_generator")
    if final is not None:
        parts.append(gen.sleep(opts.get("recovery_time", 10)))
        parts.append(gen.clients(final))
    return parts[0] if len(parts) == 1 else gen.phases(*parts)


def _opts(p):
    p.add_argument("--workload", default=None,
                   help="Workload (default upsert). "
                        + cli.one_of(WORKLOADS))
    p.add_argument("--rate", type=float, default=15)
    p.add_argument("--version", default=VERSION)
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--trace", action="store_true",
                   help="per-op trace spans to store/trace.jsonl")
    return p


def main(argv=None) -> None:
    commands = {}
    commands.update(cli.single_test_cmd(dgraph_test, parser_fn=_opts))
    commands.update(cli.serve_cmd())
    cli.run_cli(commands, argv)


if __name__ == "__main__":
    main()
