"""etcd test suite: install/start/stop etcd, drive it over its HTTP v3
gateway, check registers (linearizable) and list-append (elle).

Capability reference: the reference's canonical tutorial suite
(doc/tutorial/index.md:13-20; DB install/daemon flow in
doc/tutorial/02-db.md: /opt/etcd install-archive + start-stop-daemon
with --initial-cluster flags; client and checker shape in 03-client.md,
04-checker.md; zookeeper/src/jepsen/zookeeper.clj is the size model).

Run clusterless against the dummy remote in CI (command emission is
tested), or for real: python -m jepsen_tpu.suites.etcd test
--nodes ... --username root.
"""

from __future__ import annotations

import base64
import json
import logging
import random
import urllib.request

from .. import checker as chk
from .. import cli, client as jclient, control, db as jdb, independent
from .. import generator as gen
from .. import nemesis as jnemesis
from .. import testing, workloads
from ..nemesis import membership
from ..checker import models
from ..control import util as cu
from ..os_setup import debian

logger = logging.getLogger(__name__)

VERSION = "v3.5.15"
DIR = "/opt/etcd"
BINARY = f"{DIR}/etcd"
LOGFILE = f"{DIR}/etcd.log"
PIDFILE = f"{DIR}/etcd.pid"

CLIENT_PORT = 2379
PEER_PORT = 2380


def node_url(node, port) -> str:
    return f"http://{node}:{port}"


def peer_url(node) -> str:
    return node_url(node, PEER_PORT)


def client_url(node) -> str:
    return node_url(node, CLIENT_PORT)


def initial_cluster(test) -> str:
    """node1=http://node1:2380,... (tutorial 02-db.md
    initial-cluster)."""
    return ",".join(f"{n}={peer_url(n)}" for n in test["nodes"])


class EtcdDB(jdb.DB):
    """Installs and runs an etcd node (tutorial 02-db.md)."""

    supports_kill = True
    supports_pause = True

    def __init__(self, version: str = VERSION):
        self.version = version

    def _daemon_args(self, test, node, cluster_state: str,
                     cluster: str | None = None):
        """One flag list for every start path; restarts say
        'existing' (a fresh 'new' after kill was a bootstrap bug the
        round-2 advisor flagged), and membership joins pass the
        current cluster string."""
        return (
            {"logfile": LOGFILE, "pidfile": PIDFILE, "chdir": DIR},
            BINARY,
            "--log-outputs", "stderr",
            "--name", str(node),
            "--listen-peer-urls", peer_url(node),
            "--listen-client-urls", f"http://0.0.0.0:{CLIENT_PORT}",
            "--advertise-client-urls", client_url(node),
            "--initial-cluster-state", cluster_state,
            "--initial-advertise-peer-urls", peer_url(node),
            "--initial-cluster", cluster or initial_cluster(test))

    def setup(self, test, node):
        logger.info("%s installing etcd %s", node, self.version)
        with control.su():
            url = (f"https://storage.googleapis.com/etcd/{self.version}"
                   f"/etcd-{self.version}-linux-amd64.tar.gz")
            cu.install_archive(url, DIR)
            cu.start_daemon(*self._daemon_args(test, node, "new"))
        cu.await_tcp_port(CLIENT_PORT, timeout_secs=60)

    def teardown(self, test, node):
        logger.info("%s tearing down etcd", node)
        with control.su():
            cu.stop_daemon(BINARY, PIDFILE)
            control.exec_("rm", "-rf", DIR)

    def kill(self, test, node):
        with control.su():
            cu.grepkill("etcd")
        return "killed"

    def start(self, test, node):
        self.setup_daemon_only(test, node, cluster_state="existing")
        return "started"

    def setup_daemon_only(self, test, node, cluster_state: str = "new",
                          cluster: str | None = None):
        with control.su():
            cu.start_daemon(*self._daemon_args(test, node,
                                               cluster_state, cluster))

    def pause(self, test, node):
        with control.su():
            cu.grepkill("etcd", "stop")
        return "paused"

    def resume(self, test, node):
        with control.su():
            cu.grepkill("etcd", "cont")
        return "resumed"

    def log_files(self, test, node):
        return [LOGFILE]


# ---------------------------------------------------------------------------
# Client over the v3 HTTP/JSON gateway
# ---------------------------------------------------------------------------

def _b64(s: str) -> str:
    return base64.b64encode(s.encode()).decode()


def _unb64(s: str) -> str:
    return base64.b64decode(s).decode()


class EtcdHttp:
    """Minimal etcd v3 JSON-gateway driver (kv/range, kv/put, kv/txn).
    Split out so tests can stub `post`."""

    def __init__(self, node, timeout: float = 5.0):
        self.base = client_url(node)
        self.timeout = timeout

    def post(self, path: str, body: dict) -> dict:
        req = urllib.request.Request(
            self.base + path, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read().decode())

    def get(self, key: str):
        """(value, mod_revision) or (None, None)."""
        out = self.post("/v3/kv/range", {"key": _b64(key)})
        kvs = out.get("kvs") or []
        if not kvs:
            return None, None
        return (_unb64(kvs[0].get("value", "")),
                int(kvs[0].get("mod_revision", 0)))

    def put(self, key: str, value: str) -> None:
        self.post("/v3/kv/put", {"key": _b64(key), "value": _b64(value)})

    def cas(self, key: str, old: str, new: str) -> bool:
        """Atomic value-equality compare-and-set via kv/txn."""
        out = self.post("/v3/kv/txn", {
            "compare": [{"key": _b64(key), "target": "VALUE",
                         "value": _b64(old), "result": "EQUAL"}],
            "success": [{"requestPut": {"key": _b64(key),
                                        "value": _b64(new)}}]})
        return bool(out.get("succeeded"))

    def cas_create(self, key: str, new: str) -> bool:
        """Create iff absent (create_revision == 0)."""
        out = self.post("/v3/kv/txn", {
            "compare": [{"key": _b64(key), "target": "CREATE",
                         "create_revision": "0"}],
            "success": [{"requestPut": {"key": _b64(key),
                                        "value": _b64(new)}}]})
        return bool(out.get("succeeded"))

    def txn_rw(self, guards, puts) -> bool:
        """One atomic kv/txn: every (key, mod_revision) guard must
        still hold, then all (key, value) puts apply. Missing keys
        guard with revision 0."""
        out = self.post("/v3/kv/txn", {
            "compare": [{"key": _b64(k), "target": "MOD",
                         "mod_revision": str(rev or 0),
                         "result": "EQUAL"} for k, rev in guards],
            "success": [{"requestPut": {"key": _b64(k),
                                        "value": _b64(v)}}
                        for k, v in puts]})
        return bool(out.get("succeeded"))

    # -- cluster membership (v3/cluster gateway) --------------------------

    def members(self) -> list[dict]:
        out = self.post("/v3/cluster/member/list", {})
        return out.get("members") or []

    def member_add(self, peer: str) -> dict:
        return self.post("/v3/cluster/member/add", {"peerURLs": [peer]})

    def member_remove(self, member_id) -> dict:
        return self.post("/v3/cluster/member/remove",
                         {"ID": member_id})


_definite = jclient.definite_http_failure


class EtcdRegisterClient(jclient.Client):
    """Per-key register ops (read/write/cas) over independent-key
    tuples (tutorial 03-client.md)."""

    def __init__(self, http_factory=EtcdHttp):
        self.http_factory = http_factory
        self.http = None

    def open(self, test, node):
        c = EtcdRegisterClient(self.http_factory)
        c.http = self.http_factory(node)
        return c

    def invoke(self, test, op):
        k, v = independent.key_(op.value), independent.value_(op.value)
        key = f"/register/{k}"
        try:
            if op.f == "read":
                val, _ = self.http.get(key)
                val = None if val is None else int(val)
                return op.copy(type="ok",
                               value=independent.ktuple(k, val))
            if op.f == "write":
                self.http.put(key, str(v))
                return op.copy(type="ok")
            if op.f == "cas":
                old, new = v
                ok = self.http.cas(key, str(old), str(new))
                return op.copy(type="ok" if ok else "fail")
            raise ValueError(f"unknown f {op.f!r}")
        except Exception as e:  # noqa: BLE001
            if _definite(e):
                return op.copy(type="fail", error=repr(e))
            return op.copy(type="info", error=repr(e))


class EtcdAppendClient(jclient.Client):
    """Elle list-append transactions, executed ATOMICALLY: snapshot
    reads of every touched key, then one kv/txn guarded on all their
    mod_revisions applying every append — so the recorded txn really is
    one serializable unit and the checker can't flag healthy etcd for
    interleavings between micro-ops (round-2 advisor finding). Guard
    conflicts retry with a fresh snapshot."""

    def __init__(self, http_factory=EtcdHttp, retries: int = 8):
        self.http_factory = http_factory
        self.retries = retries
        self.http = None

    def open(self, test, node):
        c = EtcdAppendClient(self.http_factory, self.retries)
        c.http = self.http_factory(node)
        return c

    def _attempt(self, mops):
        keys = {k for _f, k, _v in mops}
        snap = {k: self.http.get(f"/append/{k}") for k in keys}
        lists = {k: (json.loads(v) if v else [])
                 for k, (v, _r) in snap.items()}
        seen_empty = {k for k, (v, _r) in snap.items() if v is None}
        out = []
        dirty = set()
        for f, k, v in mops:
            if f == "r":
                cur = lists[k]
                out.append(["r", k,
                            None if (k in seen_empty and k not in dirty
                                     and not cur) else list(cur)])
            else:
                lists[k].append(v)
                dirty.add(k)
                out.append(["append", k, v])
        guards = [(f"/append/{k}", snap[k][1]) for k in sorted(keys)]
        puts = [(f"/append/{k}", json.dumps(lists[k]))
                for k in sorted(dirty)]
        if not puts and len(keys) <= 1:
            return out  # a single-key read is atomic by itself
        if self.http.txn_rw(guards, puts):
            return out
        return None

    def invoke(self, test, op):
        try:
            for _ in range(self.retries):
                out = self._attempt(op.value)
                if out is not None:
                    return op.copy(type="ok", value=out)
            # every attempt's guard failed BEFORE any put applied:
            # provably nothing committed, so this is a definite :fail
            return op.copy(type="fail",
                           error="txn contention exhausted retries")
        except Exception as e:  # noqa: BLE001
            if _definite(e):
                return op.copy(type="fail", error=repr(e))
            return op.copy(type="info", error=repr(e))


# ---------------------------------------------------------------------------
# Membership
# ---------------------------------------------------------------------------

class EtcdMembership(membership.MembershipState):
    """Join/remove etcd members through the v3 cluster gateway
    (exercises nemesis/membership.clj's state-machine shape against a
    real member API). Views are frozensets of member names; a name->id
    map is kept for removals."""

    def __init__(self, http_factory=EtcdHttp, db: EtcdDB | None = None,
                 seed=None):
        super().__init__()
        self.http_factory = http_factory
        self.db = db
        self.member_ids: dict = {}
        self.rng = random.Random(seed)

    def node_view(self, test, node):
        try:
            members = self.http_factory(node).members()
        except Exception:  # noqa: BLE001 — node down: view unknown
            return None
        names = set()
        for m in members:
            name = m.get("name") or f"id:{m.get('ID')}"
            names.add(name)
            if m.get("ID") is not None:
                self.member_ids[name] = m["ID"]
        return frozenset(names)

    def merge_views(self, test):
        """Majority view wins; ties go to the largest view (prefer
        believing a node exists over not)."""
        views = list(self.node_views.values())
        if not views:
            return None
        counts: dict = {}
        for v in views:
            counts[v] = counts.get(v, 0) + 1
        return max(counts, key=lambda v: (counts[v], len(v)))

    def fs(self):
        return {"add-member", "remove-member"}

    def op(self, test):
        from .. import generator as gen

        if self.view is None or self.pending:
            return gen.PENDING
        nodes = set(map(str, test.get("nodes", ())))
        active = set(self.view) & nodes
        removed = nodes - set(self.view)
        # shrink while strictly above the majority floor, then grow
        # back — never create a quorum-less (useless) cluster state
        # (membership.clj principle 1). Random targets so churn
        # covers every node over a run, not one fixed victim.
        if active and len(active) > (len(nodes) // 2) + 1:
            return {"type": "info", "f": "remove-member",
                    "value": self.rng.choice(sorted(active))}
        if removed:
            return {"type": "info", "f": "add-member",
                    "value": self.rng.choice(sorted(removed))}
        return gen.PENDING

    def _any_http(self, test, exclude=None):
        for n_ in test.get("nodes", ()):
            if str(n_) != exclude and str(n_) in (self.view or ()):
                return self.http_factory(n_)
        return self.http_factory(test["nodes"][0])

    def invoke(self, test, op):
        target = op.value
        try:
            if op.f == "remove-member":
                mid = self.member_ids.get(target)
                if mid is None:
                    return op.copy(value=[target, "unknown-member"])
                self._any_http(test, exclude=target).member_remove(mid)
                return op.copy(value=[target, "removed"])
            if op.f == "add-member":
                self._any_http(test).member_add(peer_url(target))
                if self.db is not None:
                    cluster = ",".join(
                        f"{m}={peer_url(m)}"
                        for m in sorted(set(self.view) | {target}))
                    with control.with_session(test, target):
                        with control.su():
                            # a removed member's stale data dir makes
                            # etcd restart with its old (permanently
                            # removed) identity and get rejected by
                            # peers; rejoin must start clean
                            control.exec_("rm", "-rf",
                                          f"{DIR}/{target}.etcd")
                        self.db.setup_daemon_only(
                            test, target, cluster_state="existing",
                            cluster=cluster)
                return op.copy(value=[target, "added"])
            raise ValueError(f"unknown membership f {op.f!r}")
        except Exception as e:  # noqa: BLE001
            return op.copy(value=[target, f"error: {e!r}"])

    def resolve_op(self, test, pair):
        _inv, done = pair
        d = dict(done)
        f, val = d.get("f"), d.get("value")
        if not isinstance(val, tuple) or len(val) != 2:
            return True  # malformed/errored: nothing to wait for
        target, status = val
        if isinstance(status, str) and status.startswith("error"):
            return True
        if self.view is None:
            return False
        if f == "remove-member":
            return target not in self.view
        if f == "add-member":
            return target in self.view
        return True


def membership_package(opts: dict) -> dict | None:
    """An etcd membership package for nemesis composition. Without an
    explicit membership db, the test's db is used so re-added members
    actually get their daemon started (a voting member added via the
    API but never started would hold the nemesis pending forever and
    put quorum one failure away)."""
    o = dict(opts)
    mopts = dict(o.get("membership") or {})
    mopts.setdefault("state", EtcdMembership(
        http_factory=mopts.pop("http_factory", EtcdHttp),
        db=mopts.pop("db", o.get("db")),
        seed=mopts.pop("seed", None)))
    o["membership"] = mopts
    return membership.package(o)


# ---------------------------------------------------------------------------
# Tests
# ---------------------------------------------------------------------------

def r(rng):
    return {"f": "read", "value": None}


def w(rng):
    return {"f": "write", "value": rng.randrange(5)}


def cas(rng):
    return {"f": "cas", "value": [rng.randrange(5), rng.randrange(5)]}


def register_workload(opts: dict) -> dict:
    rng = random.Random(opts.get("seed"))
    keys = list(range(opts.get("keys", 4)))
    return {
        "client": EtcdRegisterClient(),
        "generator": independent.concurrent_generator(
            opts["concurrency"], keys,
            lambda k: gen.limit(opts.get("ops_per_key", 200),
                                lambda: rng.choice([r, w, cas])(rng))),
        "checker": independent.checker(chk.linearizable(
            {"model": models.cas_register()})),
    }


def append_workload(opts: dict) -> dict:
    w = workloads.txn_append.workload(
        {"ops": opts.get("ops", 1000), "seed": opts.get("seed")})
    w["client"] = EtcdAppendClient()
    return w


WORKLOADS = {"register": register_workload, "append": append_workload}


def nemesis_for(opts: dict, db) -> dict:
    """A composed nemesis package from --nemesis faults (the
    reference's suites expose nemesis menus the same way,
    combined.clj nemesis-package). Membership wires through
    membership_package so its http_factory/seed/db sub-options apply;
    an empty fault set gives the classic partitioner schedule. Never
    mutates the caller's opts (a test-count sweep re-invokes the test
    fn with the same dict, and a reused membership state machine would
    carry the previous cluster's view)."""
    from ..nemesis import combined

    faults = set(opts.get("faults") or ())
    if not faults:
        return {"nemesis": jnemesis.partition_random_halves(),
                "generator": jnemesis.start_stop_cycle(5.0),
                "final_generator": None}
    o = dict(opts)
    o["membership"] = dict(opts.get("membership") or {})
    o.update(db=db, interval=opts.get("nemesis_interval", 10))
    pkgs = combined.nemesis_packages(
        {**o, "faults": faults - {"membership"}})
    if "membership" in faults:
        mp = membership_package({**o, "faults": {"membership"}})
        if mp is not None:
            pkgs.append(mp)
    return combined.compose_packages(pkgs)


def etcd_test(opts: dict) -> dict:
    """Constructs an etcd test map from CLI options (the tutorial's
    etcd-test / zookeeper.clj zk-test shape). opts["faults"] selects
    the nemesis menu (partition/packet/kill/pause/clock/
    file-corruption/membership); empty = classic partitioner."""
    name = opts.get("workload") or "register"
    w = WORKLOADS[name](opts)
    db = EtcdDB(opts.get("version", VERSION))
    pkg = nemesis_for(opts, db)
    test = testing.noop_test()
    test.update(
        name=f"etcd-{name}",
        os=debian.os,
        db=db,
        ssh=opts["ssh"],
        nodes=opts["nodes"],
        concurrency=opts["concurrency"],
        client=w["client"],
        nemesis=pkg["nemesis"],
        checker=chk.compose({"workload": w["checker"],
                             "stats": chk.stats(),
                             "perf": chk.perf(),
                             "timeline": chk.timeline()}),
        generator=_suite_generator(opts, w["generator"], pkg))
    return test


def _suite_generator(opts, client_gen, pkg):
    """time-limit bounds client AND nemesis streams together (an
    unbounded nemesis cycle would keep the run alive forever); the
    package's final generator runs AFTER the limit so faults heal
    before teardown (combined.clj final-generator)."""
    client_part = gen.stagger(1.0 / opts.get("rate", 50), client_gen)
    nemesis_gen = pkg.get("generator")
    main = gen.time_limit(
        opts.get("time_limit", 30),
        gen.clients(client_part, nemesis_gen)
        if nemesis_gen is not None else gen.clients(client_part))
    final = pkg.get("final_generator")
    if final:
        return gen.phases(main, gen.nemesis(final))
    return main


def _workload_opt(p):
    p.add_argument("--workload", default=None,
                   help="Workload (default register; test-all sweeps "
                        "all when omitted). " + cli.one_of(WORKLOADS))
    p.add_argument("--version", default=VERSION,
                   help="etcd version tag to install.")
    p.add_argument("--rate", type=float, default=50)
    p.add_argument("--nemesis", dest="faults", default=None,
                   help="Comma-separated faults: partition,packet,"
                        "kill,pause,clock,file-corruption,membership. "
                        "Default: the classic partitioner schedule.")
    return p


def _opt_fn(opts: dict) -> dict:
    """single_test_cmd hands opt_fn the already-normalized opts dict
    (calling test_opt_fn again here was a TypeError — the --nemesis
    flag never worked from the real CLI)."""
    if opts.get("faults"):
        opts["faults"] = [f.strip()
                          for f in opts["faults"].split(",")
                          if f.strip()]
    return opts


FAULT_OPTIONS = ([], ["partition"], ["kill"], ["pause"], ["clock"],
                 ["partition", "kill"], ["membership"])


def all_tests(opts: dict):
    """The workload x fault sweep for test-all (the canonical suite
    shape: tidb/src/tidb/core.clj:47-60 workload-options). --workload
    and --nemesis narrow the matrix to the given values, and each
    combination repeats --test-count times, like the reference."""
    workloads = ([opts["workload"]] if opts.get("workload")
                 else sorted(WORKLOADS))
    fault_options = ([opts["faults"]] if opts.get("faults") is not None
                     else FAULT_OPTIONS)
    for _ in range(opts.get("test_count") or 1):
        for wname in workloads:
            for faults in fault_options:
                yield etcd_test({**opts, "workload": wname,
                                 "faults": list(faults)})


def main(argv=None) -> None:
    commands = {}
    commands.update(cli.single_test_cmd(etcd_test,
                                        parser_fn=_workload_opt,
                                        opt_fn=_opt_fn))
    commands.update(cli.test_all_cmd(all_tests,
                                     parser_fn=_workload_opt,
                                     opt_fn=_opt_fn))
    commands.update(cli.serve_cmd())
    cli.run_cli(commands, argv)


if __name__ == "__main__":
    main()
