"""etcd test suite: install/start/stop etcd, drive it over its HTTP v3
gateway, check registers (linearizable) and list-append (elle).

Capability reference: the reference's canonical tutorial suite
(doc/tutorial/index.md:13-20; DB install/daemon flow in
doc/tutorial/02-db.md: /opt/etcd install-archive + start-stop-daemon
with --initial-cluster flags; client and checker shape in 03-client.md,
04-checker.md; zookeeper/src/jepsen/zookeeper.clj is the size model).

Run clusterless against the dummy remote in CI (command emission is
tested), or for real: python -m jepsen_tpu.suites.etcd test
--nodes ... --username root.
"""

from __future__ import annotations

import base64
import json
import logging
import random
import urllib.error
import urllib.request

from .. import checker as chk
from .. import cli, client as jclient, control, db as jdb, independent
from .. import generator as gen
from .. import nemesis as jnemesis
from .. import testing, workloads
from ..checker import models
from ..control import util as cu
from ..os_setup import debian

logger = logging.getLogger(__name__)

VERSION = "v3.5.15"
DIR = "/opt/etcd"
BINARY = f"{DIR}/etcd"
LOGFILE = f"{DIR}/etcd.log"
PIDFILE = f"{DIR}/etcd.pid"

CLIENT_PORT = 2379
PEER_PORT = 2380


def node_url(node, port) -> str:
    return f"http://{node}:{port}"


def peer_url(node) -> str:
    return node_url(node, PEER_PORT)


def client_url(node) -> str:
    return node_url(node, CLIENT_PORT)


def initial_cluster(test) -> str:
    """node1=http://node1:2380,... (tutorial 02-db.md
    initial-cluster)."""
    return ",".join(f"{n}={peer_url(n)}" for n in test["nodes"])


class EtcdDB(jdb.DB):
    """Installs and runs an etcd node (tutorial 02-db.md)."""

    supports_kill = True
    supports_pause = True

    def __init__(self, version: str = VERSION):
        self.version = version

    def setup(self, test, node):
        logger.info("%s installing etcd %s", node, self.version)
        with control.su():
            url = (f"https://storage.googleapis.com/etcd/{self.version}"
                   f"/etcd-{self.version}-linux-amd64.tar.gz")
            cu.install_archive(url, DIR)
            cu.start_daemon(
                {"logfile": LOGFILE, "pidfile": PIDFILE, "chdir": DIR},
                BINARY,
                "--log-outputs", "stderr",
                "--name", str(node),
                "--listen-peer-urls", peer_url(node),
                "--listen-client-urls", f"http://0.0.0.0:{CLIENT_PORT}",
                "--advertise-client-urls", client_url(node),
                "--initial-cluster-state", "new",
                "--initial-advertise-peer-urls", peer_url(node),
                "--initial-cluster", initial_cluster(test))
        cu.await_tcp_port(CLIENT_PORT, timeout_secs=60)

    def teardown(self, test, node):
        logger.info("%s tearing down etcd", node)
        with control.su():
            cu.stop_daemon(BINARY, PIDFILE)
            control.exec_("rm", "-rf", DIR)

    def kill(self, test, node):
        with control.su():
            cu.grepkill("etcd")
        return "killed"

    def start(self, test, node):
        self.setup_daemon_only(test, node)
        return "started"

    def setup_daemon_only(self, test, node):
        with control.su():
            cu.start_daemon(
                {"logfile": LOGFILE, "pidfile": PIDFILE, "chdir": DIR},
                BINARY,
                "--log-outputs", "stderr",
                "--name", str(node),
                "--listen-peer-urls", peer_url(node),
                "--listen-client-urls", f"http://0.0.0.0:{CLIENT_PORT}",
                "--advertise-client-urls", client_url(node),
                "--initial-cluster-state", "new",
                "--initial-advertise-peer-urls", peer_url(node),
                "--initial-cluster", initial_cluster(test))

    def pause(self, test, node):
        with control.su():
            cu.grepkill("etcd", "stop")
        return "paused"

    def resume(self, test, node):
        with control.su():
            cu.grepkill("etcd", "cont")
        return "resumed"

    def log_files(self, test, node):
        return [LOGFILE]


# ---------------------------------------------------------------------------
# Client over the v3 HTTP/JSON gateway
# ---------------------------------------------------------------------------

def _b64(s: str) -> str:
    return base64.b64encode(s.encode()).decode()


def _unb64(s: str) -> str:
    return base64.b64decode(s).decode()


class EtcdHttp:
    """Minimal etcd v3 JSON-gateway driver (kv/range, kv/put, kv/txn).
    Split out so tests can stub `post`."""

    def __init__(self, node, timeout: float = 5.0):
        self.base = client_url(node)
        self.timeout = timeout

    def post(self, path: str, body: dict) -> dict:
        req = urllib.request.Request(
            self.base + path, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read().decode())

    def get(self, key: str):
        """(value, mod_revision) or (None, None)."""
        out = self.post("/v3/kv/range", {"key": _b64(key)})
        kvs = out.get("kvs") or []
        if not kvs:
            return None, None
        return (_unb64(kvs[0].get("value", "")),
                int(kvs[0].get("mod_revision", 0)))

    def put(self, key: str, value: str) -> None:
        self.post("/v3/kv/put", {"key": _b64(key), "value": _b64(value)})

    def cas(self, key: str, old: str, new: str) -> bool:
        """Atomic value-equality compare-and-set via kv/txn."""
        out = self.post("/v3/kv/txn", {
            "compare": [{"key": _b64(key), "target": "VALUE",
                         "value": _b64(old), "result": "EQUAL"}],
            "success": [{"requestPut": {"key": _b64(key),
                                        "value": _b64(new)}}]})
        return bool(out.get("succeeded"))

    def cas_create(self, key: str, new: str) -> bool:
        """Create iff absent (create_revision == 0)."""
        out = self.post("/v3/kv/txn", {
            "compare": [{"key": _b64(key), "target": "CREATE",
                         "create_revision": "0"}],
            "success": [{"requestPut": {"key": _b64(key),
                                        "value": _b64(new)}}]})
        return bool(out.get("succeeded"))


def _definite(e: Exception) -> bool:
    """True when the request certainly never executed (safe to :fail);
    timeouts and other errors are indeterminate (:info)."""
    if isinstance(e, urllib.error.URLError):
        reason = getattr(e, "reason", None)
        return isinstance(reason, ConnectionRefusedError)
    return isinstance(e, ConnectionRefusedError)


class EtcdRegisterClient(jclient.Client):
    """Per-key register ops (read/write/cas) over independent-key
    tuples (tutorial 03-client.md)."""

    def __init__(self, http_factory=EtcdHttp):
        self.http_factory = http_factory
        self.http = None

    def open(self, test, node):
        c = EtcdRegisterClient(self.http_factory)
        c.http = self.http_factory(node)
        return c

    def invoke(self, test, op):
        k, v = independent.key_(op.value), independent.value_(op.value)
        key = f"/register/{k}"
        try:
            if op.f == "read":
                val, _ = self.http.get(key)
                val = None if val is None else int(val)
                return op.copy(type="ok",
                               value=independent.ktuple(k, val))
            if op.f == "write":
                self.http.put(key, str(v))
                return op.copy(type="ok")
            if op.f == "cas":
                old, new = v
                ok = self.http.cas(key, str(old), str(new))
                return op.copy(type="ok" if ok else "fail")
            raise ValueError(f"unknown f {op.f!r}")
        except Exception as e:  # noqa: BLE001
            if _definite(e):
                return op.copy(type="fail", error=repr(e))
            return op.copy(type="info", error=repr(e))


class EtcdAppendClient(jclient.Client):
    """Elle list-append transactions: each [f k v] micro-op reads or
    appends to a JSON list under /append/<k>, appends via
    mod-revision-guarded txns retried a few times."""

    def __init__(self, http_factory=EtcdHttp, retries: int = 8):
        self.http_factory = http_factory
        self.retries = retries
        self.http = None

    def open(self, test, node):
        c = EtcdAppendClient(self.http_factory, self.retries)
        c.http = self.http_factory(node)
        return c

    def _append(self, key: str, v) -> None:
        for _ in range(self.retries):
            cur, _rev = self.http.get(key)
            if cur is None:
                if self.http.cas_create(key, json.dumps([v])):
                    return
                continue
            lst = json.loads(cur)
            if self.http.cas(key, cur, json.dumps(lst + [v])):
                return
        raise RuntimeError(f"append contention on {key}")

    def invoke(self, test, op):
        try:
            out = []
            for f, k, v in op.value:
                key = f"/append/{k}"
                if f == "r":
                    cur, _ = self.http.get(key)
                    out.append(
                        ["r", k, json.loads(cur) if cur else None])
                else:
                    self._append(key, v)
                    out.append(["append", k, v])
            return op.copy(type="ok", value=out)
        except Exception as e:  # noqa: BLE001
            if _definite(e):
                return op.copy(type="fail", error=repr(e))
            return op.copy(type="info", error=repr(e))


# ---------------------------------------------------------------------------
# Tests
# ---------------------------------------------------------------------------

def r(rng):
    return {"f": "read", "value": None}


def w(rng):
    return {"f": "write", "value": rng.randrange(5)}


def cas(rng):
    return {"f": "cas", "value": [rng.randrange(5), rng.randrange(5)]}


def register_workload(opts: dict) -> dict:
    rng = random.Random(opts.get("seed"))
    keys = list(range(opts.get("keys", 4)))
    return {
        "client": EtcdRegisterClient(),
        "generator": independent.concurrent_generator(
            opts["concurrency"], keys,
            lambda k: gen.limit(opts.get("ops_per_key", 200),
                                lambda: rng.choice([r, w, cas])(rng))),
        "checker": independent.checker(chk.linearizable(
            {"model": models.cas_register()})),
    }


def append_workload(opts: dict) -> dict:
    w = workloads.txn_append.workload(
        {"ops": opts.get("ops", 1000), "seed": opts.get("seed")})
    w["client"] = EtcdAppendClient()
    return w


WORKLOADS = {"register": register_workload, "append": append_workload}


def etcd_test(opts: dict) -> dict:
    """Constructs an etcd test map from CLI options (the tutorial's
    etcd-test / zookeeper.clj zk-test shape)."""
    name = opts.get("workload", "register")
    w = WORKLOADS[name](opts)
    test = testing.noop_test()
    test.update(
        name=f"etcd-{name}",
        os=debian.os,
        db=EtcdDB(opts.get("version", VERSION)),
        ssh=opts["ssh"],
        nodes=opts["nodes"],
        concurrency=opts["concurrency"],
        client=w["client"],
        nemesis=jnemesis.partition_random_halves(),
        checker=chk.compose({"workload": w["checker"],
                             "stats": chk.stats(),
                             "perf": chk.perf(),
                             "timeline": chk.timeline()}),
        generator=gen.clients(
            gen.time_limit(
                opts.get("time_limit", 30),
                gen.stagger(1.0 / opts.get("rate", 50),
                            w["generator"])),
            gen.cycle(gen.phases(gen.sleep(5),
                                 {"type": "info", "f": "start"},
                                 gen.sleep(5),
                                 {"type": "info", "f": "stop"}))))
    return test


def _workload_opt(p):
    p.add_argument("--workload", default="register",
                   help="Workload. " + cli.one_of(WORKLOADS))
    p.add_argument("--version", default=VERSION,
                   help="etcd version tag to install.")
    p.add_argument("--rate", type=float, default=50)
    return p


def main(argv=None) -> None:
    commands = {}
    commands.update(cli.single_test_cmd(etcd_test,
                                        parser_fn=_workload_opt))
    commands.update(cli.serve_cmd())
    cli.run_cli(commands, argv)


if __name__ == "__main__":
    main()
