"""PostgreSQL test suite: serializable list-append (elle) and bank
transfer workloads against a single postgres instance, driven through
`psql` on the client nodes.

Capability reference: stolon/src/jepsen/stolon/append.clj (table-per-
key-hash layout, INSERT .. ON CONFLICT append, per-txn isolation,
could-not-serialize/deadlock -> :fail mapping), stolon/client.clj
(with-errors classification), stolon/ledger.clj + tests/bank.clj
(transfer/read over an accounts table), and postgres-rds (the
single-endpoint topology: every client talks to one postgres server —
here the primary node — the way the reference's clients all talk to
one RDS endpoint). The reference links a JDBC driver into the JVM;
here ops go through `psql -c` on the client's own node over the
control plane, so the suite needs no SQL driver on the control host
(the same transport stance as the zookeeper suite's zkCli).
"""

from __future__ import annotations

import logging
import random
import re

from .. import checker as chk
from .. import cli, client as jclient, control, db as jdb
from .. import generator as gen
from .. import nemesis as jnemesis
from .. import testing, workloads
from ..control.core import Lit, RemoteError
from ..core import primary
from ..os_setup import debian

logger = logging.getLogger(__name__)

USER = "jepsen"
DBNAME = "jepsen"
PORT = 5432
TABLE_COUNT = 3
LOG_DIR = "/var/log/postgresql"


def table_for(k) -> str:
    """txn<i> table for a key (stolon/append.clj table-for)."""
    return f"txn{int(k) % TABLE_COUNT}"


class PostgresDB(jdb.DB):
    """apt-installed postgres on the primary, psql client everywhere
    (stolon runs its own keeper/sentinel topology; the plain-postgres
    analog is one server + thin clients)."""

    def __init__(self, accounts=8, initial_balance=10):
        self.accounts = accounts
        self.initial_balance = initial_balance

    def _sql(self, sql: str) -> str:
        """Runs sql locally as the postgres superuser."""
        with control.su("postgres"):
            return control.exec_("psql", "-X", "-q", "-A", "-t",
                                 "-v", "ON_ERROR_STOP=1", "-c", sql)

    def setup(self, test, node):
        if node != primary(test):
            logger.info("%s installing psql client", node)
            with control.su():
                debian.install(["postgresql-client"])
            return
        logger.info("%s installing postgres server", node)
        with control.su():
            debian.install(["postgresql"])
            control.exec_("service", "postgresql", "start",
                          check=False)
        # Reachable from the other nodes: listen on all interfaces,
        # trust the test network (the reference configures hba/ssl via
        # stolon's cluster spec, stolon/db.clj)
        self._sql("ALTER SYSTEM SET listen_addresses = '*'")
        hba = self._sql("SHOW hba_file").strip()
        if hba:
            with control.su():
                control.exec_(
                    "sh", "-c",
                    f"echo 'host all {USER} 0.0.0.0/0 trust' >> {hba}")
        with control.su():
            control.exec_("service", "postgresql", "restart")
        self._sql(f"DROP DATABASE IF EXISTS {DBNAME}")
        self._sql(f"DROP ROLE IF EXISTS {USER}")
        self._sql(f"CREATE ROLE {USER} LOGIN")
        self._sql(f"CREATE DATABASE {DBNAME} OWNER {USER}")
        # Tables: append tables + the bank ledger with its invariant
        # enforced in-database (negative balances abort the txn)
        ddl = []
        for i in range(TABLE_COUNT):
            ddl.append(f"CREATE TABLE txn{i} ("
                       f"id int NOT NULL PRIMARY KEY, val text)")
        ddl.append("CREATE TABLE accounts ("
                   "id int NOT NULL PRIMARY KEY, "
                   "balance int NOT NULL CHECK (balance >= 0))")
        for i in range(self.accounts):
            ddl.append(f"INSERT INTO accounts VALUES "
                       f"({i}, {self.initial_balance})")
        for stmt in ddl:
            with control.su("postgres"):
                control.exec_("psql", "-X", "-q", "-d", DBNAME,
                              "-v", "ON_ERROR_STOP=1", "-c", stmt)
        with control.su("postgres"):
            control.exec_("psql", "-X", "-q", "-d", DBNAME, "-c",
                          f"GRANT ALL ON ALL TABLES IN SCHEMA public "
                          f"TO {USER}")

    def teardown(self, test, node):
        if node != primary(test):
            return
        logger.info("%s tearing down postgres", node)
        with control.su("postgres"):
            control.exec_("psql", "-X", "-q", "-c",
                          f"DROP DATABASE IF EXISTS {DBNAME}",
                          check=False)
        with control.su():
            control.exec_("service", "postgresql", "stop", check=False)

    def log_files(self, test, node):
        if node != primary(test):
            return []
        try:
            out = control.exec_("ls", Lit(f"{LOG_DIR}/*.log"),
                                check=False)
            return [p for p in out.split() if p]
        except RemoteError:
            return []


# ---------------------------------------------------------------------------
# psql transport + error classification
# ---------------------------------------------------------------------------

class Psql:
    """Runs SQL through psql on a client node against the primary
    (stolon/client.clj open, minus the JDBC stack). Split out so tests
    can stub `run`."""

    def __init__(self, test, node, host, timeout: float = 10.0,
                 port: int = PORT):
        self.test = test
        self.node = node
        self.host = host
        self.port = port
        self.timeout = timeout
        self.sess = control.session(test, node)

    def run(self, sql: str) -> str:
        with control.with_session(self.test, self.node, self.sess):
            return control.exec_(
                "psql", "-h", self.host, "-p", str(self.port),
                "-U", USER, "-d", DBNAME,
                "-X", "-q", "-A", "-t", "-v", "ON_ERROR_STOP=1",
                "-c", sql, timeout=self.timeout)

    def close(self):
        control.disconnect(self.sess)


# Definite aborts: postgres rejected the transaction, nothing
# committed (stolon/client.clj with-errors)
_DEFINITE_RE = re.compile(
    "|".join([
        r"could not serialize access",
        r"deadlock detected",
        r"violates check constraint",
        r"connection refused",
        r"could not connect",
        r"no route to host",
        r"database system is (starting up|shutting down)",
    ]), re.I)


def classify_error(op, e: Exception):
    """RemoteError -> completed op. Serialization failures, constraint
    violations and refused connections are definite :fail; anything
    else (timeouts, dropped connections mid-commit) is :info."""
    msg = " ".join(str(x) for x in
                   (getattr(e, "err", ""), getattr(e, "out", ""), e))
    if _DEFINITE_RE.search(msg):
        return op.copy(type="fail", error=_short_error(msg))
    return op.copy(type="info", error=_short_error(msg))


def _short_error(msg: str) -> str:
    m = re.search(r"ERROR:\s*([^\n]+)", msg)
    return m.group(1)[:200] if m else msg[:200]


# ---------------------------------------------------------------------------
# Clients
# ---------------------------------------------------------------------------

class PgAppendClient(jclient.Client):
    """Elle list-append over SQL: reads select the comma-joined list,
    appends upsert with INSERT .. ON CONFLICT .. val || ',' || new
    (stolon/append.clj append-using-on-conflict!). Multi-mop
    transactions run inside one BEGIN ISOLATION LEVEL <iso> block in a
    single psql round-trip, so the recorded txn is exactly one SQL
    transaction."""

    def __init__(self, psql_factory=Psql, isolation="SERIALIZABLE"):
        self.psql_factory = psql_factory
        self.isolation = isolation
        self.psql = None

    def open(self, test, node):
        c = PgAppendClient(self.psql_factory, self.isolation)
        c.psql = self.psql_factory(test, node, primary(test))
        return c

    def close(self, test):
        if self.psql is not None:
            self.psql.close()

    def _mop_sql(self, i: int, f: str, k, v) -> str:
        t = table_for(k)
        if f == "r":
            return (f"SELECT 'm{i}=' || COALESCE("
                    f"(SELECT val FROM {t} WHERE id = {int(k)}), '~')")
        return (f"INSERT INTO {t} AS t (id, val) "
                f"VALUES ({int(k)}, '{int(v)}') "
                f"ON CONFLICT (id) DO UPDATE "
                f"SET val = t.val || ',' || EXCLUDED.val")

    def invoke(self, test, op):
        mops = op.value
        stmts = [self._mop_sql(i, f, k, v)
                 for i, (f, k, v) in enumerate(mops)]
        # ALWAYS wrap, even single mops: postgres SSI only promises
        # serializability among SERIALIZABLE transactions — a lone
        # read at the session default can witness the read-only
        # anomaly and elle would flag a healthy server
        sql = (f"BEGIN ISOLATION LEVEL {self.isolation}; "
               + "; ".join(stmts) + "; COMMIT;")
        try:
            out = self.psql.run(sql)
        except RemoteError as e:
            return classify_error(op, e)
        reads = {}
        for line in out.splitlines():
            m = re.match(r"m(\d+)=(.*)$", line.strip())
            if m:
                raw = m.group(2)
                reads[int(m.group(1))] = (
                    None if raw == "~"
                    else [int(x) for x in raw.split(",") if x])
        done = []
        for i, (f, k, v) in enumerate(mops):
            if f == "r":
                done.append(["r", k, reads.get(i)])
            else:
                done.append(["append", k, v])
        return op.copy(type="ok", value=done)


class PgBankClient(jclient.Client):
    """Bank transfers: two guarded UPDATEs in one serializable txn;
    the accounts table's CHECK (balance >= 0) turns an overdraft into
    a definite abort. Reads aggregate the whole table in one SELECT
    (tests/bank.clj ops; stolon/ledger.clj is the reference's SQL
    shape)."""

    def __init__(self, psql_factory=Psql, isolation="SERIALIZABLE"):
        self.psql_factory = psql_factory
        self.isolation = isolation
        self.psql = None

    def open(self, test, node):
        c = PgBankClient(self.psql_factory, self.isolation)
        c.psql = self.psql_factory(test, node, primary(test))
        return c

    def close(self, test):
        if self.psql is not None:
            self.psql.close()

    def invoke(self, test, op):
        try:
            if op.f == "read":
                out = self.psql.run(
                    "SELECT 'b=' || COALESCE(string_agg("
                    "id || ':' || balance, ',' ORDER BY id), '') "
                    "FROM accounts;")
                m = re.search(r"b=(.*)$", out, re.M)
                if not m:
                    raise ValueError(f"unparseable read: {out!r}")
                balances = {}
                for part in m.group(1).split(","):
                    if part:
                        acct, bal = part.split(":")
                        balances[int(acct)] = int(bal)
                return op.copy(type="ok", value=balances)
            if op.f == "transfer":
                v = op.value
                frm, to, amt = (int(v["from"]), int(v["to"]),
                                int(v["amount"]))
                sql = (
                    f"BEGIN ISOLATION LEVEL {self.isolation}; "
                    f"UPDATE accounts SET balance = balance - {amt} "
                    f"WHERE id = {frm}; "
                    f"UPDATE accounts SET balance = balance + {amt} "
                    f"WHERE id = {to}; "
                    f"COMMIT;")
                self.psql.run(sql)
                return op.copy(type="ok")
            raise ValueError(f"unknown f {op.f!r}")
        except RemoteError as e:
            if op.f == "read":
                return op.copy(type="fail", error=_short_error(
                    f"{getattr(e, 'err', '')} {e}"))
            return classify_error(op, e)


# ---------------------------------------------------------------------------
# Workloads / test
# ---------------------------------------------------------------------------

def append_workload(opts: dict) -> dict:
    w = workloads.txn_append.workload(
        {"ops": opts.get("ops", 2000),
         "key-count": opts.get("keys", 6),
         "seed": opts.get("seed")})
    w["client"] = PgAppendClient(
        isolation=opts.get("isolation", "SERIALIZABLE"))
    return w


def bank_workload(opts: dict) -> dict:
    from ..workloads import bank

    accounts = list(range(opts.get("accounts", 8)))
    total = opts.get("accounts", 8) * opts.get("initial_balance", 10)
    return {
        "client": PgBankClient(
            isolation=opts.get("isolation", "SERIALIZABLE")),
        "generator": bank.generator(accounts=accounts,
                                    seed=opts.get("seed")),
        "checker": chk.checker(
            lambda test, hist, o: bank.check_fast(hist, total)),
    }


WORKLOADS = {"append": append_workload, "bank": bank_workload}


def postgres_test(opts: dict) -> dict:
    name = opts.get("workload", "append")
    w = WORKLOADS[name](opts)
    test = testing.noop_test()
    test.update(
        name=f"postgres-{name}",
        os=debian.os,
        db=PostgresDB(accounts=opts.get("accounts", 8),
                      initial_balance=opts.get("initial_balance", 10)),
        ssh=opts["ssh"],
        nodes=opts["nodes"],
        concurrency=opts["concurrency"],
        client=w["client"],
        nemesis=jnemesis.partition_random_halves(),
        checker=chk.compose({"workload": w["checker"],
                             "stats": chk.stats(),
                             "perf": chk.perf(),
                             "timeline": chk.timeline()}),
        generator=gen.time_limit(
            opts.get("time_limit", 30),
            gen.clients(
                gen.stagger(1.0 / opts.get("rate", 20),
                            w["generator"]),
                jnemesis.start_stop_cycle(10.0))))
    return test


def _opts(p):
    p.add_argument("--workload", default="append",
                   help="Workload. " + cli.one_of(WORKLOADS))
    p.add_argument("--rate", type=float, default=20)
    p.add_argument("--isolation", default="SERIALIZABLE",
                   choices=["SERIALIZABLE", "REPEATABLE READ",
                            "READ COMMITTED"],
                   help="Transaction isolation level under test.")
    return p


def main(argv=None) -> None:
    commands = {}
    commands.update(cli.single_test_cmd(postgres_test,
                                        parser_fn=_opts))
    commands.update(cli.serve_cmd())
    cli.run_cli(commands, argv)


if __name__ == "__main__":
    main()
