"""Aerospike test suite: a linearizable CAS register over the `aql`
CLI client.

Capability reference: jepsen's aerospike test (aphyr/jepsen
aerospike/src/aerospike/core.clj) — .deb install of
aerospike-server-community + aerospike-tools, a mesh-heartbeat
aerospike.conf naming every peer, and a read/write/cas register in the
`test` namespace checked for linearizability under partitions (the
reference's headline finding). The reference drives the Java client;
here ops run `aql` on the node over the control plane — reads/writes
as AQL statements, CAS as a record UDF (jepsen.lua, registered at
setup) so the compare-and-set executes atomically inside the server —
the same node-side CLI transport pattern as the raftis/rethinkdb/
disque suites, so tests stub the transport with a scripted in-memory
register.
"""

from __future__ import annotations

import logging
import random
import re

from .. import checker as chk
from .. import cli, client as jclient, control, db as jdb
from .. import generator as gen
from .. import nemesis as jnemesis
from .. import testing
from ..checker import models
from ..control import util as cu
from ..control.core import RemoteError
from ..os_setup import debian

logger = logging.getLogger(__name__)

VERSION = "3.5.4"
SERVICE_PORT = 3000
FABRIC_PORT = 3001
HEARTBEAT_PORT = 3002
CONF = "/etc/aerospike/aerospike.conf"
UDF = "/opt/jepsen/jepsen.lua"
LOGFILE = "/var/log/aerospike/aerospike.log"
NAMESPACE = "test"
SET = "jepsen"
KEY = "r"

# The record UDF behind cas/write: runs atomically on the record
# inside the server (the reference uses the Java client's
# generation-check writes; a record UDF is the CLI-reachable
# equivalent). cas returns 1 only when the precondition held.
UDF_BODY = """\
function cas(rec, old, new)
    if aerospike:exists(rec) and rec['v'] == old then
        rec['v'] = new
        aerospike:update(rec)
        return 1
    end
    return 0
end

function put(rec, v)
    rec['v'] = v
    if aerospike:exists(rec) then
        aerospike:update(rec)
    else
        aerospike:create(rec)
    end
    return 1
end
"""


def conf_body(test, node) -> str:
    """aerospike.conf with mesh heartbeat seeds for every peer and an
    in-memory `test` namespace replicated across the cluster
    (aerospike core.clj configure!)."""
    seeds = "\n".join(
        f"        mesh-seed-address-port {n} {HEARTBEAT_PORT}"
        for n in test["nodes"] if str(n) != str(node))
    return f"""\
service {{
    user root
    group root
    paxos-single-replica-limit 1
    pidfile /var/run/aerospike/asd.pid
    service-threads 4
    transaction-queues 4
    transaction-threads-per-queue 4
    proto-fd-max 1024
}}
logging {{
    file {LOGFILE} {{
        context any info
    }}
}}
network {{
    service {{
        address any
        port {SERVICE_PORT}
    }}
    heartbeat {{
        mode mesh
        port {HEARTBEAT_PORT}
{seeds}
        interval 150
        timeout 10
    }}
    fabric {{
        port {FABRIC_PORT}
    }}
}}
namespace {NAMESPACE} {{
    replication-factor {len(test["nodes"])}
    memory-size 1G
    default-ttl 0
    storage-engine memory
}}
"""


class AerospikeDB(jdb.DB):
    """.deb install + mesh config + asd service + UDF registration
    (aerospike core.clj db)."""

    supports_kill = True

    def __init__(self, version: str = VERSION):
        self.version = version

    def setup(self, test, node):
        logger.info("%s installing aerospike %s", node, self.version)
        with control.su():
            url = ("https://www.aerospike.com/artifacts/"
                   "aerospike-server-community/"
                   f"{self.version}/aerospike-server-community-"
                   f"{self.version}-debian8.tgz")
            d = cu.install_archive(url, "/opt/aerospike-install")
            control.exec_("sh", "-c",
                          f"dpkg -i {d}/aerospike-server-*.deb "
                          f"{d}/aerospike-tools-*.deb")
            control.exec_("mkdir", "-p", "/var/log/aerospike",
                          "/opt/jepsen")
            cu.write_file(conf_body(test, node), CONF)
            cu.write_file(UDF_BODY, UDF)
            control.exec_("service", "aerospike", "restart")
        cu.await_tcp_port(SERVICE_PORT, timeout_secs=120)
        # the CAS/put UDF must exist before the first client op
        control.exec_("aql", "-h", str(node), "-c",
                      f"REGISTER MODULE '{UDF}'", timeout=30.0)

    def teardown(self, test, node):
        logger.info("%s tearing down aerospike", node)
        with control.su():
            try:
                control.exec_("service", "aerospike", "stop")
            except RemoteError:
                pass
            control.exec_("rm", "-rf", "/opt/aerospike-install",
                          "/opt/jepsen", CONF)

    def kill(self, test, node):
        with control.su():
            cu.grepkill("asd")
        return "killed"

    def start(self, test, node):
        with control.su():
            control.exec_("service", "aerospike", "restart")
        return "started"

    def log_files(self, test, node):
        return [LOGFILE]


# ---------------------------------------------------------------------------
# aql transport
# ---------------------------------------------------------------------------

class AqlCli:
    """One `aql -c` statement on the node. Split out so tests can stub
    `run`. Non-retrying session: INSERT/EXECUTE are not idempotent — a
    transport retry after the server applied one double-applies a
    write the history records once (the raftis RedisCli rationale)."""

    def __init__(self, test, node, timeout: float = 5.0):
        self.test = test
        self.node = node
        self.timeout = timeout
        self.sess = self._session(test, node)

    @staticmethod
    def _session(test, node):
        if test.get("remote") is not None or \
                (test.get("ssh") or {}).get("dummy"):
            return control.session(test, node)
        from ..control.scp import ScpRemote
        from ..control.ssh import SshRemote

        return ScpRemote(SshRemote()).connect(
            control.conn_spec(test, node))

    def run(self, statement: str) -> str:
        with control.with_session(self.test, self.node, self.sess):
            return control.exec_("aql", "-h", str(self.node), "-c",
                                 statement, timeout=self.timeout)

    def close(self):
        control.disconnect(self.sess)


# error markers proving the statement definitely did NOT apply
_DEFINITE = ("aerospike_err_cluster", "not authenticated",
             "invalid namespace", "connection refused",
             "could not connect", "failed to connect",
             "unavailable")

_CELL = re.compile(r"^\|\s*(-?\d+)\s*\|$")


class _ErrReply(Exception):
    """aql reported an error line — the server rejected or never saw
    the statement."""


def parse_cells(out: str) -> list[int]:
    """Integer cells out of aql's box-drawing table output (one value
    column). 'Error: (n) ...' lines raise; '0 rows in set' yields
    []."""
    vals = []
    for line in out.splitlines():
        s = line.strip()
        if s.lower().startswith("error"):
            raise _ErrReply(s)
        m = _CELL.match(s)
        if m:
            vals.append(int(m.group(1)))
    return vals


def _classify(op, e: Exception):
    msg = f"{e} {getattr(e, 'err', '')} {getattr(e, 'out', '')}" \
        .strip().lower()
    if op.f == "read":
        # an unanswered read changed nothing: always a definite fail
        return op.copy(type="fail", error=msg[:200])
    if isinstance(e, _ErrReply) and any(m in msg for m in _DEFINITE):
        return op.copy(type="fail", error=msg[:200])
    # timeouts and everything else may have applied: indeterminate
    return op.copy(type="info", error=msg[:200])


class AerospikeCasClient(jclient.Client):
    """read/write/cas register at PK 'r' (aerospike core.clj
    cas-register client). Reads are AQL SELECTs; write/cas execute the
    jepsen.lua record UDF so the compare runs atomically server-side.
    A CAS whose UDF returns 0 definitely did not apply (:fail); a lost
    reply is indeterminate (:info)."""

    def __init__(self, cli_factory=AqlCli):
        self.cli_factory = cli_factory
        self.cli = None

    def open(self, test, node):
        c = AerospikeCasClient(self.cli_factory)
        c.cli = self.cli_factory(test, node)
        return c

    def close(self, test):
        if self.cli is not None:
            self.cli.close()

    def invoke(self, test, op):
        try:
            if op.f == "read":
                cells = parse_cells(self.cli.run(
                    f"SELECT v FROM {NAMESPACE}.{SET} WHERE "
                    f"PK='{KEY}'"))
                return op.copy(type="ok",
                               value=cells[0] if cells else None)
            if op.f == "write":
                cells = parse_cells(self.cli.run(
                    f"EXECUTE jepsen.put({int(op.value)}) ON "
                    f"{NAMESPACE}.{SET} WHERE PK='{KEY}'"))
                if cells != [1]:
                    raise RemoteError("unexpected put reply", exit=0,
                                      out=str(cells), err="",
                                      cmd="aql", node=None)
                return op.copy(type="ok")
            if op.f == "cas":
                old, new = op.value
                cells = parse_cells(self.cli.run(
                    f"EXECUTE jepsen.cas({int(old)}, {int(new)}) ON "
                    f"{NAMESPACE}.{SET} WHERE PK='{KEY}'"))
                if cells == [1]:
                    return op.copy(type="ok")
                if cells == [0]:
                    # the UDF's precondition check said no: definite
                    return op.copy(type="fail",
                                   error="cas precondition failed")
                raise RemoteError("unexpected cas reply", exit=0,
                                  out=str(cells), err="", cmd="aql",
                                  node=None)
            raise ValueError(f"unknown f {op.f!r}")
        except (RemoteError, _ErrReply) as e:
            return _classify(op, e)


# ---------------------------------------------------------------------------
# Workloads / test
# ---------------------------------------------------------------------------

def register_workload(opts: dict) -> dict:
    """The reference's cas-register workload: mixed read/write/cas
    against one key, checked linearizable against CASRegister."""
    rng = random.Random(opts.get("seed"))

    def one():
        roll = rng.random()
        if roll < 0.5:
            return {"f": "read", "value": None}
        if roll < 0.75:
            return {"f": "write", "value": rng.randrange(5)}
        return {"f": "cas", "value": [rng.randrange(5),
                                      rng.randrange(5)]}

    return {
        "client": AerospikeCasClient(),
        "generator": gen.limit(opts.get("ops", 500), one),
        "checker": chk.linearizable({"model": models.cas_register()}),
    }


WORKLOADS = {"register": register_workload}


def aerospike_test(opts: dict) -> dict:
    name = opts.get("workload") or "register"
    w = WORKLOADS[name](opts)
    test = testing.noop_test()
    test.update(
        name=f"aerospike-{name}",
        os=debian.os,
        db=AerospikeDB(opts.get("version", VERSION)),
        ssh=opts["ssh"],
        nodes=opts["nodes"],
        concurrency=opts["concurrency"],
        client=w["client"],
        nemesis=jnemesis.partition_random_halves(),
        checker=chk.compose({"workload": w["checker"],
                             "stats": chk.stats(),
                             "perf": chk.perf(),
                             "timeline": chk.timeline()}),
        generator=gen.time_limit(
            opts.get("time_limit", 30),
            gen.clients(
                gen.stagger(1.0 / opts.get("rate", 20),
                            w["generator"]),
                jnemesis.start_stop_cycle(10.0))))
    return test


def _opts(p):
    p.add_argument("--workload", default=None,
                   help="Workload (default register). "
                        + cli.one_of(WORKLOADS))
    p.add_argument("--version", default=VERSION,
                   help="aerospike-server-community version to "
                        "install.")
    p.add_argument("--rate", type=float, default=20)
    return p


def main(argv=None) -> None:
    commands = {}
    commands.update(cli.single_test_cmd(aerospike_test,
                                        parser_fn=_opts))
    commands.update(cli.serve_cmd())
    commands.update(cli.coverage_cmd(list(WORKLOADS)))
    cli.run_cli(commands, argv)


if __name__ == "__main__":
    main()
