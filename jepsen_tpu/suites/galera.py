"""MariaDB Galera test suite: multi-master bank transfers and set
inserts, every client talking to its OWN node's mysqld.

Capability reference: galera/src/jepsen/galera.clj — DB: mariadb
repo + debconf root password + package install with a stashed stock
datadir (34-56), jepsen.cnf with the gcomm:// cluster address
(58-72), primary starts --wsrep-new-cluster and the rest join between
synchronize barriers (104-121), jepsen database + grant (93-101),
teardown restores the stock datadir (123-128); bank client: read
balances / read-check-update transfer inside one txn, negative
balances refused client-side (240-303); set client: insert-per-value
+ final read (215-238). The reference's JDBC conn-spec targets the
client's own node (90-96) — Galera is multi-master, which is exactly
what the bank test stresses. Here ops go through `mysql -e` on the
node, one batch per transaction, with SQL variables carrying the
read-check-update logic so the whole transfer stays one atomic
round trip.
"""

from __future__ import annotations

import logging
import re

from .. import checker as chk
from .. import cli, client as jclient, control, core, db as jdb
from .. import generator as gen
from .. import nemesis as jnemesis
from .. import testing
from . import common
from ..control import util as cu
from ..control.core import RemoteError
from ..core import primary
from ..os_setup import debian

logger = logging.getLogger(__name__)

DATA_DIR = "/var/lib/mysql"
STOCK_DIR = "/var/lib/mysql-stock"
CNF = "/etc/mysql/conf.d/jepsen.cnf"
LOGFILE = "/var/log/mysql/error.log"
USER = "jepsen"
PASSWORD = "jepsen"
DB_NAME = "jepsen"

JEPSEN_CNF = """[mysqld]
bind-address=0.0.0.0
wsrep_on=ON
wsrep_provider=/usr/lib/galera/libgalera_smm.so
wsrep_cluster_address={cluster}
wsrep_cluster_name=jepsen
binlog_format=ROW
default_storage_engine=InnoDB
innodb_autoinc_lock_mode=2
"""


def cluster_address(test) -> str:
    """gcomm://n1,n2,... (galera.clj:58-62)."""
    return "gcomm://" + ",".join(str(n) for n in test["nodes"])


class GaleraDB(jdb.DB):
    """mariadb-galera cluster (galera.clj db, 104-128)."""

    def __init__(self, accounts: int = 8, initial_balance: int = 10):
        self.accounts = accounts
        self.initial_balance = initial_balance

    def setup(self, test, node):
        logger.info("%s installing mariadb-galera", node)
        with control.su():
            debian.add_repo(
                "galera",
                "deb http://mirror.mariadb.org/repo/10.0/debian "
                "jessie main",
                "keyserver.ubuntu.com", "0xcbcb082a1bb943db")
            for line in (
                    "mariadb-galera-server-10.0 mysql-server/"
                    f"root_password password {PASSWORD}",
                    "mariadb-galera-server-10.0 mysql-server/"
                    f"root_password_again password {PASSWORD}"):
                control.exec_("sh", "-c",
                              f"echo {line!r} | debconf-set-selections")
            debian.install(["rsync", "mariadb-galera-server"])
            control.exec_("service", "mysql", "stop", check=False)
            # stash pristine data files for teardown restore
            control.exec_("sh", "-c",
                          f"test -d {STOCK_DIR} || "
                          f"cp -rp {DATA_DIR} {STOCK_DIR}")
            cnf = JEPSEN_CNF.format(cluster=cluster_address(test))
            cu.write_file(cnf, CNF)
            if node == primary(test):
                control.exec_("service", "mysql", "start",
                              "--wsrep-new-cluster")
        core.synchronize(test)  # the new cluster exists before joins
        with control.su():
            if node != primary(test):
                control.exec_("service", "mysql", "start")
        core.synchronize(test)
        self._eval(f"CREATE DATABASE IF NOT EXISTS {DB_NAME};")
        self._eval(f"GRANT ALL PRIVILEGES ON {DB_NAME}.* TO "
                   f"'{USER}'@'%' IDENTIFIED BY '{PASSWORD}';")
        if node == primary(test):
            self._eval(
                f"CREATE TABLE IF NOT EXISTS {DB_NAME}.accounts ("
                "id INT NOT NULL PRIMARY KEY, "
                "balance BIGINT NOT NULL);"
                f"CREATE TABLE IF NOT EXISTS {DB_NAME}.sets ("
                "id INT AUTO_INCREMENT PRIMARY KEY, val INT);")
            rows = ",".join(f"({i}, {self.initial_balance})"
                            for i in range(self.accounts))
            self._eval(f"INSERT IGNORE INTO {DB_NAME}.accounts "
                       f"VALUES {rows};")

    def _eval(self, sql: str) -> str:
        """Local root mysql eval (galera.clj eval!, 80-83)."""
        return control.exec_("mysql", "-u", "root",
                             f"--password={PASSWORD}", "-e", sql)

    def teardown(self, test, node):
        logger.info("%s tearing down galera", node)
        with control.su():
            cu.grepkill("mysqld")
            control.exec_("rm", "-rf", DATA_DIR)
            control.exec_("sh", "-c",
                          f"test -d {STOCK_DIR} && "
                          f"cp -rp {STOCK_DIR} {DATA_DIR} || true")

    def log_files(self, test, node):
        return [LOGFILE]


# ---------------------------------------------------------------------------
# mysql CLI transport
# ---------------------------------------------------------------------------

class Mysql(common.SqlCli):
    """Node-local mysql CLI batches (multi-master: each client writes
    to its own node, galera.clj conn-spec)."""

    def __init__(self, test, node, timeout: float = 10.0):
        super().__init__(
            test, node,
            ["mysql", "-u", USER, f"--password={PASSWORD}",
             "-D", DB_NAME, "-N", "-B", "-e"],
            timeout=timeout)


_classify = common.make_classifier([
    r"deadlock", r"lock wait timeout",
    r"wsrep has not yet prepared", r"connection refused",
    r"can't connect", r"unknown mysql server"])


class GaleraBankClient(jclient.Client):
    """Bank transfers, reference semantics (galera.clj BankClient,
    258-303): read both balances, refuse a transfer that would go
    negative, otherwise update both rows — all one transaction, with
    SQL variables standing in for the reference's client-side check."""

    def __init__(self, mysql_factory=Mysql):
        self.mysql_factory = mysql_factory
        self.mysql = None

    def open(self, test, node):
        c = GaleraBankClient(self.mysql_factory)
        c.mysql = self.mysql_factory(test, node)
        return c

    def close(self, test):
        if self.mysql is not None:
            self.mysql.close()

    def invoke(self, test, op):
        try:
            if op.f == "read":
                out = self.mysql.run(
                    # same 1024-byte GROUP_CONCAT truncation guard as
                    # the set client: wide account tables must not be
                    # silently cut into a false loss verdict
                    "SET SESSION group_concat_max_len = 1048576; "
                    "SELECT CONCAT('b=', COALESCE(GROUP_CONCAT("
                    "CONCAT(id, ':', balance) ORDER BY id), '')) "
                    "FROM accounts;")
                m = re.search(r"b=(.*)$", out, re.M)
                if not m:
                    raise ValueError(f"unparseable read: {out!r}")
                balances = {}
                for part in m.group(1).split(","):
                    if part:
                        i, b = part.split(":")
                        balances[int(i)] = int(b)
                return op.copy(type="ok", value=balances)
            if op.f == "transfer":
                v = op.value
                f, t, a = (int(v["from"]), int(v["to"]),
                           int(v["amount"]))
                out = self.mysql.run(
                    "SET SESSION TRANSACTION ISOLATION LEVEL "
                    "SERIALIZABLE; "
                    "START TRANSACTION; "
                    f"SELECT balance INTO @b1 FROM accounts "
                    f"WHERE id = {f}; "
                    f"UPDATE accounts SET balance = balance - {a} "
                    f"WHERE id = {f} AND @b1 >= {a}; "
                    f"UPDATE accounts SET balance = balance + {a} "
                    f"WHERE id = {t} AND @b1 >= {a}; "
                    f"SELECT CONCAT('applied=', "
                    f"IF(@b1 >= {a}, 1, 0)); "
                    "COMMIT;")
                m = re.search(r"applied=(\d)", out)
                if not m:
                    raise ValueError(f"unparseable transfer: {out!r}")
                if m.group(1) == "1":
                    return op.copy(type="ok")
                return op.copy(type="fail", error="insufficient funds")
            raise ValueError(f"unknown f {op.f!r}")
        except RemoteError as e:
            return _classify(op, e)


class GaleraSetClient(jclient.Client):
    """Insert-a-row-per-element set (galera.clj set-client, 215-238);
    the final read gathers what survived."""

    def __init__(self, mysql_factory=Mysql):
        self.mysql_factory = mysql_factory
        self.mysql = None

    def open(self, test, node):
        c = GaleraSetClient(self.mysql_factory)
        c.mysql = self.mysql_factory(test, node)
        return c

    def close(self, test):
        if self.mysql is not None:
            self.mysql.close()

    def invoke(self, test, op):
        try:
            if op.f == "add":
                self.mysql.run(
                    f"INSERT INTO sets (val) VALUES ({int(op.value)});")
                return op.copy(type="ok")
            if op.f == "read":
                out = self.mysql.run(
                    # mariadb 10.0 truncates GROUP_CONCAT at 1024
                    # bytes by default — silently losing elements and
                    # framing a healthy cluster for data loss
                    "SET SESSION group_concat_max_len = 1048576; "
                    "SELECT CONCAT('s=', COALESCE(GROUP_CONCAT(val), "
                    "'')) FROM sets;")
                m = re.search(r"s=(.*)$", out, re.M)
                if not m:
                    raise ValueError(f"unparseable read: {out!r}")
                vals = [int(x) for x in m.group(1).split(",") if x]
                return op.copy(type="ok", value=sorted(vals))
            raise ValueError(f"unknown f {op.f!r}")
        except RemoteError as e:
            return _classify(op, e)


# ---------------------------------------------------------------------------
# Workloads / test
# ---------------------------------------------------------------------------

def bank_workload(opts: dict) -> dict:
    from ..workloads import bank

    n = opts.get("accounts", 8)
    total = n * opts.get("initial_balance", 10)
    return {
        "client": GaleraBankClient(),
        "generator": bank.generator(accounts=list(range(n)),
                                    seed=opts.get("seed")),
        "checker": chk.checker(
            lambda test, hist, o: bank.check_fast(hist, total)),
    }


def set_workload(opts: dict) -> dict:
    """Adds under faults; the final reads are a SEPARATE phase so the
    test can heal the network first (reading mid-partition would frame
    a healthy cluster for lost elements)."""
    import itertools

    counter = itertools.count()
    return {
        "client": GaleraSetClient(),
        "generator": gen.limit(
            opts.get("ops", 500),
            lambda: {"f": "add", "value": next(counter)}),
        "final_generator": gen.each_thread(gen.once(
            lambda: {"f": "read", "value": None})),
        "checker": chk.set_checker(),
    }


WORKLOADS = {"bank": bank_workload, "set": set_workload}


def galera_test(opts: dict) -> dict:
    name = opts.get("workload") or "bank"
    w = WORKLOADS[name](opts)
    test = testing.noop_test()
    test.update(
        name=f"galera-{name}",
        os=debian.os,
        db=GaleraDB(accounts=opts.get("accounts", 8),
                    initial_balance=opts.get("initial_balance", 10)),
        ssh=opts["ssh"],
        nodes=opts["nodes"],
        concurrency=opts["concurrency"],
        client=w["client"],
        nemesis=jnemesis.partition_random_halves(),
        checker=chk.compose({"workload": w["checker"],
                             "stats": chk.stats(),
                             "perf": chk.perf(),
                             "timeline": chk.timeline()}),
        generator=_suite_generator(opts, w))
    return test


def _suite_generator(opts, w):
    """time-limit bounds the op mix + nemesis cycle; any final phase
    (the set workload's reads) runs after an explicit heal + settle."""
    main = gen.time_limit(
        opts.get("time_limit", 30),
        gen.clients(
            gen.stagger(1.0 / opts.get("rate", 20), w["generator"]),
            jnemesis.start_stop_cycle(10.0)))
    final = w.get("final_generator")
    if final is None:
        return main
    return gen.phases(
        main,
        gen.nemesis(gen.once({"type": "info", "f": "stop"})),
        gen.sleep(opts.get("recovery_time", 5)),
        gen.clients(final))


def _opts(p):
    p.add_argument("--workload", default=None,
                   help="Workload (default bank). "
                        + cli.one_of(WORKLOADS))
    p.add_argument("--rate", type=float, default=20)
    return p


def main(argv=None) -> None:
    commands = {}
    commands.update(cli.single_test_cmd(galera_test, parser_fn=_opts))
    commands.update(cli.serve_cmd())
    cli.run_cli(commands, argv)


if __name__ == "__main__":
    main()
