"""Stolon test suite: HA PostgreSQL (keeper/sentinel/proxy over an
etcd store) checked with elle list-append and a double-spend ledger.

Capability reference: stolon/src/jepsen/stolon/db.clj — postgres-12 +
stolon release archive install (45-72), stolonctl with cluster
name/store flags (77-87), initial cluster spec with synchronous
replication (89-108), sentinel/keeper/proxy daemons (110-176,
node->pg-id naming), teardown grepkills postgres and wipes data
(280-295); stolon/append.clj (the elle workload, reused from the
postgres suite's client over the proxy port); stolon/ledger.clj —
transfer inserts a ledger row iff the account total stays non-negative
(57-69), per-account charitable balance check (140-165: indeterminate
deposits count, indeterminate withdrawals don't; the reference flags
any nonzero balance — an artifact of its all-accounts-start-at-zero
generator — while here the documented non-negativity invariant is
checked); stolon/nemesis.clj (partitions + keeper kills).

Clients talk to the stolon-proxy on THEIR OWN node (the reference's
jdbc spec does the same), so a partitioned proxy pointing at a
deposed primary is part of the test surface.
"""

from __future__ import annotations

import json
import logging
import re

from .. import checker as chk
from .. import cli, client as jclient, control, core, db as jdb
from .. import generator as gen
from .. import testing, util as jutil, workloads
from . import postgres as pg
from ..control import util as cu
from ..control.core import RemoteError
from ..core import primary
from ..os_setup import debian

logger = logging.getLogger(__name__)

DIR = "/opt/stolon"
DATA_DIR = f"{DIR}/data"
CLUSTER = "jepsen-cluster"
OS_USER = "postgres"
VERSION = "0.16.0"
PROXY_PORT = 25432
KEEPER_PG_PORT = 5433
ETCD_CLIENT_PORT = 2379

SENTINEL = ("stolon-sentinel", f"{DIR}/sentinel.log",
            f"{DIR}/sentinel.pid")
KEEPER = ("stolon-keeper", f"{DIR}/keeper.log", f"{DIR}/keeper.pid")
PROXY = ("stolon-proxy", f"{DIR}/proxy.log", f"{DIR}/proxy.pid")


def cluster_spec(test) -> dict:
    """Initial cluster spec (db.clj:89-108): synchronous replication,
    tight fail/proxy intervals."""
    return {
        "synchronousReplication": True,
        "initMode": "new",
        "sleepInterval": "1s",
        "requestTimeout": "2s",
        "failInterval": "4s",
        "proxyCheckInterval": "1s",
        "proxyTimeout": "3s",
        "deadKeeperRemovalInterval": "48h",
        "maxStandbysPerSender": len(test["nodes"]) - 1,
        "minSynchronousStandbys": 1,
        "maxSynchronousStandbys": 1,
    }


def pg_id(test, node) -> str:
    """node -> pg<i> (db.clj node->pg-id)."""
    return f"pg{list(test['nodes']).index(node) + 1}"


def store_endpoints(node) -> str:
    return f"http://{node}:{ETCD_CLIENT_PORT}"


def store_flags(node) -> list:
    return ["--cluster-name", CLUSTER,
            "--store-backend", "etcdv3",
            "--store-endpoints", store_endpoints(node)]


class StolonDB(jdb.DB):
    """postgres + etcd store + stolon keeper/sentinel/proxy per node
    (db.clj db, 230-295)."""

    supports_kill = True

    def __init__(self, version: str = VERSION, accounts: int = 8,
                 initial_balance: int = 10):
        self.version = version
        self.accounts = accounts
        self.initial_balance = initial_balance

    def setup(self, test, node):
        from . import etcd as etcd_suite

        logger.info("%s installing stolon %s", node, self.version)
        # the cluster store: a plain etcd member on every node
        etcd_suite.EtcdDB().setup(test, node)
        with control.su():
            debian.install(["postgresql-12", "postgresql-client-12"])
            control.exec_("service", "postgresql", "stop", check=False)
            cu.install_archive(
                "https://github.com/sorintlab/stolon/releases/"
                f"download/v{self.version}/stolon-v{self.version}"
                "-linux-amd64.tar.gz", DIR)
            control.exec_("mkdir", "-p", DATA_DIR)
            control.exec_("chown", "-R", f"{OS_USER}:{OS_USER}", DIR)
        self._start_sentinel(test, node)
        self._start_keeper(test, node)
        core.synchronize(test)
        self._start_proxy(test, node)
        core.synchronize(test)
        if node == primary(test):
            self._await_proxy()
            self._init_schema()
        core.synchronize(test)

    def _start_sentinel(self, test, node):
        spec = f"{DIR}/init-spec.json"
        with control.su(OS_USER):
            cu.write_file(json.dumps(cluster_spec(test)), spec)
            cu.start_daemon(
                {"chdir": DIR, "logfile": SENTINEL[1],
                 "pidfile": SENTINEL[2]},
                f"{DIR}/bin/{SENTINEL[0]}", *store_flags(node),
                "--initial-cluster-spec", spec)

    def _start_keeper(self, test, node):
        with control.su(OS_USER):
            cu.start_daemon(
                {"chdir": DIR, "logfile": KEEPER[1],
                 "pidfile": KEEPER[2]},
                f"{DIR}/bin/{KEEPER[0]}", *store_flags(node),
                "--uid", pg_id(test, node),
                "--data-dir", f"{DATA_DIR}/{pg_id(test, node)}",
                "--pg-su-password", pg.USER,
                "--pg-repl-username", "repluser",
                "--pg-repl-password", pg.USER,
                "--pg-listen-address", str(node),
                "--pg-port", str(KEEPER_PG_PORT),
                "--pg-bin-path", "/usr/lib/postgresql/12/bin")

    def _start_proxy(self, test, node):
        with control.su(OS_USER):
            cu.start_daemon(
                {"chdir": DIR, "logfile": PROXY[1],
                 "pidfile": PROXY[2]},
                f"{DIR}/bin/{PROXY[0]}", *store_flags(node),
                "--listen-address", "0.0.0.0",
                "--port", str(PROXY_PORT))

    def _psql_local(self, sql: str, check: bool = True) -> str:
        return control.exec_(
            "psql", "-h", "127.0.0.1", "-p", str(PROXY_PORT),
            "-U", "stolon", "-d", "postgres", "-X", "-q", "-A", "-t",
            "-c", sql, check=check)

    def _await_proxy(self):
        jutil.await_fn(lambda: self._psql_local("SELECT 1"),
                       timeout_secs=120, retry_interval=2,
                       log_message="waiting for stolon proxy")

    def _init_schema(self):
        ddl = [f"CREATE ROLE {pg.USER} LOGIN",
               f"CREATE DATABASE {pg.DBNAME} OWNER {pg.USER}"]
        for stmt in ddl:
            self._psql_local(stmt, check=False)
        tables = []
        for i in range(pg.TABLE_COUNT):
            tables.append(f"CREATE TABLE IF NOT EXISTS txn{i} ("
                          "id int NOT NULL PRIMARY KEY, val text)")
        tables.append("CREATE TABLE IF NOT EXISTS ledger ("
                      "id bigint PRIMARY KEY, account int NOT NULL, "
                      "amount int NOT NULL)")
        tables.append("CREATE INDEX IF NOT EXISTS i_account ON "
                      "ledger (account)")
        for stmt in tables:
            control.exec_(
                "psql", "-h", "127.0.0.1", "-p", str(PROXY_PORT),
                "-U", "stolon", "-d", pg.DBNAME, "-X", "-q", "-c",
                stmt, check=False)
        control.exec_(
            "psql", "-h", "127.0.0.1", "-p", str(PROXY_PORT),
            "-U", "stolon", "-d", pg.DBNAME, "-X", "-q", "-c",
            f"GRANT ALL ON ALL TABLES IN SCHEMA public TO {pg.USER}",
            check=False)

    def teardown(self, test, node):
        from . import etcd as etcd_suite

        logger.info("%s tearing down stolon", node)
        with control.su():
            for name, _log, pid in (PROXY, SENTINEL, KEEPER):
                cu.stop_daemon(name, pid)
            cu.grepkill("postgres")
            control.exec_("rm", "-rf", DATA_DIR)
        etcd_suite.EtcdDB().teardown(test, node)

    def log_files(self, test, node):
        return [SENTINEL[1], KEEPER[1], PROXY[1]]

    # stolon-keeper kills are the suite's signature fault
    # (stolon/nemesis.clj): the sentinel must fail postgres over
    def kill(self, test, node):
        with control.su():
            cu.stop_daemon(KEEPER[0], KEEPER[2])

    def start(self, test, node):
        self._start_keeper(test, node)


class ProxyPsql(pg.Psql):
    """psql against the node-local stolon proxy."""

    def __init__(self, test, node, host=None, timeout: float = 10.0,
                 port: int = PROXY_PORT):
        super().__init__(test, node, "127.0.0.1", timeout=timeout,
                         port=port)


class LedgerClient(jclient.Client):
    """ledger.clj transfer!: deposits insert unconditionally;
    withdrawals first sum the account's OTHER rows and only insert if
    the total stays non-negative — the read-then-insert shape that G2
    breaks into a double-spend. The row id comes from a per-process
    disjoint counter so concurrent inserts never collide."""

    def __init__(self, psql_factory=ProxyPsql,
                 isolation: str = "SERIALIZABLE"):
        self.psql_factory = psql_factory
        self.isolation = isolation
        self.psql = None
        self._next_id = 0

    def open(self, test, node):
        c = LedgerClient(self.psql_factory, self.isolation)
        c.psql = self.psql_factory(test, node)
        return c

    def setup(self, test):
        return self

    def close(self, test):
        if self.psql is not None:
            self.psql.close()

    def _row_id(self, op) -> int:
        # processes are globally unique; stride by 1M per process
        pid = op.process if isinstance(op.process, int) else 0
        self._next_id += 1
        return pid * 1_000_000 + self._next_id

    def invoke(self, test, op):
        try:
            if op.f == "read":
                out = self.psql.run(
                    "SELECT 'a=' || COALESCE(string_agg(account || "
                    "':' || total, ',' ORDER BY account), '') FROM "
                    "(SELECT account, SUM(amount) AS total FROM "
                    "ledger GROUP BY account) t;")
                m = re.search(r"a=(.*)$", out, re.M)
                if not m:
                    raise ValueError(f"unparseable read: {out!r}")
                balances = {}
                for part in m.group(1).split(","):
                    if part:
                        a, b = part.split(":")
                        balances[int(a)] = int(b)
                return op.copy(type="ok", value=balances)
            if op.f == "transfer":
                account, amount = op.value
                rid = self._row_id(op)
                if amount > 0:
                    self.psql.run(
                        f"BEGIN ISOLATION LEVEL {self.isolation}; "
                        f"INSERT INTO ledger VALUES "
                        f"({rid}, {int(account)}, {int(amount)}); "
                        "COMMIT;")
                    return op.copy(type="ok")
                out = self.psql.run(
                    f"BEGIN ISOLATION LEVEL {self.isolation}; "
                    f"SELECT 'bal=' || COALESCE(SUM(amount), 0) "
                    f"FROM ledger WHERE account = {int(account)} "
                    f"AND id != {rid}; "
                    f"INSERT INTO ledger SELECT {rid}, "
                    f"{int(account)}, {int(amount)} WHERE "
                    f"(SELECT COALESCE(SUM(amount), 0) FROM ledger "
                    f"WHERE account = {int(account)} AND id != {rid})"
                    f" + {int(amount)} >= 0; "
                    f"SELECT 'n=' || COUNT(*) FROM ledger WHERE "
                    f"id = {rid}; COMMIT;")
                m = re.search(r"n=(\d+)", out)
                if not m:
                    raise ValueError(f"unparseable transfer: {out!r}")
                if m.group(1) == "1":
                    return op.copy(type="ok")
                return op.copy(type="fail",
                               error="insufficient funds")
            raise ValueError(f"unknown f {op.f!r}")
        except RemoteError as e:
            if op.f == "read":
                return op.copy(type="fail", error=str(e)[:200])
            return pg.classify_error(op, e)


def check_ledger(hist) -> dict:
    """ledger.clj check-account (140-153): per-account, charitable
    interpretation — ok+info deposits count, only ok withdrawals do;
    the resulting balance must be non-negative."""
    accounts: dict = {}
    for op in hist:
        if op.f != "transfer" or op.type not in ("ok", "info"):
            continue
        account, amount = op.value
        if amount > 0 or op.type == "ok":
            accounts[account] = accounts.get(account, 0) + amount
    errors = [{"account": a, "balance": b}
              for a, b in sorted(accounts.items()) if b < 0]
    return {"valid?": not errors, "errors": errors,
            "account-count": len(accounts)}


def ledger_checker() -> chk.Checker:
    return chk.checker(lambda test, hist, opts: check_ledger(hist))


class _LedgerGen(gen.Generator):
    """fund-then-double-spend (ledger.clj:167-175): per account, one
    +10 deposit then a burst of -9 withdrawals racing to double-spend.
    Functional successor; the burst size derives from (seed, account)."""

    def __init__(self, seed=None, account: int = 0, remaining=None):
        self.seed = seed
        self.account = account
        self.remaining = remaining

    def op(self, test, ctx):
        if self.remaining is None:
            rng = jutil.seeded_rng(self.seed, self.account)
            burst = 2 ** rng.randrange(5)
            m = gen.fill_in_op(
                {"f": "transfer", "value": [self.account, 10]}, ctx)
            if m is gen.PENDING:
                return gen.PENDING, self
            return m, _LedgerGen(self.seed, self.account, burst)
        if self.remaining == 0:
            return _LedgerGen(self.seed, self.account + 1).op(
                test, ctx)
        m = gen.fill_in_op(
            {"f": "transfer", "value": [self.account, -9]}, ctx)
        if m is gen.PENDING:
            return gen.PENDING, self
        return m, _LedgerGen(self.seed, self.account,
                             self.remaining - 1)

    def update(self, test, ctx, event):
        return self


# ---------------------------------------------------------------------------
# Workloads / test
# ---------------------------------------------------------------------------

def append_workload(opts: dict) -> dict:
    w = workloads.txn_append.workload(
        {"ops": opts.get("ops", 2000),
         "key-count": opts.get("keys", 6),
         "seed": opts.get("seed")})
    w["client"] = pg.PgAppendClient(
        psql_factory=ProxyPsql,
        isolation=opts.get("isolation", "SERIALIZABLE"))
    return w


def ledger_workload(opts: dict) -> dict:
    return {
        "client": LedgerClient(
            isolation=opts.get("isolation", "SERIALIZABLE")),
        "generator": gen.limit(opts.get("ops", 400),
                               _LedgerGen(seed=opts.get("seed"))),
        "checker": ledger_checker(),
    }


WORKLOADS = {"append": append_workload, "ledger": ledger_workload}


def nemesis_for(opts: dict, db) -> dict:
    """Partitions + keeper kills through the package system
    (stolon/nemesis.clj's menu); DbNemesis' kill routes to
    StolonDB.kill = stop the keeper, so the sentinel must fail
    postgres over to a standby."""
    from ..nemesis import combined

    faults = set(opts.get("faults") or ("partition", "kill"))
    o = dict(opts)
    o.update(db=db, faults=faults,
             interval=opts.get("nemesis_interval", 10))
    return combined.compose_packages(combined.nemesis_packages(o))


def stolon_test(opts: dict) -> dict:
    name = opts.get("workload") or "append"
    w = WORKLOADS[name](opts)
    db = StolonDB(version=opts.get("version", VERSION))
    pkg = nemesis_for(opts, db)
    test = testing.noop_test()
    test.update(
        name=f"stolon-{name}",
        os=debian.os,
        db=db,
        ssh=opts["ssh"],
        nodes=opts["nodes"],
        concurrency=opts["concurrency"],
        client=w["client"],
        nemesis=pkg["nemesis"],
        checker=chk.compose({"workload": w["checker"],
                             "stats": chk.stats(),
                             "perf": chk.perf(),
                             "timeline": chk.timeline()}),
        generator=_suite_generator(opts, w, pkg))
    return test


def _suite_generator(opts, w, pkg):
    nemesis_gen = pkg.get("generator")
    client_part = gen.stagger(1.0 / opts.get("rate", 20),
                              w["generator"])
    mix = gen.time_limit(
        opts.get("time_limit", 60),
        gen.clients(client_part, nemesis_gen)
        if nemesis_gen is not None else gen.clients(client_part))
    parts = [mix]
    final_nem = pkg.get("final_generator")
    if final_nem:
        parts.append(gen.nemesis(final_nem))
    final = w.get("final_generator")
    if final is not None:
        parts.append(gen.sleep(opts.get("recovery_time", 10)))
        parts.append(gen.clients(final))
    return parts[0] if len(parts) == 1 else gen.phases(*parts)


def _opts(p):
    p.add_argument("--workload", default=None,
                   help="Workload (default append). "
                        + cli.one_of(WORKLOADS))
    p.add_argument("--rate", type=float, default=20)
    p.add_argument("--version", default=VERSION)
    return p


def main(argv=None) -> None:
    commands = {}
    commands.update(cli.single_test_cmd(stolon_test, parser_fn=_opts))
    commands.update(cli.serve_cmd())
    cli.run_cli(commands, argv)


if __name__ == "__main__":
    main()
