"""ZooKeeper test suite: a linearizable compare-and-set register over
a zk ensemble, driven entirely through the control plane.

Capability reference: zookeeper/src/jepsen/zookeeper.clj (the
reference's tutorial-grade suite, 145 LoC): node-id/myid + zoo.cfg
server-list construction (19-37), apt install + service restart DB
(40-72), a cas-register client (78-110: read / write / cas with
:info on timeout), and the test bundle with partitions + linearizable
checking (112-137). The reference talks to zk through a JVM client
library; here ops go through `zkCli.sh` on the node itself using the
3.4 dialect matching the pinned package: `get` prints the stat
(dataVersion) after the value, and `set path data version` is the
version-guarded write that gives cas. The suite needs no zk driver on
the control host.
"""

from __future__ import annotations

import logging
import re

from .. import checker as chk
from .. import cli, client as jclient, control, db as jdb
from .. import generator as gen
from .. import nemesis as jnemesis
from .. import testing
from ..checker import models
from ..control import util as cu
from ..control.core import Lit, RemoteError
from ..os_setup import debian

logger = logging.getLogger(__name__)

VERSION = "3.4.13-2"
CONF = "/etc/zookeeper/conf"
CLI = "/usr/share/zookeeper/bin/zkCli.sh"
LOG = "/var/log/zookeeper/zookeeper.log"
PORT = 2181
NODE_PATH = "/jepsen"

ZOO_CFG = """tickTime=2000
initLimit=10
syncLimit=5
dataDir=/var/lib/zookeeper
clientPort=2181
"""


def node_ids(test) -> dict:
    """node name -> zk server id (zookeeper.clj:19-30)."""
    return {node: i for i, node in enumerate(test["nodes"])}


def zoo_cfg_servers(test) -> str:
    return "\n".join(f"server.{i}={node}:2888:3888"
                     for node, i in node_ids(test).items())


class ZkDB(jdb.DB):
    """apt-installed zookeeperd with a generated ensemble config
    (zookeeper.clj db, 40-72)."""

    def __init__(self, version: str = VERSION):
        self.version = version

    def setup(self, test, node):
        logger.info("%s installing ZK %s", node, self.version)
        with control.su():
            debian.install({"zookeeper": self.version,
                            "zookeeperd": self.version})
            control.exec_("sh", "-c",
                          f"echo {node_ids(test)[node]} > {CONF}/myid")
            cfg = ZOO_CFG + zoo_cfg_servers(test) + "\n"
            cu.write_file(cfg, f"{CONF}/zoo.cfg")
            logger.info("%s ZK restarting", node)
            control.exec_("service", "zookeeper", "stop", check=False)
            control.exec_("service", "zookeeper", "start")
        cu.await_tcp_port(PORT, timeout_secs=60)
        logger.info("%s ZK ready", node)

    def teardown(self, test, node):
        logger.info("%s tearing down ZK", node)
        with control.su():
            control.exec_("service", "zookeeper", "stop", check=False)
            control.exec_("rm", "-rf",
                          Lit("/var/lib/zookeeper/version-*"),
                          Lit("/var/log/zookeeper/*"))

    def log_files(self, test, node):
        return [LOG]


_VALUE_RE = re.compile(r"^(\d+)\s*$", re.M)
_VERSION_RE = re.compile(r"dataVersion\s*=\s*(\d+)")


class ZkCasClient(jclient.Client):
    """CAS register at /jepsen via zkCli on the node: reads parse the
    value + dataVersion, cas re-writes with the read version as the
    positional guard (3.4 zkCli: `set path data version`) — optimistic
    concurrency, the zkCli analog of avout swap!!
    (zookeeper.clj:78-110)."""

    def __init__(self):
        self.node = None
        self.sess = None

    def open(self, test, node):
        c = ZkCasClient()
        c.node = node
        c.sess = control.session(test, node)
        return c

    def close(self, test):
        if self.sess is not None:
            control.disconnect(self.sess)

    def _cli(self, test, *cmd) -> str:
        with control.with_session(test, self.node, self.sess):
            return control.exec_(CLI, "-server",
                                 f"localhost:{PORT}", " ".join(cmd),
                                 timeout=10.0)

    def _read(self, test):
        """(value, dataVersion); creates the node on first touch. Only
        a definite NoNode triggers the create — any other error (e.g.
        a timeout mid-partition) propagates instead of burning two
        more zkCli launches."""
        try:
            out = self._cli(test, "get", NODE_PATH)
        except RemoteError as e:
            err = f"{e.err or ''} {e.out or ''}".lower()
            if "nonode" not in err:
                raise
            self._cli(test, "create", NODE_PATH, "0")
            out = self._cli(test, "get", NODE_PATH)
        vm = _VALUE_RE.search(out)
        ver = _VERSION_RE.search(out)
        return (int(vm.group(1)) if vm else None,
                int(ver.group(1)) if ver else None)

    def invoke(self, test, op):
        try:
            if op.f == "read":
                v, _ = self._read(test)
                return op.copy(type="ok", value=v)
            if op.f == "write":
                try:
                    self._cli(test, "set", NODE_PATH, str(op.value))
                except RemoteError:
                    self._cli(test, "create", NODE_PATH, str(op.value))
                return op.copy(type="ok")
            if op.f == "cas":
                old, new = op.value
                v, ver = self._read(test)
                if v != old or ver is None:
                    return op.copy(type="fail")
                try:
                    self._cli(test, "set", NODE_PATH, str(new),
                              str(ver))
                    return op.copy(type="ok")
                except RemoteError as e:
                    # Only the specific keeper error proves the write
                    # definitely did not happen; zkCli logs a
                    # "zookeeper.version=..." banner on every run, so
                    # substring-matching the whole message would turn
                    # indeterminate failures into false :fail
                    err = f"{e.err or ''} {e.out or ''}".lower()
                    if "badversion" in err:
                        return op.copy(type="fail")  # lost the race
                    raise
            raise ValueError(f"unknown f {op.f!r}")
        except Exception as e:  # noqa: BLE001
            if op.f == "read":
                # reads are side-effect free: a failed read is a
                # definite :fail, keeping the search space tight
                return op.copy(type="fail", error=repr(e))
            return op.copy(type="info", error=repr(e))


def zk_test(opts: dict) -> dict:
    """Test map from CLI options (zookeeper.clj zk-test, 112-137)."""
    import random

    from ..workloads import register as register_wl

    rng = random.Random(opts.get("seed"))

    test = testing.noop_test()
    test.update(
        name="zookeeper",
        os=debian.os,
        db=ZkDB(opts.get("version", VERSION)),
        ssh=opts.get("ssh", {}),
        nodes=opts["nodes"],
        concurrency=opts["concurrency"],
        client=ZkCasClient(),
        nemesis=jnemesis.partition_random_halves(),
        checker=chk.compose({
            "perf": chk.perf(),
            # the client creates /jepsen as 0 on first touch, so the
            # register's initial value is 0 (the reference's zk-atom
            # is likewise seeded with 0)
            "linear": chk.linearizable(
                {"model": models.cas_register(0)})}),
        # time-limit wraps the WHOLE generator (client + nemesis), as
        # the reference does — limiting only the client side leaves
        # the infinite nemesis cycle running forever
        generator=gen.time_limit(
            opts.get("time_limit", 15),
            gen.clients(
                gen.stagger(1.0,
                            lambda: register_wl.cas_op_mix(rng)),
                jnemesis.start_stop_cycle(5.0))))
    return test


def _opts(p):
    p.add_argument("--version", default=VERSION,
                   help="zookeeper package version to install.")
    return p


def main(argv=None) -> None:
    commands = {}
    commands.update(cli.single_test_cmd(zk_test, parser_fn=_opts))
    commands.update(cli.serve_cmd())
    cli.run_cli(commands, argv)


if __name__ == "__main__":
    main()
