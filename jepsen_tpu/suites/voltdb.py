"""VoltDB test suite: linearizable per-key registers and dirty-read
detection over sqlcmd against a k-safe cluster.

Capability reference: voltdb/src/jepsen/voltdb.clj (tarball install to
/opt/voltdb, a generated deployment.xml carrying sitesperhost +
kfactor, `voltdb create --deployment --host <primary>` on every node,
await the client port), single.clj (single-partition register table,
read/write/cas per independent key — CAS is a guarded UPDATE whose
modified-tuple count decides ok/fail), and dirty_read.clj (writers
insert, readers probe the in-flight row single-partition, and after
healing every client takes a multi-partition strong read; a value some
read saw that no strong read contains was a dirty read). The reference
drives the Java client; here every transaction is one sqlcmd batch on
the client's own node with tagged SELECTs carrying read results (the
tidb/galera transport stance — VoltDB speaks SQL over sqlcmd, and
its DML results arrive as modified-tuple counts)."""

from __future__ import annotations

import logging
import re

from .. import checker as chk
from .. import cli, client as jclient, control, db as jdb, independent
from .. import generator as gen
from .. import nemesis as jnemesis
from .. import testing, workloads
from . import common
from ..checker import models
from ..control import util as cu
from ..control.core import RemoteError
from ..core import primary
from ..os_setup import debian

logger = logging.getLogger(__name__)

VERSION = "6.8"
DIR = "/opt/voltdb"
CLIENT_PORT = 21212
HTTP_PORT = 8080
DEPLOYMENT = f"{DIR}/deployment.xml"
LOGFILE = f"{DIR}/stdout.log"
PIDFILE = f"{DIR}/voltdb.pid"


def deployment_xml(kfactor: int, sites_per_host: int = 2) -> str:
    """voltdb.clj deployment: k-safety + command logging, so a killed
    node replays its journal instead of forgetting acked writes."""
    return (
        "<?xml version=\"1.0\"?>\n"
        f"<deployment>\n"
        f"  <cluster sitesperhost=\"{sites_per_host}\" "
        f"kfactor=\"{kfactor}\" />\n"
        "  <commandlog enabled=\"true\" synchronous=\"true\">\n"
        "    <frequency time=\"2\" />\n"
        "  </commandlog>\n"
        "</deployment>\n")


class VoltdbDB(jdb.DB):
    """Tarball install + `voltdb create` on every node
    (voltdb.clj:40-120); the primary loads the schema once."""

    supports_kill = True

    def __init__(self, version: str = VERSION, kfactor: int | None = None):
        self.version = version
        self.kfactor = kfactor

    def _kfactor(self, test) -> int:
        # k-safety defaults to tolerating a minority (voltdb.clj)
        if self.kfactor is not None:
            return self.kfactor
        return max(0, (len(test["nodes"]) - 1) // 2)

    def _start(self, test, node):
        cu.start_daemon(
            {"logfile": LOGFILE, "pidfile": PIDFILE, "chdir": DIR},
            f"{DIR}/bin/voltdb", "create",
            "--deployment", DEPLOYMENT,
            "--host", str(primary(test)))
        cu.await_tcp_port(CLIENT_PORT, timeout_secs=180)

    def setup(self, test, node):
        logger.info("%s installing voltdb %s", node, self.version)
        with control.su():
            debian.install(["openjdk-8-jdk"])
            url = (f"https://downloads.voltdb.com/technologies/server/"
                   f"voltdb-community-{self.version}.tar.gz")
            cu.install_archive(url, DIR)
            control.exec_("bash", "-c",
                          f"cat > {DEPLOYMENT} <<'EOF'\n"
                          f"{deployment_xml(self._kfactor(test))}EOF")
            self._start(test, node)
        from .. import core

        core.synchronize(test)
        if node == primary(test):
            self._schema(node)
        core.synchronize(test)

    def _schema(self, node):
        stmts = [
            "CREATE TABLE registers (id INTEGER NOT NULL, "
            "value INTEGER NOT NULL, PRIMARY KEY (id));",
            "PARTITION TABLE registers ON COLUMN id;",
            "CREATE TABLE dirty_reads (id INTEGER NOT NULL, "
            "PRIMARY KEY (id));",
            "PARTITION TABLE dirty_reads ON COLUMN id;",
        ]
        control.exec_(f"{DIR}/bin/sqlcmd", f"--servers={node}",
                      "--query=" + " ".join(stmts))

    def teardown(self, test, node):
        logger.info("%s tearing down voltdb", node)
        with control.su():
            cu.grepkill("org.voltdb.VoltDB")
            control.exec_("rm", "-rf", DIR)

    def kill(self, test, node):
        with control.su():
            cu.grepkill("org.voltdb.VoltDB")
        return "killed"

    def start(self, test, node):
        with control.su():
            self._start(test, node)
        return "started"

    def log_files(self, test, node):
        return [LOGFILE, f"{DIR}/voltdbroot/log/volt.log"]


# ---------------------------------------------------------------------------
# sqlcmd transport
# ---------------------------------------------------------------------------

class VoltSql(common.SqlCli):
    """sqlcmd batches against the node's own server. sqlcmd takes the
    statement list as one --query= token, so run() folds the batch into
    the final argv element instead of appending it."""

    def __init__(self, test, node, timeout: float = 10.0):
        super().__init__(
            test, node,
            [f"{DIR}/bin/sqlcmd", f"--servers={node}",
             "--output-skip-metadata", "--query="],
            timeout=timeout)

    def run(self, sql: str) -> str:
        argv = self.argv[:-1] + [self.argv[-1] + sql]
        with control.with_session(self.test, self.node, self.sess):
            return control.exec_(*argv, timeout=self.timeout)


_classify = common.make_classifier([
    r"connection refused", r"no connections", r"server is paused",
    r"unable to connect", r"connection to database host"])


def _count(out: str) -> int:
    """The modified-tuple count a DML statement prints as its result
    row (the first bare integer line; voltdb surfaces DML results as
    one-column counts)."""
    for line in out.splitlines():
        s = line.strip()
        if re.fullmatch(r"-?\d+", s):
            return int(s)
    return 0


class VoltRegisterClient(jclient.Client):
    """Independent-key read/write/cas on the partitioned registers
    table (single.clj). CAS is the guarded single-partition UPDATE;
    its modified count decides ok vs fail."""

    def __init__(self, sql_factory=VoltSql):
        self.sql_factory = sql_factory
        self.sql = None

    def open(self, test, node):
        c = VoltRegisterClient(self.sql_factory)
        c.sql = self.sql_factory(test, node)
        return c

    def close(self, test):
        if self.sql is not None:
            self.sql.close()

    def invoke(self, test, op):
        k, v = independent.key_(op.value), independent.value_(op.value)
        try:
            if op.f == "read":
                out = self.sql.run(
                    "SELECT 'v=' || CAST(value AS VARCHAR) FROM "
                    f"registers WHERE id = {int(k)};")
            elif op.f == "write":
                self.sql.run(
                    f"UPSERT INTO registers (id, value) VALUES "
                    f"({int(k)}, {int(v)});")
                return op.copy(type="ok")
            elif op.f == "cas":
                old, new = v
                out = self.sql.run(
                    f"UPDATE registers SET value = {int(new)} WHERE "
                    f"id = {int(k)} AND value = {int(old)};")
                return op.copy(
                    type="ok" if _count(out) > 0 else "fail",
                    error=None if _count(out) > 0 else "cas mismatch")
            else:
                raise ValueError(f"unknown f {op.f!r}")
        except RemoteError as e:
            return _classify(op, e)
        # parse OUTSIDE the error net: a corrupt value is evidence
        m = re.search(r"v=(-?\d+)", out)
        return op.copy(type="ok", value=independent.ktuple(
            k, int(m.group(1)) if m else None))


class VoltDirtyReadClient(jclient.Client):
    """dirty_read.clj contract: write inserts the row, read probes it
    single-partition (ok iff visible), strong-read scans the whole
    table multi-partition. refresh is a no-op ack — VoltDB commits are
    immediately visible on the partition owner; the phase exists for
    generator parity with the eventually-consistent suites."""

    def __init__(self, sql_factory=VoltSql):
        self.sql_factory = sql_factory
        self.sql = None

    def open(self, test, node):
        c = VoltDirtyReadClient(self.sql_factory)
        c.sql = self.sql_factory(test, node)
        return c

    def close(self, test):
        if self.sql is not None:
            self.sql.close()

    def invoke(self, test, op):
        try:
            if op.f == "write":
                out = self.sql.run(
                    "INSERT INTO dirty_reads (id) VALUES "
                    f"({int(op.value)});")
                return op.copy(
                    type="ok" if _count(out) > 0 else "fail")
            if op.f == "read":
                out = self.sql.run(
                    "SELECT 'v=' || CAST(id AS VARCHAR) FROM "
                    f"dirty_reads WHERE id = {int(op.value)};")
                seen = re.search(r"v=(-?\d+)", out) is not None
                return op.copy(type="ok" if seen else "fail")
            if op.f == "refresh":
                return op.copy(type="ok")
            if op.f == "strong-read":
                out = self.sql.run(
                    "SELECT 'i=' || CAST(id AS VARCHAR) FROM "
                    "dirty_reads ORDER BY id;")
                vals = sorted(int(m.group(1)) for m in
                              re.finditer(r"i=(-?\d+)", out))
                return op.copy(type="ok", value=vals)
            raise ValueError(f"unknown f {op.f!r}")
        except RemoteError as e:
            return _classify(op, e)


# ---------------------------------------------------------------------------
# Workloads / test
# ---------------------------------------------------------------------------

def register_workload(opts: dict) -> dict:
    """Linearizable reads/writes/cas per independent key
    (single.clj workload)."""
    import random

    rng = random.Random(opts.get("seed"))
    keys = list(range(opts.get("keys", 4)))

    def key_gen(_k):
        return gen.limit(
            opts.get("ops_per_key", 200),
            gen.mix([lambda: {"f": "read", "value": None},
                     lambda: {"f": "write",
                              "value": rng.randrange(5)},
                     lambda: {"f": "cas",
                              "value": [rng.randrange(5),
                                        rng.randrange(5)]}]))

    return {
        "client": VoltRegisterClient(),
        "generator": independent.concurrent_generator(
            opts["concurrency"], keys, key_gen),
        "checker": independent.checker(chk.linearizable(
            {"model": models.cas_register()})),
    }


def dirty_read_workload(opts: dict) -> dict:
    w = workloads.dirty_read.workload(dict(opts))
    w["client"] = VoltDirtyReadClient()
    return w


WORKLOADS = {"register": register_workload,
             "dirty-read": dirty_read_workload}


def voltdb_test(opts: dict) -> dict:
    """Test map from CLI options (jepsen.voltdb/voltdb-test)."""
    name = opts.get("workload") or "register"
    w = WORKLOADS[name](opts)
    db = VoltdbDB(opts.get("version", VERSION),
                  kfactor=opts.get("kfactor"))
    main = gen.time_limit(
        opts.get("time_limit", 30),
        gen.clients(
            gen.stagger(1.0 / opts.get("rate", 10), w["generator"]),
            jnemesis.start_stop_cycle(10.0)))
    phases = [main,
              gen.nemesis(gen.once({"type": "info", "f": "stop"})),
              gen.sleep(opts.get("recovery_time", 10))]
    if w.get("final_generator"):
        phases.append(gen.clients(w["final_generator"]))
    test = testing.noop_test()
    test.update(
        name=f"voltdb-{name}",
        os=debian.os,
        db=db,
        ssh=opts["ssh"],
        nodes=opts["nodes"],
        concurrency=opts["concurrency"],
        client=w["client"],
        nemesis=jnemesis.partition_random_halves(),
        checker=chk.compose({"workload": w["checker"],
                             "stats": chk.stats(),
                             "perf": chk.perf(),
                             "timeline": chk.timeline()}),
        generator=gen.phases(*phases))
    return test


def _opts(p):
    p.add_argument("--workload", default="register",
                   help="Workload. " + cli.one_of(WORKLOADS))
    p.add_argument("--version", default=VERSION,
                   help="voltdb community version to install.")
    p.add_argument("--rate", type=float, default=10)
    p.add_argument("--kfactor", type=int, default=None,
                   help="k-safety factor (default: tolerate a "
                        "minority).")
    return p


def main(argv=None) -> None:
    commands = {}
    commands.update(cli.single_test_cmd(voltdb_test, parser_fn=_opts))
    commands.update(cli.serve_cmd())
    cli.run_cli(commands, argv)


if __name__ == "__main__":
    main()
