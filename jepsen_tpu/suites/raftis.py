"""Raftis (redis + raft) test suite: a linearizable register and a
counter over redis-cli.

Capability reference: raftis/src/jepsen/raftis.clj — tarball install
with the host:8901 initial-cluster string (70-100), a read/write
register client over the redis protocol with no-leader/socket errors
mapped to definite fails and indeterminate writes to info (28-62),
partitions + linearizable checking (the reference's test map). The
reference links the carmine redis client into the JVM; here ops run
`redis-cli` on the node over the control plane. Beyond the
reference's register, the suite also exercises the counter checker
through INCRBY/DECRBY — atomic in redis, so the counter's bounds
hold on a healthy cluster.
"""

from __future__ import annotations

import logging
import random

from .. import checker as chk
from .. import cli, client as jclient, control, db as jdb
from .. import generator as gen
from .. import nemesis as jnemesis
from .. import testing
from ..checker import models
from ..control import util as cu
from ..control.core import RemoteError
from ..os_setup import debian

logger = logging.getLogger(__name__)

VERSION = "v1.0"
DIR = "/opt/raftis"
BINARY = f"{DIR}/raftis"
LOGFILE = f"{DIR}/raftis.log"
PIDFILE = f"{DIR}/raftis.pid"
PORT = 6379
PEER_PORT = 8901


def initial_cluster(test) -> str:
    """node:8901,... (raftis.clj initial-cluster, 73-80)."""
    return ",".join(f"{n}:{PEER_PORT}" for n in test["nodes"])


class RaftisDB(jdb.DB):
    """Tarball install + daemon with the peer cluster string
    (raftis.clj db, 83-110)."""

    supports_kill = True

    def __init__(self, version: str = VERSION):
        self.version = version

    def _start(self, test, node):
        cu.start_daemon(
            {"logfile": LOGFILE, "pidfile": PIDFILE, "chdir": DIR},
            BINARY,
            "--cluster", initial_cluster(test),
            "--local_ip", str(node),
            "--local_port", str(PEER_PORT),
            "--listen_port", str(PORT))

    def setup(self, test, node):
        logger.info("%s installing raftis %s", node, self.version)
        with control.su():
            debian.install(["redis-tools"])  # the client transport
            url = (f"https://github.com/PikaLabs/floyd/releases/"
                   f"download/{self.version}/raftis-"
                   f"{self.version}.tar.gz")
            cu.install_archive(url, DIR)
            self._start(test, node)
        cu.await_tcp_port(PORT, timeout_secs=60)

    def teardown(self, test, node):
        logger.info("%s tearing down raftis", node)
        with control.su():
            cu.stop_daemon(BINARY, PIDFILE)
            control.exec_("rm", "-rf", DIR)

    def kill(self, test, node):
        with control.su():
            cu.grepkill("raftis")
        return "killed"

    def start(self, test, node):
        with control.su():
            self._start(test, node)
        return "started"

    def log_files(self, test, node):
        return [LOGFILE]


# ---------------------------------------------------------------------------
# redis-cli transport
# ---------------------------------------------------------------------------

class RedisCli:
    """One redis-cli command on the node. Split out so tests can stub
    `run`.

    Uses a NON-retrying session: SET/INCRBY are not idempotent, and
    the default control stack's transport retry would re-execute a
    command whose connection dropped AFTER it ran — double-applying an
    increment the history records once (the same double-execution
    hazard control/ssh.py's timeout path documents)."""

    def __init__(self, test, node, timeout: float = 5.0):
        self.test = test
        self.node = node
        self.timeout = timeout
        self.sess = self._session(test, node)

    @staticmethod
    def _session(test, node):
        if test.get("remote") is not None or \
                (test.get("ssh") or {}).get("dummy"):
            return control.session(test, node)
        from ..control.scp import ScpRemote
        from ..control.ssh import SshRemote

        return ScpRemote(SshRemote()).connect(
            control.conn_spec(test, node))

    def run(self, *args) -> str:
        with control.with_session(self.test, self.node, self.sess):
            return control.exec_("redis-cli", "-h", str(self.node),
                                 "-p", str(PORT), *args,
                                 timeout=self.timeout)

    def close(self):
        control.disconnect(self.sess)


_DEFINITE = ("no leader", "socket closed", "connection refused",
             "could not connect")

# redis error replies arrive on stdout with exit 0; NON-tty redis-cli
# (what exec gives us) prints them raw, tty mode wraps them in
# "(error) ..." — accept both
_ERROR_PREFIXES = ("(error)", "ERR ", "-ERR", "WRONGTYPE", "MOVED",
                   "CLUSTERDOWN", "LOADING", "NOAUTH", "READONLY")


class _ErrorReply(Exception):
    """The server REJECTED the command — it definitely did not apply
    (the reference's no-leader -> :fail mapping generalized)."""


def _reply(out: str) -> str:
    s = out.strip()
    if s.startswith(_ERROR_PREFIXES):
        raise _ErrorReply(s)
    return s


def _classify(op, e: Exception):
    if isinstance(e, _ErrorReply):
        return op.copy(type="fail", error=str(e)[:200])
    msg = f"{getattr(e, 'err', '')} {getattr(e, 'out', '')} {e}".lower()
    if op.f == "read" or any(m in msg for m in _DEFINITE):
        return op.copy(type="fail", error=msg.strip()[:200])
    return op.copy(type="info", error=msg.strip()[:200])


class RaftisRegisterClient(jclient.Client):
    """Read/write register at key "r" (raftis.clj client, 28-62).
    redis-cli prints errors like "(error) ERR ..." on stdout with exit
    0, so replies are checked, not just exit codes."""

    def __init__(self, cli_factory=RedisCli):
        self.cli_factory = cli_factory
        self.cli = None

    def open(self, test, node):
        c = RaftisRegisterClient(self.cli_factory)
        c.cli = self.cli_factory(test, node)
        return c

    def close(self, test):
        if self.cli is not None:
            self.cli.close()

    def invoke(self, test, op):
        try:
            if op.f == "read":
                out = _reply(self.cli.run("GET", "r"))
                return op.copy(type="ok",
                               value=int(out) if out else None)
            if op.f == "write":
                out = _reply(self.cli.run("SET", "r", str(op.value)))
                if out != "OK":
                    # unrecognized non-OK reply: indeterminate
                    raise RemoteError("unexpected SET reply", exit=0,
                                      out=out, err="", cmd="SET",
                                      node=None)
                return op.copy(type="ok")
            raise ValueError(f"unknown f {op.f!r}")
        except (RemoteError, _ErrorReply) as e:
            return _classify(op, e)


class RaftisCounterClient(jclient.Client):
    """Counter at key "c": INCRBY/DECRBY are atomic; reads report the
    current value for checker.counter's concurrent-bounds analysis."""

    def __init__(self, cli_factory=RedisCli):
        self.cli_factory = cli_factory
        self.cli = None

    def open(self, test, node):
        c = RaftisCounterClient(self.cli_factory)
        c.cli = self.cli_factory(test, node)
        return c

    def close(self, test):
        if self.cli is not None:
            self.cli.close()

    def invoke(self, test, op):
        try:
            if op.f == "add":
                delta = int(op.value)
                cmd = ("INCRBY", "c", str(delta)) if delta >= 0 \
                    else ("DECRBY", "c", str(-delta))
                out = _reply(self.cli.run(*cmd))
                if not out.lstrip("-").isdigit():
                    raise RemoteError("unexpected reply", exit=0,
                                      out=out, err="", cmd=cmd[0],
                                      node=None)
                return op.copy(type="ok")
            if op.f == "read":
                out = _reply(self.cli.run("GET", "c"))
                return op.copy(type="ok",
                               value=int(out) if out else 0)
            raise ValueError(f"unknown f {op.f!r}")
        except (RemoteError, _ErrorReply) as e:
            return _classify(op, e)


# ---------------------------------------------------------------------------
# Workloads / test
# ---------------------------------------------------------------------------

def register_workload(opts: dict) -> dict:
    rng = random.Random(opts.get("seed"))

    def one():
        if rng.random() < 0.5:
            return {"f": "read", "value": None}
        return {"f": "write", "value": rng.randrange(5)}

    return {
        "client": RaftisRegisterClient(),
        "generator": gen.limit(opts.get("ops", 500), one),
        "checker": chk.linearizable(
            {"model": models.register()}),
    }


def counter_workload(opts: dict) -> dict:
    from ..workloads import counter

    w = counter.workload({"ops": opts.get("ops", 500),
                          "seed": opts.get("seed")})
    w["client"] = RaftisCounterClient()
    return w


WORKLOADS = {"register": register_workload,
             "counter": counter_workload}


def raftis_test(opts: dict) -> dict:
    name = opts.get("workload") or "register"
    w = WORKLOADS[name](opts)
    test = testing.noop_test()
    test.update(
        name=f"raftis-{name}",
        os=debian.os,
        db=RaftisDB(opts.get("version", VERSION)),
        ssh=opts["ssh"],
        nodes=opts["nodes"],
        concurrency=opts["concurrency"],
        client=w["client"],
        nemesis=jnemesis.partition_random_halves(),
        checker=chk.compose({"workload": w["checker"],
                             "stats": chk.stats(),
                             "perf": chk.perf(),
                             "timeline": chk.timeline()}),
        generator=gen.time_limit(
            opts.get("time_limit", 30),
            gen.clients(
                gen.stagger(1.0 / opts.get("rate", 20),
                            w["generator"]),
                jnemesis.start_stop_cycle(10.0))))
    return test


def _opts(p):
    p.add_argument("--workload", default=None,
                   help="Workload (default register). "
                        + cli.one_of(WORKLOADS))
    p.add_argument("--version", default=VERSION,
                   help="raftis release tag to install.")
    p.add_argument("--rate", type=float, default=20)
    return p


def main(argv=None) -> None:
    commands = {}
    commands.update(cli.single_test_cmd(raftis_test, parser_fn=_opts))
    commands.update(cli.serve_cmd())
    cli.run_cli(commands, argv)


if __name__ == "__main__":
    main()
