"""Consul test suite: a linearizable register per independent key over
the HTTP KV API, with index-based compare-and-set.

Capability reference: consul/src/jepsen/consul/db.clj (zip-binary
install + agent daemon with -bootstrap on the primary and -retry-join
everywhere else, await catalog convergence), consul/client.clj (KV
reads return base64 values + ModifyIndex; CAS is index-based: read the
index, then PUT ?cas=<index>; with-errors maps 404/403/500), and
consul/register.clj (independent-key register workload with a reserved
read pool). Consistency levels ("stale"/"consistent"/default) thread
through every request as query params, as the reference's
--consistency flag does.
"""

from __future__ import annotations

import base64
import json
import logging
import random
import urllib.error
import urllib.parse
import urllib.request

from .. import checker as chk
from .. import cli, client as jclient, control, db as jdb, independent
from .. import generator as gen
from .. import nemesis as jnemesis
from .. import net, testing
from ..checker import models
from ..control import util as cu
from ..core import primary
from ..os_setup import debian

logger = logging.getLogger(__name__)

VERSION = "1.6.1"
DIR = "/opt"
BINARY = f"{DIR}/consul"
PIDFILE = "/var/run/consul.pid"
LOGFILE = "/var/log/consul.log"
DATA_DIR = "/var/lib/consul"
HTTP_PORT = 8500
RETRY_INTERVAL = "5s"

CONSISTENCY_LEVELS = {"stale", "consistent"}


# ---------------------------------------------------------------------------
# HTTP KV client
# ---------------------------------------------------------------------------

class ConsulHttp:
    """Minimal consul KV driver (consul/client.clj). Split out so
    tests can stub `request`."""

    def __init__(self, node, consistency: str | None = None,
                 timeout: float = 5.0):
        self.base = f"http://{node}:{HTTP_PORT}"
        self.consistency = consistency
        self.timeout = timeout

    def request(self, method: str, path: str, params: dict | None = None,
                body: str | None = None) -> tuple[int, str]:
        """(status, body). 404 comes back as a status, not an
        exception; other HTTP errors raise."""
        url = self.base + path
        if params:
            url += "?" + urllib.parse.urlencode(
                {k: ("" if v is None else v) for k, v in params.items()})
        req = urllib.request.Request(
            url, method=method,
            data=body.encode() if body is not None else None)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return 404, ""
            raise

    def _params(self, extra: dict | None = None) -> dict:
        p = dict(extra or {})
        if self.consistency:
            p[self.consistency] = None
        return p

    def get(self, key: str):
        """(value, modify_index) or (None, None) for a missing key.
        Values arrive base64-encoded (consul/client.clj parse-body)."""
        status, out = self.request("GET", f"/v1/kv/{key}",
                                   self._params())
        if status == 404 or not out:
            return None, None
        entry = json.loads(out)[0]
        raw = entry.get("Value")
        value = (base64.b64decode(raw).decode()
                 if raw is not None else None)
        return value, int(entry.get("ModifyIndex", 0))

    def put(self, key: str, value: str) -> None:
        self.request("PUT", f"/v1/kv/{key}", self._params(), value)

    def cas(self, key: str, old: str, new: str) -> bool:
        """Index-based CAS: read the current value + ModifyIndex, then
        PUT ?cas=<index> iff the value matched
        (consul/client.clj cas!, 64-90)."""
        value, index = self.get(key)
        if value != old or index is None:
            return False
        _status, out = self.request(
            "PUT", f"/v1/kv/{key}", self._params({"cas": index}), new)
        return out.strip() == "true"

    def catalog_nodes(self) -> list:
        _status, out = self.request("GET", "/v1/catalog/nodes")
        return json.loads(out) if out else []


def await_cluster_ready(http: ConsulHttp, n_nodes: int,
                        timeout_secs: float = 60.0) -> None:
    """Blocks until the catalog lists every node
    (consul/client.clj await-cluster-ready)."""
    from .. import util

    def check():
        n = len(http.catalog_nodes())
        if n < n_nodes:
            raise RuntimeError(
                f"only {n}/{n_nodes} nodes in consul catalog")

    util.await_fn(check, timeout_secs=timeout_secs,
                  log_message="waiting for consul catalog")



class ConsulDB(jdb.DB):
    """Installs the consul binary and runs the server agent
    (consul/db.clj:23-92): the primary bootstraps, the rest
    retry-join it."""

    supports_kill = True

    def __init__(self, version: str = VERSION,
                 http_factory=ConsulHttp):
        self.version = version
        # injectable for clusterless tests; None skips the catalog
        # await (the tcp-port await already gates liveness)
        self.http_factory = http_factory

    def _start_agent(self, test, node, bootstrap: bool):
        """One flag list for every start path. Fresh setup bootstraps
        on the primary; restarts always rejoin (a killed primary's
        peers already hold the raft state)."""
        args = [BINARY, "agent", "-server",
                "-log-level", "debug",
                "-client", "0.0.0.0",
                "-bind", net.ip(node),
                "-data-dir", DATA_DIR,
                "-node", str(node),
                "-retry-interval", RETRY_INTERVAL]
        if bootstrap:
            args += ["-bootstrap"]
        else:
            args += ["-retry-join", net.ip(primary(test))]
        cu.start_daemon({"logfile": LOGFILE, "pidfile": PIDFILE,
                         "chdir": DIR}, *args)

    def setup(self, test, node):
        logger.info("%s installing consul %s", node, self.version)
        with control.su():
            url = (f"https://releases.hashicorp.com/consul/"
                   f"{self.version}/consul_{self.version}"
                   f"_linux_amd64.zip")
            cu.install_archive(url, BINARY)
            self._start_agent(test, node, node == primary(test))
        cu.await_tcp_port(HTTP_PORT, timeout_secs=60)
        if self.http_factory is not None:
            await_cluster_ready(self.http_factory(node),
                                len(test["nodes"]))

    def teardown(self, test, node):
        logger.info("%s tearing down consul", node)
        with control.su():
            cu.stop_daemon(BINARY, PIDFILE)
            control.exec_("rm", "-rf", PIDFILE, LOGFILE, DATA_DIR,
                          BINARY)

    def kill(self, test, node):
        with control.su():
            cu.grepkill("consul")
        return "killed"

    def start(self, test, node):
        with control.su():
            self._start_agent(test, node, bootstrap=False)
        return "started"

    def log_files(self, test, node):
        return [LOGFILE]


# ---------------------------------------------------------------------------
# Clients
# ---------------------------------------------------------------------------

class ConsulRegisterClient(jclient.Client):
    """Independent-key register ops over the KV API
    (consul/register.clj Client). Reads of a missing key are None (the
    register's initial state); read failures are definite :fail (reads
    are side-effect free), write/cas failures :info unless the
    connection was refused outright."""

    def __init__(self, http_factory=ConsulHttp,
                 consistency: str | None = None):
        self.http_factory = http_factory
        self.consistency = consistency
        self.http = None

    def open(self, test, node):
        c = ConsulRegisterClient(self.http_factory, self.consistency)
        c.http = self.http_factory(node, consistency=self.consistency)
        return c

    def invoke(self, test, op):
        k, v = independent.key_(op.value), independent.value_(op.value)
        key = f"register/{k}"
        try:
            if op.f == "read":
                raw, _idx = self.http.get(key)
            elif op.f == "write":
                self.http.put(key, str(v))
                return op.copy(type="ok")
            elif op.f == "cas":
                old, new = v
                ok = self.http.cas(key, str(old), str(new))
                return op.copy(type="ok" if ok else "fail")
            else:
                raise ValueError(f"unknown f {op.f!r}")
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            if op.f == "read" or jclient.definite_http_failure(e):
                return op.copy(type="fail", error=repr(e))
            return op.copy(type="info", error=repr(e))
        # Parse OUTSIDE the network-error net: a corrupt value is
        # evidence, not a clean network :fail — let it crash the op
        # (the interpreter records :info with the exception)
        return op.copy(type="ok", value=independent.ktuple(
            k, None if raw is None else int(raw)))


# ---------------------------------------------------------------------------
# Workloads / test
# ---------------------------------------------------------------------------

def register_workload(opts: dict) -> dict:
    """Linearizable reads/writes/cas on independent keys, with a
    reserved read pool like the reference
    (consul/register.clj workload: reserve 5 r over mix [w cas])."""
    rng = random.Random(opts.get("seed"))

    def r(_rng):
        return {"f": "read", "value": None}

    def w(rng):
        return {"f": "write", "value": rng.randrange(5)}

    def cas(rng):
        return {"f": "cas",
                "value": [rng.randrange(5), rng.randrange(5)]}

    keys = list(range(opts.get("keys", 4)))
    # Reserve a read pool like the reference, but never ALL threads:
    # at concurrency 1 a reserved reader would starve the write/cas
    # mix and the test would vacuously pass on a never-written register
    reserved = min(5, opts["concurrency"] // 2)

    def key_gen(k):
        if reserved:
            body = gen.reserve(reserved, lambda: r(rng),
                               gen.mix([lambda: w(rng),
                                        lambda: cas(rng)]))
        else:
            body = gen.mix([lambda: r(rng), lambda: w(rng),
                            lambda: cas(rng)])
        return gen.limit(opts.get("ops_per_key", 200), body)

    return {
        "client": ConsulRegisterClient(
            consistency=opts.get("consistency")),
        "generator": independent.concurrent_generator(
            opts["concurrency"], keys, key_gen),
        "checker": independent.checker(chk.linearizable(
            {"model": models.cas_register()})),
    }


WORKLOADS = {"register": register_workload}


def consul_test(opts: dict) -> dict:
    """Test map from CLI options (jepsen.consul/consul-test)."""
    name = opts.get("workload", "register")
    w = WORKLOADS[name](opts)
    test = testing.noop_test()
    test.update(
        name=f"consul-{name}",
        os=debian.os,
        db=ConsulDB(opts.get("version", VERSION)),
        ssh=opts["ssh"],
        nodes=opts["nodes"],
        concurrency=opts["concurrency"],
        client=w["client"],
        nemesis=jnemesis.partition_random_halves(),
        checker=chk.compose({"workload": w["checker"],
                             "stats": chk.stats(),
                             "perf": chk.perf(),
                             "timeline": chk.timeline()}),
        generator=gen.phases(
            gen.time_limit(
                opts.get("time_limit", 30),
                gen.clients(
                    gen.stagger(1.0 / opts.get("rate", 10),
                                w["generator"]),
                    jnemesis.start_stop_cycle(10.0))),
            # heal and let the cluster settle before final analysis
            gen.nemesis(gen.once({"type": "info", "f": "stop"})),
            gen.sleep(opts.get("recovery_time", 10))))
    return test


def _opts(p):
    p.add_argument("--workload", default="register",
                   help="Workload. " + cli.one_of(WORKLOADS))
    p.add_argument("--version", default=VERSION,
                   help="consul version to install.")
    p.add_argument("--rate", type=float, default=10)
    p.add_argument("--consistency", default=None,
                   choices=sorted(CONSISTENCY_LEVELS),
                   help="KV request consistency level "
                        "(default: consul's default).")
    return p


def main(argv=None) -> None:
    commands = {}
    commands.update(cli.single_test_cmd(consul_test, parser_fn=_opts))
    commands.update(cli.serve_cmd())
    cli.run_cli(commands, argv)


if __name__ == "__main__":
    main()
