"""Disque (the redis-family distributed message broker) test suite:
queue conservation over the `disque` CLI.

Capability reference: jepsen's disque test (aphyr/jepsen disque/src/
jepsen/disque.clj) — source build + disque-server daemon, cluster-meet
topology from the primary, an enqueue/dequeue/drain client over the
disque protocol with ADDJOB/GETJOB/ACKJOB, and total-queue checking
under partitions. The reference links the jedisque JVM client; here
ops run the bundled `disque` CLI on the node over the control plane,
the same transport pattern as the raftis suite. Every dequeue ACKs the
job it fetched — an unacked GETJOB is redelivered by design, so a
crashed dequeue yields a duplicate (visible to total-queue) rather
than a lost message.
"""

from __future__ import annotations

import logging

from .. import checker as chk
from .. import cli, client as jclient, control, db as jdb
from .. import generator as gen
from .. import nemesis as jnemesis
from .. import testing
from ..control import util as cu
from ..control.core import RemoteError
from ..os_setup import debian

logger = logging.getLogger(__name__)

VERSION = "1.0-rc1"
DIR = "/opt/disque"
BINARY = f"{DIR}/src/disque-server"
CLI_BIN = f"{DIR}/src/disque"
LOGFILE = f"{DIR}/disque.log"
PIDFILE = f"{DIR}/disque.pid"
PORT = 7711
QUEUE = "jepsen"


class DisqueDB(jdb.DB):
    """Source build + daemon + cluster meet (disque.clj db)."""

    supports_kill = True

    def __init__(self, version: str = VERSION):
        self.version = version

    def _start(self, test, node):
        cu.start_daemon(
            {"logfile": LOGFILE, "pidfile": PIDFILE, "chdir": DIR},
            BINARY,
            "--port", str(PORT),
            "--appendonly", "yes",
            "--appendfsync", "everysec")

    def setup(self, test, node):
        logger.info("%s installing disque %s", node, self.version)
        with control.su():
            debian.install(["build-essential"])
            url = (f"https://github.com/antirez/disque/archive/"
                   f"refs/tags/{self.version}.tar.gz")
            cu.install_archive(url, DIR)
            with control.cd(DIR):
                control.exec_("make")
            self._start(test, node)
        cu.await_tcp_port(PORT, timeout_secs=60)
        # mesh the cluster: every node meets every other (the
        # reference meets from one node; symmetric meets converge to
        # the same gossip view and need no primary election)
        for other in test["nodes"]:
            if str(other) != str(node):
                control.exec_(CLI_BIN, "-p", str(PORT),
                              "cluster", "meet", str(other),
                              str(PORT))

    def teardown(self, test, node):
        logger.info("%s tearing down disque", node)
        with control.su():
            cu.stop_daemon(BINARY, PIDFILE)
            control.exec_("rm", "-rf", DIR)

    def kill(self, test, node):
        with control.su():
            cu.grepkill("disque-server")
        return "killed"

    def start(self, test, node):
        with control.su():
            self._start(test, node)
        return "started"

    def log_files(self, test, node):
        return [LOGFILE]


# ---------------------------------------------------------------------------
# disque CLI transport
# ---------------------------------------------------------------------------

class DisqueCli:
    """One `disque` CLI command on the node. Split out so tests can
    stub `run`. Non-retrying session: ADDJOB is not idempotent — a
    transport retry after the broker accepted the job double-enqueues
    a message the history records once (the raftis RedisCli
    rationale)."""

    def __init__(self, test, node, timeout: float = 5.0):
        self.test = test
        self.node = node
        self.timeout = timeout
        self.sess = self._session(test, node)

    @staticmethod
    def _session(test, node):
        if test.get("remote") is not None or \
                (test.get("ssh") or {}).get("dummy"):
            return control.session(test, node)
        from ..control.scp import ScpRemote
        from ..control.ssh import SshRemote

        return ScpRemote(SshRemote()).connect(
            control.conn_spec(test, node))

    def run(self, *args) -> str:
        with control.with_session(self.test, self.node, self.sess):
            return control.exec_(CLI_BIN, "-p", str(PORT), *args,
                                 timeout=self.timeout)

    def close(self):
        control.disconnect(self.sess)


_DEFINITE = ("noreplica", "connection refused", "could not connect",
             "pausing", "loading")

_ERROR_PREFIXES = ("(error)", "ERR ", "-ERR", "NOREPLICA", "PAUSED",
                   "LOADING", "BUSYKEY")


class _ErrorReply(Exception):
    """The broker REJECTED the command — it definitely did not apply."""


def _reply(out: str) -> str:
    s = out.strip()
    if s.startswith(_ERROR_PREFIXES):
        raise _ErrorReply(s)
    return s


def _classify(op, e: Exception):
    if isinstance(e, _ErrorReply):
        return op.copy(type="fail", error=str(e)[:200])
    msg = f"{getattr(e, 'err', '')} {getattr(e, 'out', '')} {e}".lower()
    if any(m in msg for m in _DEFINITE):
        return op.copy(type="fail", error=msg.strip()[:200])
    return op.copy(type="info", error=msg.strip()[:200])


class DisqueQueueClient(jclient.Client):
    """enqueue -> ADDJOB, dequeue -> GETJOB + ACKJOB, drain -> GETJOB
    until empty (disque.clj client). An indeterminate dequeue whose
    GETJOB fetched but whose ACK was lost redelivers — total-queue
    reports it as duplicated, never lost."""

    def __init__(self, cli_factory=DisqueCli):
        self.cli_factory = cli_factory
        self.cli = None

    def open(self, test, node):
        c = DisqueQueueClient(self.cli_factory)
        c.cli = self.cli_factory(test, node)
        return c

    def close(self, test):
        if self.cli is not None:
            self.cli.close()

    def _getjob(self):
        """One GETJOB NOHANG: (job-id, value) or None when the queue
        is (locally) empty. The CLI prints queue/id/body lines."""
        out = _reply(self.cli.run("getjob", "nohang", "count", "1",
                                  "from", QUEUE))
        lines = [ln.strip() for ln in out.splitlines() if ln.strip()]
        if len(lines) < 3:
            return None
        jid, body = lines[1], lines[2]
        return jid, int(body.strip('"'))

    def invoke(self, test, op):
        try:
            if op.f == "enqueue":
                jid = _reply(self.cli.run("addjob", QUEUE,
                                          str(op.value), "100"))
                if not jid.startswith(("DI", "D-")):
                    raise RemoteError("unexpected ADDJOB reply",
                                      exit=0, out=jid, err="",
                                      cmd="addjob", node=None)
                return op.copy(type="ok")
            if op.f == "dequeue":
                got = self._getjob()
                if got is None:
                    return op.copy(type="fail", error="empty")
                jid, value = got
                _reply(self.cli.run("ackjob", jid))
                return op.copy(type="ok", value=value)
            if op.f == "drain":
                out = []
                while True:
                    got = self._getjob()
                    if got is None:
                        return op.copy(type="ok", value=out)
                    jid, value = got
                    _reply(self.cli.run("ackjob", jid))
                    out.append(value)
            raise ValueError(f"unknown f {op.f!r}")
        except (RemoteError, _ErrorReply, ValueError) as e:
            if isinstance(e, ValueError) and "unknown f" in str(e):
                raise
            return _classify(op, e)


# ---------------------------------------------------------------------------
# Workloads / test
# ---------------------------------------------------------------------------

def queue_workload(opts: dict) -> dict:
    from ..workloads import queue

    w = queue.workload({"ops": opts.get("ops", 500)})
    w["client"] = DisqueQueueClient()
    return w


WORKLOADS = {"queue": queue_workload}


def disque_test(opts: dict) -> dict:
    name = opts.get("workload") or "queue"
    w = WORKLOADS[name](opts)
    test = testing.noop_test()
    test.update(
        name=f"disque-{name}",
        os=debian.os,
        db=DisqueDB(opts.get("version", VERSION)),
        ssh=opts["ssh"],
        nodes=opts["nodes"],
        concurrency=opts["concurrency"],
        client=w["client"],
        nemesis=jnemesis.partition_random_halves(),
        checker=chk.compose({"workload": w["checker"],
                             "stats": chk.stats(),
                             "perf": chk.perf(),
                             "timeline": chk.timeline()}),
        # the queue workload's generator already ends in its own
        # drain phase; the time limit brackets everything (a run cut
        # before the drain degrades honestly to valid? unknown)
        generator=gen.time_limit(
            opts.get("time_limit", 30),
            gen.clients(
                gen.stagger(1.0 / opts.get("rate", 20),
                            w["generator"]),
                jnemesis.start_stop_cycle(10.0))))
    return test


def _opts(p):
    p.add_argument("--workload", default=None,
                   help="Workload (default queue). "
                        + cli.one_of(WORKLOADS))
    p.add_argument("--version", default=VERSION,
                   help="disque release tag to build.")
    p.add_argument("--rate", type=float, default=20)
    return p


def main(argv=None) -> None:
    commands = {}
    commands.update(cli.single_test_cmd(disque_test, parser_fn=_opts))
    commands.update(cli.serve_cmd())
    commands.update(cli.coverage_cmd(list(WORKLOADS)))
    cli.run_cli(commands, argv)


if __name__ == "__main__":
    main()
