"""Elasticsearch test suite: dirty-read hunting and set conservation
over the HTTP API.

Capability reference: elasticsearch/src/jepsen/elasticsearch/ —
core.clj (tarball install, cluster config with unicast discovery,
dedicated non-root user), dirty_read.clj (index-create writes, get-
by-id reads, refresh-until-all-shards, search-everything strong
reads; the rw generator and checker live in
workloads/dirty_read.py), sets.clj (insert-a-doc-per-element + final
search). The reference links the ES transport client into the JVM;
here ops go over the HTTP JSON API from the control host (the same
transport stance as etcd/consul).
"""

from __future__ import annotations

import json
import logging
import urllib.error
import urllib.request

from .. import checker as chk
from .. import cli, client as jclient, control, db as jdb
from .. import generator as gen
from .. import nemesis as jnemesis
from .. import testing, workloads
from ..control import util as cu
from ..control.core import RemoteError
from ..os_setup import debian

logger = logging.getLogger(__name__)

VERSION = "7.17.23"
DIR = "/opt/elasticsearch"
ES_USER = "elasticsearch"
DATA_DIR = "/var/lib/elasticsearch"
LOGFILE = f"{DIR}/logs/jepsen.log"
PIDFILE = "/var/run/elasticsearch.pid"
HTTP_PORT = 9200
INDEX = "dirty_read"
SET_INDEX = "sets"

ES_YML = """cluster.name: jepsen
node.name: {node}
network.host: 0.0.0.0
http.port: {port}
path.data: {data}
discovery.seed_hosts: [{hosts}]
cluster.initial_master_nodes: [{hosts}]
"""


class ElasticsearchDB(jdb.DB):
    """Tarball install running as a dedicated non-root user (ES
    refuses root), unicast discovery across the cluster
    (elasticsearch/core.clj db)."""

    supports_kill = True

    def __init__(self, version: str = VERSION):
        self.version = version

    def setup(self, test, node):
        logger.info("%s installing elasticsearch %s", node,
                    self.version)
        hosts = ", ".join(f'"{n}"' for n in test["nodes"])
        with control.su():
            url = (f"https://artifacts.elastic.co/downloads/"
                   f"elasticsearch/elasticsearch-{self.version}"
                   f"-linux-x86_64.tar.gz")
            cu.install_archive(url, DIR)
            cu.ensure_user(ES_USER)
            control.exec_("mkdir", "-p", DATA_DIR)
            cu.write_file(
                ES_YML.format(node=node, port=HTTP_PORT,
                              data=DATA_DIR, hosts=hosts),
                f"{DIR}/config/elasticsearch.yml")
            control.exec_("chown", "-R", f"{ES_USER}:{ES_USER}",
                          DIR, DATA_DIR)
        with control.su(ES_USER):
            cu.start_daemon(
                {"logfile": LOGFILE, "pidfile": PIDFILE, "chdir": DIR},
                f"{DIR}/bin/elasticsearch")
        cu.await_tcp_port(HTTP_PORT, timeout_secs=180)

    def teardown(self, test, node):
        logger.info("%s tearing down elasticsearch", node)
        with control.su():
            cu.stop_daemon(f"{DIR}/bin/elasticsearch", PIDFILE)
            control.exec_("rm", "-rf", DATA_DIR, DIR)

    def kill(self, test, node):
        with control.su():
            cu.grepkill("elasticsearch")
        return "killed"

    def start(self, test, node):
        with control.su(ES_USER):
            cu.start_daemon(
                {"logfile": LOGFILE, "pidfile": PIDFILE, "chdir": DIR},
                f"{DIR}/bin/elasticsearch")
        return "started"

    def log_files(self, test, node):
        return [LOGFILE]


# ---------------------------------------------------------------------------
# HTTP driver
# ---------------------------------------------------------------------------

class EsHttp:
    """Minimal ES JSON driver. Split out so tests can stub
    `request`."""

    def __init__(self, node, timeout: float = 8.0):
        self.base = f"http://{node}:{HTTP_PORT}"
        self.timeout = timeout

    def request(self, method: str, path: str,
                body: dict | None = None) -> tuple[int, dict]:
        req = urllib.request.Request(
            self.base + path, method=method,
            data=json.dumps(body).encode() if body is not None
            else None,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.status, json.loads(r.read().decode() or "{}")
        except urllib.error.HTTPError as e:
            payload = e.read().decode() or "{}"
            try:
                return e.code, json.loads(payload)
            except ValueError:
                return e.code, {"raw": payload}

    def create_index(self, index: str) -> None:
        status, out = self.request("PUT", f"/{index}")
        if status not in (200, 400):  # 400: already exists
            raise RuntimeError(f"create index {index}: {out}")

    def index_doc(self, index: str, doc_id) -> bool:
        """True when the write is acknowledged as created."""
        status, out = self.request(
            "PUT", f"/{index}/_doc/{doc_id}?op_type=create",
            {"id": doc_id})
        if status == 409:
            return True  # already created: an earlier try landed
        if status not in (200, 201):
            raise RuntimeError(f"index {doc_id}: {out}")
        return out.get("result") in ("created", "updated")

    def get_doc(self, index: str, doc_id) -> bool:
        status, out = self.request("GET", f"/{index}/_doc/{doc_id}")
        return status == 200 and bool(out.get("found"))

    def refresh(self, index: str) -> bool:
        """True iff the refresh touched every shard
        (dirty_read.clj's all-shards-successful retry condition)."""
        _status, out = self.request("POST", f"/{index}/_refresh")
        sh = out.get("_shards") or {}
        return (sh.get("total", 0) > 0
                and sh.get("successful") == sh.get("total"))

    def search_ids(self, index: str) -> list:
        """Every doc id, paging with search_after — a bare size-10000
        search silently truncates larger indices and would frame a
        healthy cluster for losing the excess."""
        ids: list = []
        after = None
        while True:
            body = {"size": 10000, "query": {"match_all": {}},
                    "_source": False, "sort": [{"_id": "asc"}]}
            if after is not None:
                body["search_after"] = after
            _status, out = self.request(
                "POST", f"/{index}/_search", body)
            hits = (out.get("hits") or {}).get("hits") or []
            if not hits:
                return ids
            ids.extend(h["_id"] for h in hits)
            last = hits[-1]
            after = last.get("sort", [last["_id"]])


def _definite(e: Exception) -> bool:
    return jclient.definite_http_failure(e)


def _await_full_refresh(http: EsHttp, index: str,
                        timeout_secs: float = 120) -> None:
    """Retries until a refresh touches EVERY shard (dirty_read.clj's
    all-shards-successful loop): a partial refresh would hide acked
    docs from the following search and fake a loss."""
    from .. import util

    def check():
        if not http.refresh(index):
            raise RuntimeError("refresh incomplete")

    util.await_fn(check, timeout_secs=timeout_secs,
                  log_message="refresh incomplete; retrying")


# ---------------------------------------------------------------------------
# Clients
# ---------------------------------------------------------------------------

class EsDirtyReadClient(jclient.Client):
    """dirty_read.clj client over HTTP: writes index a doc by id,
    reads are get-by-id (a miss is a definite fail), refresh retries
    until all shards answer, strong reads search everything."""

    def __init__(self, http_factory=EsHttp):
        self.http_factory = http_factory
        self.http = None

    def open(self, test, node):
        c = EsDirtyReadClient(self.http_factory)
        c.http = self.http_factory(node)
        return c

    def setup(self, test):
        try:
            self.http.create_index(INDEX)
        except Exception:  # noqa: BLE001 — another client won the race
            pass
        return self

    def invoke(self, test, op):
        try:
            if op.f == "write":
                ok = self.http.index_doc(INDEX, str(op.value))
                return op.copy(type="ok" if ok else "info")
            if op.f == "read":
                found = self.http.get_doc(INDEX, str(op.value))
                return op.copy(type="ok" if found else "fail")
            if op.f == "refresh":
                _await_full_refresh(self.http, INDEX)
                return op.copy(type="ok")
            if op.f == "strong-read":
                ids = self.http.search_ids(INDEX)
                return op.copy(type="ok",
                               value=sorted(int(i) for i in ids))
            raise ValueError(f"unknown f {op.f!r}")
        except Exception as e:  # noqa: BLE001
            if op.f == "read" or _definite(e):
                return op.copy(type="fail", error=repr(e)[:200])
            return op.copy(type="info", error=repr(e)[:200])


class EsSetClient(jclient.Client):
    """sets.clj client: one doc per element, final read = refresh +
    search."""

    def __init__(self, http_factory=EsHttp):
        self.http_factory = http_factory
        self.http = None

    def open(self, test, node):
        c = EsSetClient(self.http_factory)
        c.http = self.http_factory(node)
        return c

    def setup(self, test):
        try:
            self.http.create_index(SET_INDEX)
        except Exception:  # noqa: BLE001
            pass
        return self

    def invoke(self, test, op):
        try:
            if op.f == "add":
                ok = self.http.index_doc(SET_INDEX, str(op.value))
                return op.copy(type="ok" if ok else "info")
            if op.f == "read":
                _await_full_refresh(self.http, SET_INDEX)
                ids = self.http.search_ids(SET_INDEX)
                return op.copy(type="ok",
                               value=sorted(int(i) for i in ids))
            raise ValueError(f"unknown f {op.f!r}")
        except Exception as e:  # noqa: BLE001
            if op.f == "read" or _definite(e):
                return op.copy(type="fail", error=repr(e)[:200])
            return op.copy(type="info", error=repr(e)[:200])


# ---------------------------------------------------------------------------
# Workloads / test
# ---------------------------------------------------------------------------

def dirty_read_workload(opts: dict) -> dict:
    w = workloads.dirty_read.workload(
        {"ops": opts.get("ops", 1000),
         "concurrency": opts["concurrency"],
         "seed": opts.get("seed")})
    w["client"] = EsDirtyReadClient()
    return w


def set_workload(opts: dict) -> dict:
    import itertools

    counter = itertools.count()
    return {
        "client": EsSetClient(),
        "generator": gen.limit(
            opts.get("ops", 500),
            lambda: {"f": "add", "value": next(counter)}),
        "final_generator": gen.each_thread(gen.once(
            lambda: {"f": "read", "value": None})),
        "checker": chk.set_checker(),
    }


WORKLOADS = {"dirty-read": dirty_read_workload, "set": set_workload}


def elasticsearch_test(opts: dict) -> dict:
    name = opts.get("workload") or "dirty-read"
    w = WORKLOADS[name](opts)
    test = testing.noop_test()
    test.update(
        name=f"elasticsearch-{name}",
        os=debian.os,
        db=ElasticsearchDB(opts.get("version", VERSION)),
        ssh=opts["ssh"],
        nodes=opts["nodes"],
        concurrency=opts["concurrency"],
        client=w["client"],
        nemesis=jnemesis.partition_random_halves(),
        checker=chk.compose({"workload": w["checker"],
                             "stats": chk.stats(),
                             "perf": chk.perf(),
                             "timeline": chk.timeline()}),
        generator=_suite_generator(opts, w))
    return test


def _suite_generator(opts, w):
    main = gen.time_limit(
        opts.get("time_limit", 30),
        gen.clients(
            gen.stagger(1.0 / opts.get("rate", 20), w["generator"]),
            jnemesis.start_stop_cycle(10.0)))
    final = w.get("final_generator")
    if final is None:
        return main
    return gen.phases(
        main,
        gen.nemesis(gen.once({"type": "info", "f": "stop"})),
        gen.sleep(opts.get("recovery_time", 10)),
        gen.clients(final))


def _opts(p):
    p.add_argument("--workload", default=None,
                   help="Workload (default dirty-read). "
                        + cli.one_of(WORKLOADS))
    p.add_argument("--version", default=VERSION,
                   help="elasticsearch version to install.")
    p.add_argument("--rate", type=float, default=20)
    return p


def main(argv=None) -> None:
    commands = {}
    commands.update(cli.single_test_cmd(elasticsearch_test,
                                        parser_fn=_opts))
    commands.update(cli.serve_cmd())
    cli.run_cli(commands, argv)


if __name__ == "__main__":
    main()
