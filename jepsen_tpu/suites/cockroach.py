"""CockroachDB test suite: register, bank, monotonic and sequential
workloads over `cockroach sql` on the nodes.

Capability reference: cockroachdb/src/jepsen/cockroach/ — auto.clj
(tarball install, `cockroach start --insecure --join` on every node,
one-time `cockroach init`), register.clj (per-key cas register over
SQL), bank.clj (transfer txns), monotonic.clj (max+1 inserts carrying
cluster_logical_timestamp(), node, process, table), sequential.clj
(subkey chains probed in reverse), runner.clj (workload menu). The
reference drives JDBC; here every op is one `cockroach sql -e` batch
on the client's node — cockroach speaks the postgres dialect, so the
statement shapes mirror the postgres suite's, plus cockroach-isms:
UPSERT, RETURNING on guarded updates, and
cluster_logical_timestamp() as the monotonic timestamp source.
"""

from __future__ import annotations

import logging
import random
import re
from decimal import Decimal

from .. import checker as chk
from .. import cli, client as jclient, control, core, db as jdb
from .. import generator as gen
from .. import independent
from .. import nemesis as jnemesis
from .. import testing, workloads
from ..checker import models
from ..control import util as cu
from ..control.core import RemoteError
from ..core import primary
from ..os_setup import debian

logger = logging.getLogger(__name__)

VERSION = "v23.1.14"
DIR = "/opt/cockroach"
BINARY = f"{DIR}/cockroach"
STORE_DIR = "/var/lib/cockroach"
LOGFILE = f"{DIR}/cockroach.log"
PIDFILE = f"{DIR}/cockroach.pid"
SQL_PORT = 26257
HTTP_PORT = 8080
DB_NAME = "jepsen"


class CockroachDB(jdb.DB):
    """Tarball install + insecure cluster join; the test primary runs
    the one-time init (auto.clj)."""

    supports_kill = True

    def __init__(self, version: str = VERSION):
        self.version = version

    def setup(self, test, node):
        logger.info("%s installing cockroach %s", node, self.version)
        join = ",".join(f"{n}:{SQL_PORT}" for n in test["nodes"])
        with control.su():
            url = (f"https://binaries.cockroachdb.com/cockroach-"
                   f"{self.version}.linux-amd64.tgz")
            cu.install_archive(url, DIR)
            control.exec_("mkdir", "-p", STORE_DIR)
            cu.start_daemon(
                {"logfile": LOGFILE, "pidfile": PIDFILE, "chdir": DIR},
                BINARY, "start", "--insecure",
                "--store", STORE_DIR,
                "--listen-addr", f"{node}:{SQL_PORT}",
                "--http-addr", f"{node}:{HTTP_PORT}",
                "--join", join)
        core.synchronize(test)  # every daemon up before init
        if node == primary(test):
            with control.su():
                control.exec_(BINARY, "init", "--insecure",
                              "--host", f"{node}:{SQL_PORT}",
                              check=False)  # idempotent re-runs fail
            self._schema(test, node)
        core.synchronize(test)

    def _schema(self, test, node):
        stmts = [
            f"CREATE DATABASE IF NOT EXISTS {DB_NAME}",
            f"CREATE TABLE IF NOT EXISTS {DB_NAME}.kv "
            "(k INT PRIMARY KEY, v INT)",
            f"CREATE TABLE IF NOT EXISTS {DB_NAME}.accounts "
            "(id INT PRIMARY KEY, balance INT NOT NULL "
            "CHECK (balance >= 0))",
            f"CREATE TABLE IF NOT EXISTS {DB_NAME}.mono "
            "(val INT PRIMARY KEY, sts DECIMAL, node INT, "
            "process INT, tb INT)",
            f"CREATE TABLE IF NOT EXISTS {DB_NAME}.seq "
            "(key STRING PRIMARY KEY)",
            f"CREATE TABLE IF NOT EXISTS {DB_NAME}.sets "
            "(v INT PRIMARY KEY)",
            f"CREATE TABLE IF NOT EXISTS {DB_NAME}.g2a "
            "(id INT PRIMARY KEY, k INT)",
            f"CREATE TABLE IF NOT EXISTS {DB_NAME}.g2b "
            "(id INT PRIMARY KEY, k INT)",
        ] + [
            # (key, id) composite pk: the causal-reverse workload's
            # write ids are per-key sequences, not globally unique
            f"CREATE TABLE IF NOT EXISTS {DB_NAME}.comment_{i} "
            "(id INT, key INT, PRIMARY KEY (key, id))"
            for i in range(COMMENT_TABLES)
        ] + [
            f"CREATE TABLE IF NOT EXISTS {DB_NAME}.bank{i} "
            "(id INT PRIMARY KEY, balance INT NOT NULL "
            "CHECK (balance >= 0))"
            for i in range(8)
        ] + [
            f"INSERT INTO {DB_NAME}.bank{i} VALUES (0, 10) "
            "ON CONFLICT (id) DO NOTHING" for i in range(8)
        ]
        accounts = ",".join(f"({i}, 10)" for i in range(8))
        stmts.append(f"INSERT INTO {DB_NAME}.accounts VALUES "
                     f"{accounts} ON CONFLICT (id) DO NOTHING")
        for s in stmts:
            control.exec_(BINARY, "sql", "--insecure",
                          "--host", f"{node}:{SQL_PORT}", "-e", s)

    def teardown(self, test, node):
        logger.info("%s tearing down cockroach", node)
        with control.su():
            cu.stop_daemon(BINARY, PIDFILE)
            control.exec_("rm", "-rf", STORE_DIR, DIR)

    def kill(self, test, node):
        with control.su():
            cu.grepkill("cockroach")
        return "killed"

    def start(self, test, node):
        join = ",".join(f"{n}:{SQL_PORT}" for n in test["nodes"])
        with control.su():
            cu.start_daemon(
                {"logfile": LOGFILE, "pidfile": PIDFILE, "chdir": DIR},
                BINARY, "start", "--insecure",
                "--store", STORE_DIR,
                "--listen-addr", f"{node}:{SQL_PORT}",
                "--http-addr", f"{node}:{HTTP_PORT}",
                "--join", join)
        return "started"

    def log_files(self, test, node):
        return [LOGFILE]


# ---------------------------------------------------------------------------
# SQL transport
# ---------------------------------------------------------------------------

COMMENT_TABLES = 4


class CrdbSql:
    """One `cockroach sql -e` batch on the client's node. Split out so
    tests can stub `run`."""

    def __init__(self, test, node, timeout: float = 10.0):
        self.test = test
        self.node = node
        self.timeout = timeout
        self.sess = control.session(test, node)

    def run(self, sql: str) -> str:
        with control.with_session(self.test, self.node, self.sess):
            return control.exec_(
                BINARY, "sql", "--insecure",
                "--host", f"{self.node}:{SQL_PORT}",
                "-d", DB_NAME, "--format", "tsv", "-e", sql,
                timeout=self.timeout)

    def close(self):
        control.disconnect(self.sess)


_DEFINITE_RE = re.compile(
    "|".join([r"restart transaction", r"TransactionRetryError",
              r"connection refused", r"failed to connect",
              r"violates check constraint",
              r"node is not ready"]), re.I)


def _classify(op, e: Exception):
    msg = f"{getattr(e, 'err', '')} {getattr(e, 'out', '')} {e}"
    if op.f == "read" or _DEFINITE_RE.search(msg):
        return op.copy(type="fail", error=msg.strip()[:200])
    return op.copy(type="info", error=msg.strip()[:200])


def _data_lines(out: str) -> list[str]:
    """tsv output minus the header row and notices."""
    lines = [ln for ln in out.splitlines()
             if ln.strip() and not ln.startswith(("NOTICE", "#"))]
    return lines[1:] if lines else []


# ---------------------------------------------------------------------------
# Clients
# ---------------------------------------------------------------------------

class CrdbRegisterClient(jclient.Client):
    """Per-key cas register over the kv table (register.clj): UPSERT
    writes, UPDATE .. WHERE v = old RETURNING guarded cas."""

    def __init__(self, sql_factory=CrdbSql):
        self.sql_factory = sql_factory
        self.sql = None

    def open(self, test, node):
        c = CrdbRegisterClient(self.sql_factory)
        c.sql = self.sql_factory(test, node)
        return c

    def close(self, test):
        if self.sql is not None:
            self.sql.close()

    def invoke(self, test, op):
        k, v = independent.key_(op.value), independent.value_(op.value)
        try:
            if op.f == "read":
                out = self.sql.run(
                    f"SELECT v FROM kv WHERE k = {int(k)};")
                rows = _data_lines(out)
                val = int(rows[0]) if rows else None
                return op.copy(type="ok",
                               value=independent.ktuple(k, val))
            if op.f == "write":
                self.sql.run(f"UPSERT INTO kv VALUES "
                             f"({int(k)}, {int(v)});")
                return op.copy(type="ok")
            if op.f == "cas":
                old, new = v
                out = self.sql.run(
                    f"UPDATE kv SET v = {int(new)} "
                    f"WHERE k = {int(k)} AND v = {int(old)} "
                    f"RETURNING v;")
                return op.copy(
                    type="ok" if _data_lines(out) else "fail")
            raise ValueError(f"unknown f {op.f!r}")
        except RemoteError as e:
            return _classify(op, e)


class CrdbMonotonicClient(jclient.Client):
    """Monotonic inserts (monotonic.clj): ONE atomic statement reads
    the max and inserts max+1 stamped with
    cluster_logical_timestamp()."""

    def __init__(self, sql_factory=CrdbSql):
        self.sql_factory = sql_factory
        self.sql = None
        self.node_index = 0

    def open(self, test, node):
        c = CrdbMonotonicClient(self.sql_factory)
        c.sql = self.sql_factory(test, node)
        nodes = list(test.get("nodes", ()))
        c.node_index = nodes.index(node) if node in nodes else 0
        return c

    def close(self, test):
        if self.sql is not None:
            self.sql.close()

    @staticmethod
    def _row(parts) -> dict:
        # HLC timestamps carry 10 fractional digits (the logical
        # component); scale to an exact int so the value is numeric in
        # SQL (DECIMAL column: ORDER BY is numeric, not lexicographic)
        # AND survives the JSON store round trip losslessly — a
        # Decimal would be re-read as a repr STRING and the checker
        # would compare timestamps lexicographically
        return {"val": int(parts[0]),
                "sts": int(Decimal(parts[1]) * 10**10),
                "node": int(parts[2]),
                "process": int(parts[3]),
                "tb": int(parts[4])}

    def invoke(self, test, op):
        try:
            if op.f == "add":
                tb = random.randrange(2)
                out = self.sql.run(
                    "INSERT INTO mono (val, sts, node, process, tb) "
                    "SELECT COALESCE(MAX(val), 0) + 1, "
                    "cluster_logical_timestamp(), "
                    f"{self.node_index}, {int(op.process)}, {tb} "
                    "FROM mono RETURNING val, sts, node, process, tb;")
                rows = _data_lines(out)
                if not rows:
                    raise ValueError(f"no row returned: {out!r}")
                return op.copy(type="ok",
                               value=self._row(rows[0].split("\t")))
            if op.f == "read":
                out = self.sql.run(
                    "SELECT val, sts, node, process, tb FROM mono "
                    "ORDER BY sts;")
                rows = [self._row(ln.split("\t"))
                        for ln in _data_lines(out)]
                return op.copy(type="ok", value=rows)
            raise ValueError(f"unknown f {op.f!r}")
        except RemoteError as e:
            return _classify(op, e)


class CrdbSequentialClient(jclient.Client):
    """Subkey chains (sequential.clj): inserts in order, each its own
    statement; reads probe reversed."""

    def __init__(self, sql_factory=CrdbSql, key_count: int = 5):
        self.sql_factory = sql_factory
        self.key_count = key_count
        self.sql = None

    def open(self, test, node):
        c = CrdbSequentialClient(self.sql_factory,
                                 test.get("key_count",
                                          self.key_count))
        c.sql = self.sql_factory(test, node)
        return c

    def close(self, test):
        if self.sql is not None:
            self.sql.close()

    def invoke(self, test, op):
        seq = workloads.sequential
        ks = seq.subkeys(self.key_count, op.value)
        try:
            if op.f == "write":
                for k in ks:
                    self.sql.run(f"INSERT INTO seq (key) "
                                 f"VALUES ('{k}') "
                                 f"ON CONFLICT (key) DO NOTHING;")
                return op.copy(type="ok")
            if op.f == "read":
                obs = []
                for k in reversed(ks):
                    out = self.sql.run(
                        f"SELECT key FROM seq WHERE key = '{k}';")
                    rows = _data_lines(out)
                    obs.append(rows[0] if rows else None)
                return op.copy(type="ok", value=(op.value, obs))
            raise ValueError(f"unknown f {op.f!r}")
        except RemoteError as e:
            return _classify(op, e)


class CrdbBankClient(jclient.Client):
    """Bank transfers in one serializable batch (bank.clj; cockroach
    is always SERIALIZABLE) guarded by the accounts CHECK."""

    def __init__(self, sql_factory=CrdbSql):
        self.sql_factory = sql_factory
        self.sql = None

    def open(self, test, node):
        c = CrdbBankClient(self.sql_factory)
        c.sql = self.sql_factory(test, node)
        return c

    def close(self, test):
        if self.sql is not None:
            self.sql.close()

    def invoke(self, test, op):
        try:
            if op.f == "read":
                out = self.sql.run(
                    "SELECT id, balance FROM accounts ORDER BY id;")
                balances = {}
                for ln in _data_lines(out):
                    i, b = ln.split("\t")
                    balances[int(i)] = int(b)
                return op.copy(type="ok", value=balances)
            if op.f == "transfer":
                v = op.value
                f, t, a = (int(v["from"]), int(v["to"]),
                           int(v["amount"]))
                self.sql.run(
                    "BEGIN; "
                    f"UPDATE accounts SET balance = balance - {a} "
                    f"WHERE id = {f}; "
                    f"UPDATE accounts SET balance = balance + {a} "
                    f"WHERE id = {t}; "
                    "COMMIT;")
                return op.copy(type="ok")
            raise ValueError(f"unknown f {op.f!r}")
        except RemoteError as e:
            return _classify(op, e)


# ---------------------------------------------------------------------------
# Workloads / test
# ---------------------------------------------------------------------------

def register_workload(opts: dict) -> dict:
    rng = random.Random(opts.get("seed"))
    keys = list(range(opts.get("keys", 4)))

    def one():
        r = rng.random()
        if r < 0.4:
            return {"f": "read", "value": None}
        if r < 0.7:
            return {"f": "write", "value": rng.randrange(5)}
        return {"f": "cas",
                "value": [rng.randrange(5), rng.randrange(5)]}

    return {
        "client": CrdbRegisterClient(),
        "generator": independent.concurrent_generator(
            opts["concurrency"], keys,
            lambda k: gen.limit(opts.get("ops_per_key", 200), one)),
        "checker": independent.checker(chk.linearizable(
            {"model": models.cas_register()})),
    }


def bank_workload(opts: dict) -> dict:
    from ..workloads import bank

    total = 8 * 10
    return {
        "client": CrdbBankClient(),
        "generator": bank.generator(accounts=list(range(8)),
                                    seed=opts.get("seed")),
        "checker": chk.checker(
            lambda test, hist, o: bank.check_fast(hist, total)),
    }


def monotonic_workload(opts: dict) -> dict:
    w = workloads.monotonic.workload({"ops": opts.get("ops", 300)})
    w["client"] = CrdbMonotonicClient()
    return w


def sequential_workload(opts: dict) -> dict:
    w = workloads.sequential.workload(
        {"ops": opts.get("ops", 400),
         "writers": workloads.sequential.default_writers(
             opts["concurrency"]),
         "seed": opts.get("seed")})
    w["client"] = CrdbSequentialClient(key_count=w["key_count"])
    return w


class CrdbSetClient(jclient.Client):
    """sets.clj: blind inserts of unique ints, one final full read."""

    def __init__(self, sql_factory=CrdbSql):
        self.sql_factory = sql_factory
        self.sql = None

    def open(self, test, node):
        c = CrdbSetClient(self.sql_factory)
        c.sql = self.sql_factory(test, node)
        return c

    def close(self, test):
        if self.sql is not None:
            self.sql.close()

    def invoke(self, test, op):
        try:
            if op.f == "add":
                self.sql.run(f"INSERT INTO sets (v) VALUES "
                             f"({int(op.value)});")
                return op.copy(type="ok")
            out = self.sql.run("SELECT v FROM sets;")
            return op.copy(type="ok", value=sorted(
                int(x) for x in _data_lines(out)))
        except RemoteError as e:
            return _classify(op, e)


class CrdbCommentsClient(jclient.Client):
    """comments.clj: blind inserts of (id, key) hashed across
    comment_N tables; reads select ids for the key across ALL tables
    in one txn. A read seeing w but missing an acked predecessor of w
    is the strict-serializability violation (causal-reverse)."""

    def __init__(self, sql_factory=CrdbSql,
                 table_count: int = COMMENT_TABLES):
        self.sql_factory = sql_factory
        self.table_count = table_count
        self.sql = None

    def open(self, test, node):
        c = CrdbCommentsClient(self.sql_factory, self.table_count)
        c.sql = self.sql_factory(test, node)
        return c

    def close(self, test):
        if self.sql is not None:
            self.sql.close()

    def _table(self, wid) -> str:
        return f"comment_{int(wid) % self.table_count}"

    def invoke(self, test, op):
        k, v = op.value
        try:
            if op.f == "write":
                self.sql.run(
                    f"INSERT INTO {self._table(v)} (id, key) VALUES "
                    f"({int(v)}, {int(k)});")
                return op.copy(type="ok")
            sels = "; ".join(
                f"SELECT id FROM comment_{i} WHERE key = {int(k)}"
                for i in range(self.table_count))
            out = self.sql.run(f"BEGIN; {sels}; COMMIT;")
            ids = sorted(int(x) for x in out.split()
                         if x.strip().lstrip("-").isdigit())
            return op.copy(type="ok", value=(k, ids))
        except RemoteError as e:
            return _classify(op, e)


class CrdbG2Client(jclient.Client):
    """adya.clj G2: predicate-read both pair tables; insert only when
    both are empty. Serializability allows at most one committed
    insert per key (anti-dependency cycle otherwise)."""

    def __init__(self, sql_factory=CrdbSql):
        self.sql_factory = sql_factory
        self.sql = None

    def open(self, test, node):
        c = CrdbG2Client(self.sql_factory)
        c.sql = self.sql_factory(test, node)
        return c

    def close(self, test):
        if self.sql is not None:
            self.sql.close()

    def invoke(self, test, op):
        k, pair = op.value
        a_id, b_id = pair
        table, rid = (("g2a", a_id) if a_id is not None
                      else ("g2b", b_id))
        try:
            # ONE statement = one serializable txn: the predicate
            # check and the insert must not be split, or a healthy DB
            # serializes two unconditional inserts and gets flagged
            out = self.sql.run(
                f"INSERT INTO {table} (id, k) "
                f"SELECT {int(rid)}, {int(k)} WHERE NOT EXISTS "
                f"(SELECT 1 FROM g2a WHERE k = {int(k)}) AND "
                f"NOT EXISTS (SELECT 1 FROM g2b WHERE k = {int(k)}) "
                "RETURNING id;")
            if _data_lines(out):
                return op.copy(type="ok")
            return op.copy(type="fail", error="existing row")
        except RemoteError as e:
            return _classify(op, e)


class CrdbMultiBankClient(CrdbBankClient):
    """bank.clj multitable: each account in its own bankN table; the
    transfer txn spans two tables (different ranges/shards)."""

    def open(self, test, node):
        c = CrdbMultiBankClient(self.sql_factory)
        c.sql = self.sql_factory(test, node)
        return c

    def invoke(self, test, op):
        try:
            if op.f == "read":
                sels = "; ".join(
                    f"SELECT balance FROM bank{i} WHERE id = 0"
                    for i in range(8))
                out = self.sql.run(f"BEGIN; {sels}; COMMIT;")
                vals = [int(x) for x in _data_lines(out)
                        if x.strip().lstrip("-").isdigit()]
                return op.copy(type="ok",
                               value={i: b for i, b in
                                      enumerate(vals)})
            v = op.value
            f, t, a = (int(v["from"]), int(v["to"]),
                       int(v["amount"]))
            self.sql.run(
                "BEGIN; "
                f"UPDATE bank{f} SET balance = balance - {a} "
                "WHERE id = 0; "
                f"UPDATE bank{t} SET balance = balance + {a} "
                "WHERE id = 0; COMMIT;")
            return op.copy(type="ok")
        except RemoteError as e:
            return _classify(op, e)


def sets_workload(opts: dict) -> dict:
    w = workloads.sets.workload({"ops": opts.get("ops", 400)})
    w["client"] = CrdbSetClient()
    return w


def comments_workload(opts: dict) -> dict:
    w = workloads.causal_reverse.workload(dict(opts))
    w["client"] = CrdbCommentsClient()
    return w


def g2_workload(opts: dict) -> dict:
    w = workloads.adya.workload(dict(opts))
    w["client"] = CrdbG2Client()
    return w


def bank_multitable_workload(opts: dict) -> dict:
    w = bank_workload(opts)
    w["client"] = CrdbMultiBankClient()
    return w


WORKLOADS = {"register": register_workload,
             "bank": bank_workload,
             "bank-multitable": bank_multitable_workload,
             "monotonic": monotonic_workload,
             "sequential": sequential_workload,
             "sets": sets_workload,
             "comments": comments_workload,
             "g2": g2_workload}


def cockroach_test(opts: dict) -> dict:
    name = opts.get("workload") or "register"
    w = WORKLOADS[name](opts)
    test = testing.noop_test()
    test.update(
        name=f"cockroach-{name}",
        os=debian.os,
        db=CockroachDB(opts.get("version", VERSION)),
        ssh=opts["ssh"],
        nodes=opts["nodes"],
        concurrency=opts["concurrency"],
        key_count=w.get("key_count", 5),
        client=w["client"],
        nemesis=jnemesis.partition_random_halves(),
        checker=chk.compose({"workload": w["checker"],
                             "stats": chk.stats(),
                             "perf": chk.perf(),
                             "timeline": chk.timeline()}),
        generator=_suite_generator(opts, w))
    return test


def _suite_generator(opts, w):
    main = gen.time_limit(
        opts.get("time_limit", 30),
        gen.clients(
            gen.stagger(1.0 / opts.get("rate", 20), w["generator"]),
            jnemesis.start_stop_cycle(10.0)))
    final = w.get("final_generator")
    if final is None:
        return main
    return gen.phases(
        main,
        gen.nemesis(gen.once({"type": "info", "f": "stop"})),
        gen.sleep(opts.get("recovery_time", 5)),
        gen.clients(final))


def _opts(p):
    p.add_argument("--workload", default=None,
                   help="Workload (default register). "
                        + cli.one_of(WORKLOADS))
    p.add_argument("--version", default=VERSION,
                   help="cockroach release tag to install.")
    p.add_argument("--rate", type=float, default=20)
    return p


def main(argv=None) -> None:
    commands = {}
    commands.update(cli.single_test_cmd(cockroach_test,
                                        parser_fn=_opts))
    commands.update(cli.serve_cmd())
    cli.run_cli(commands, argv)


if __name__ == "__main__":
    main()
