"""Shared plumbing for the SQL-CLI suites (galera, tidb, ...): one
ambient-session transport class and the fail/info error classifier,
parameterized on connection argv and the engine's definite-error
patterns (every reference suite carries its own with-errors macro
making the same split; here it's one helper)."""

from __future__ import annotations

import re

from .. import control


class SqlCli:
    """Runs one SQL batch through a CLI on the client's node. Split
    out so tests can stub `run`."""

    def __init__(self, test, node, argv, timeout: float = 10.0):
        self.test = test
        self.node = node
        self.argv = argv
        self.timeout = timeout
        self.sess = control.session(test, node)

    def run(self, sql: str) -> str:
        with control.with_session(self.test, self.node, self.sess):
            return control.exec_(*self.argv, sql,
                                 timeout=self.timeout)

    def close(self):
        control.disconnect(self.sess)


def make_classifier(definite_patterns):
    """op-error classifier: reads and definite rejections -> :fail,
    anything indeterminate -> :info."""
    definite_re = re.compile("|".join(definite_patterns), re.I)

    def classify(op, e: Exception):
        msg = (f"{getattr(e, 'err', '')} {getattr(e, 'out', '')} "
               f"{e}")
        if op.f == "read" or definite_re.search(msg):
            return op.copy(type="fail", error=msg.strip()[:200])
        return op.copy(type="info", error=msg.strip()[:200])

    return classify
