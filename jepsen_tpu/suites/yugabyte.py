"""YugabyteDB test suite: an API-parameterized workload matrix — each
workload runs over YCQL (cassandra dialect) or YSQL (postgres
dialect), exactly the reference's two client families.

Capability reference: yugabyte/src/yugabyte/
  core.clj:75-105  — workloads-ycql / workloads-ysql matrix this
                     module mirrors (counter, set, set-index, bank,
                     bank-multitable, long-fork, single/multi-key-acid,
                     append, append-table, default-value)
  auto.clj         — master/tserver daemon automation, replication
                     factor, --master_addresses wiring
  ycql/client.clj, ysql/client.clj — per-API clients; ycql bank runs
                     allow-negatives (core.clj:80-82 comment)
  ysql/append.clj  — elle list-append over text-concat rows;
                     append_table.clj the per-table variant
  ysql/default_value.clj — DDL default-value race: concurrent ALTER
                     TABLE ADD COLUMN DEFAULT + inserts; no read may
                     see a NULL in the defaulted column
  multi_key_acid.clj — atomic two-key writes, linearizable against a
                     multi-register model

Transport: `ysqlsh` (psql-compatible) and `ycqlsh -e` on the client's
own node. Clients depend on a small `run(stmt) -> str` runner, so the
clusterless tests substitute scripted fakes.
"""

from __future__ import annotations

import logging
import re
import random as _random

from .. import checker as chk
from .. import cli, client as jclient, control, db as jdb
from .. import generator as gen
from .. import independent, testing
from ..checker import models
from ..control import util as cu
from ..control.core import RemoteError
from ..os_setup import debian
from ..workloads import bank as bank_wl
from ..workloads import counter as counter_wl
from ..workloads import long_fork as lf_wl
from ..workloads import sets as sets_wl
from ..workloads import txn_append as append_wl

logger = logging.getLogger(__name__)

DIR = "/opt/yugabyte"
VERSION = "2.20.1.3"
URL = (f"https://downloads.yugabyte.com/releases/{VERSION}/"
       f"yugabyte-{VERSION}-b3-linux-x86_64.tar.gz")
MASTER_PORT = 7100
TSERVER_PORT = 9100
YSQL_PORT = 5433
YCQL_PORT = 9042
MASTER = (f"{DIR}/master.log", f"{DIR}/master.pid")
TSERVER = (f"{DIR}/tserver.log", f"{DIR}/tserver.pid")
KEYSPACE = "jepsen"


def master_addresses(test) -> str:
    return ",".join(f"{n}:{MASTER_PORT}" for n in test["nodes"])


class YbDB(jdb.DB):
    """Installs and runs yb-master + yb-tserver on every node
    (auto.clj start-master!/start-tserver!)."""

    supports_kill = True

    def __init__(self, version: str = VERSION, replicas: int = 3):
        self.version = version
        self.replicas = replicas

    def setup(self, test, node):
        with control.su():
            cu.install_archive(URL, DIR)
            control.exec_(f"{DIR}/bin/post_install.sh", check=False)
        self._start_master(test, node)
        self._start_tserver(test, node)
        cu.await_tcp_port(YSQL_PORT, timeout_secs=180)
        # YCQL clients run inside this keyspace (ycqlsh has no
        # default; unqualified DDL would fail otherwise)
        control.exec_(
            f"{DIR}/bin/ycqlsh", node, str(YCQL_PORT), "-e",
            f"CREATE KEYSPACE IF NOT EXISTS {KEYSPACE};",
            check=False)

    def _start_master(self, test, node):
        with control.su():
            cu.start_daemon(
                {"chdir": DIR, "logfile": MASTER[0],
                 "pidfile": MASTER[1]},
                f"{DIR}/bin/yb-master",
                "--master_addresses", master_addresses(test),
                "--rpc_bind_addresses", f"{node}:{MASTER_PORT}",
                "--replication_factor", str(self.replicas),
                "--fs_data_dirs", f"{DIR}/data/master")

    def _start_tserver(self, test, node):
        with control.su():
            cu.start_daemon(
                {"chdir": DIR, "logfile": TSERVER[0],
                 "pidfile": TSERVER[1]},
                f"{DIR}/bin/yb-tserver",
                "--tserver_master_addrs", master_addresses(test),
                "--rpc_bind_addresses", f"{node}:{TSERVER_PORT}",
                "--start_pgsql_proxy",
                "--pgsql_proxy_bind_address", f"{node}:{YSQL_PORT}",
                "--cql_proxy_bind_address", f"{node}:{YCQL_PORT}",
                "--fs_data_dirs", f"{DIR}/data/tserver")

    def teardown(self, test, node):
        self.kill(test, node)
        with control.su():
            control.exec_("rm", "-rf", f"{DIR}/data", MASTER[0],
                          TSERVER[0], check=False)

    def log_files(self, test, node):
        return [MASTER[0], TSERVER[0]]

    def kill(self, test, node):
        with control.su():
            cu.grepkill("yb-master")
            cu.grepkill("yb-tserver")
            control.exec_("rm", "-rf", MASTER[1], TSERVER[1],
                          check=False)

    def start(self, test, node):
        self._start_master(test, node)
        self._start_tserver(test, node)


# ---------------------------------------------------------------------------
# Runners (ysqlsh / ycqlsh), swappable in tests
# ---------------------------------------------------------------------------


class YsqlRunner:
    """SQL through ysqlsh on the client's own node (ysql/client.clj)."""

    dialect = "ysql"

    def __init__(self, test, node, timeout: float = 10.0):
        self.node = node
        self.timeout = timeout

    def run(self, stmt: str) -> str:
        return control.exec_(
            f"{DIR}/bin/ysqlsh", "-h", self.node, "-p",
            str(YSQL_PORT), "-U", "yugabyte", "-d", "yugabyte",
            "-X", "-q", "-A", "-t", "-v", "ON_ERROR_STOP=1",
            "-c", stmt, timeout=self.timeout)

    def close(self):
        pass


class YcqlRunner:
    """CQL through ycqlsh on the client's own node (ycql/client.clj)."""

    dialect = "ycql"

    def __init__(self, test, node, timeout: float = 10.0):
        self.node = node
        self.timeout = timeout

    def run(self, stmt: str) -> str:
        return control.exec_(
            f"{DIR}/bin/ycqlsh", self.node, str(YCQL_PORT),
            "--no-color", "-k", KEYSPACE, "-e", stmt,
            timeout=self.timeout)

    def close(self):
        pass


RUNNERS = {"ysql": YsqlRunner, "ycql": YcqlRunner}

# Definite rejections: the statement was refused, nothing committed
_DEFINITE = ("could not serialize", "conflicts with higher priority",
             "restart read required", "duplicate key",
             "invalidqueryexception", "conditional", "aborted")


def _classify(op, e: Exception, writing: bool):
    msg = str(e).lower()
    if any(p in msg for p in _DEFINITE):
        return op.copy(type="fail", error=str(e)[:200])
    return op.copy(type="info" if writing else "fail",
                   error=str(e)[:200])


def _int_lines(out: str) -> list[int]:
    """Integers from CLI output, one per line — robust to ycqlsh's
    headers, rules, and '(n rows)' trailers."""
    vals = []
    for line in out.splitlines():
        s = line.strip()
        if re.fullmatch(r"-?\d+", s):
            vals.append(int(s))
    return vals


class _YbClient(jclient.Client):
    runner_factory: type = YsqlRunner
    setup_stmts: tuple = ()

    @property
    def dialect(self) -> str:
        return getattr(self.runner, "dialect", "ysql")

    def __init__(self, runner_factory=None):
        if runner_factory is not None:
            self.runner_factory = runner_factory
        self.runner = None

    def open(self, test, node):
        c = type(self)(self.runner_factory)
        c.runner = self.runner_factory(test, node)
        return c

    def setup(self, test):
        if self.runner is not None:
            for stmt in self.setup_stmts:
                try:
                    self.runner.run(stmt)
                except RemoteError:
                    pass
        return self

    def close(self, test):
        if self.runner is not None:
            self.runner.close()
            self.runner = None


# -- counter ---------------------------------------------------------------


class CounterClient(_YbClient):
    """increment/read one counter row (ycql/counter.clj uses a CQL
    counter column; ysql an int column). UPDATE .. count + x is valid
    in both dialects; only the DDL differs."""

    @property
    def setup_stmts(self):
        if self.dialect == "ycql":
            # CQL counter tables can't be INSERTed; the first UPDATE
            # creates the row
            return ("CREATE TABLE IF NOT EXISTS counters "
                    "(id INT PRIMARY KEY, count COUNTER)",)
        return (
            "CREATE TABLE IF NOT EXISTS counters (id INT PRIMARY "
            "KEY, count INT)",
            "INSERT INTO counters (id, count) VALUES (0, 0) "
            "ON CONFLICT (id) DO NOTHING",
        )

    def invoke(self, test, op):
        try:
            if op.f == "add":
                self.runner.run("UPDATE counters SET count = count + "
                                f"{op.value} WHERE id = 0")
                return op.copy(type="ok")
            out = self.runner.run(
                "SELECT count FROM counters WHERE id = 0")
            vals = _int_lines(out)
            return op.copy(type="ok", value=vals[0] if vals else 0)
        except RemoteError as e:
            return _classify(op, e, op.f == "add")


# -- set -------------------------------------------------------------------


class SetClient(_YbClient):
    """add unique ints / read them all (ycql+ysql set.clj); the
    `index` flavor reads through a covering secondary index
    (ycql/set.clj CQLSetIndexClient)."""

    index = False
    setup_stmts = (
        "CREATE TABLE IF NOT EXISTS elements (v INT PRIMARY KEY)",
    )

    def invoke(self, test, op):
        try:
            if op.f == "add":
                self.runner.run(
                    f"INSERT INTO elements (v) VALUES ({op.value})")
                return op.copy(type="ok")
            out = self.runner.run("SELECT v FROM elements")
            return op.copy(type="ok", value=sorted(_int_lines(out)))
        except RemoteError as e:
            return _classify(op, e, op.f == "add")


class SetIndexClient(SetClient):
    index = True
    setup_stmts = SetClient.setup_stmts + (
        "CREATE INDEX IF NOT EXISTS elements_idx ON elements (v)",
    )


# -- bank ------------------------------------------------------------------


class BankClient(_YbClient):
    """Single-table bank; transfers in one SQL txn. The reference runs
    allow-negatives for both APIs (core.clj:80-82), so the guard stays
    out and the checker gets negative-balances? true. `multitable`
    puts every account in its own table (ysql/bank.clj
    YSQLMultiBankClient)."""

    multitable = False
    accounts = tuple(range(8))
    initial = 10

    @property
    def setup_stmts(self):
        # CQL INSERT is already an upsert; ON CONFLICT is ysql-only
        guard = ("" if self.dialect == "ycql"
                 else " ON CONFLICT (id) DO NOTHING")
        if self.multitable:
            out = []
            for a in self.accounts:
                out.append(f"CREATE TABLE IF NOT EXISTS bank{a} "
                           "(id INT PRIMARY KEY, balance INT)")
                out.append(f"INSERT INTO bank{a} (id, balance) "
                           f"VALUES (0, {self.initial}){guard}")
            return tuple(out)
        return (
            "CREATE TABLE IF NOT EXISTS bank (id INT PRIMARY KEY, "
            "balance INT)",
        ) + tuple(
            f"INSERT INTO bank (id, balance) VALUES ({a}, "
            f"{self.initial}){guard}" for a in self.accounts)

    def _table(self, a):
        return f"bank{a}" if self.multitable else "bank"

    def _id(self, a):
        return 0 if self.multitable else a

    def _read_stmt(self) -> str:
        # ONE statement = one snapshot: a per-account SELECT loop
        # would read across concurrent transfers (bank.clj reads all
        # balances in a single query)
        if self.multitable:
            return " UNION ALL ".join(
                f"SELECT {a} AS id, balance FROM bank{a} WHERE id = 0"
                for a in self.accounts)
        if self.dialect == "ycql":
            # CQL rejects ORDER BY on the partition key; rows sort
            # host-side by the parsed ids anyway
            return "SELECT id, balance FROM bank"
        return "SELECT id, balance FROM bank ORDER BY id"

    def _txn(self, stmts: list[str]) -> str:
        if self.dialect == "ycql":
            return ("BEGIN TRANSACTION " + "; ".join(stmts)
                    + "; END TRANSACTION;")
        return ("BEGIN TRANSACTION ISOLATION LEVEL SERIALIZABLE; "
                + "; ".join(stmts) + "; COMMIT;")

    def invoke(self, test, op):
        try:
            if op.f == "read":
                out = self.runner.run(self._read_stmt())
                bal = {}
                for line in out.splitlines():
                    m = re.match(r"\s*(\d+)\s*\|\s*(-?\d+)\s*$",
                                 line)
                    if m:
                        bal[int(m.group(1))] = int(m.group(2))
                return op.copy(type="ok", value=bal)
            v = op.value
            frm, to, amt = v["from"], v["to"], v["amount"]
            self.runner.run(self._txn([
                f"UPDATE {self._table(frm)} SET balance = balance - "
                f"{amt} WHERE id = {self._id(frm)}",
                f"UPDATE {self._table(to)} SET balance = balance + "
                f"{amt} WHERE id = {self._id(to)}"]))
            return op.copy(type="ok")
        except RemoteError as e:
            return _classify(op, e, op.f == "transfer")


class MultiBankClient(BankClient):
    multitable = True


# -- single-key acid -------------------------------------------------------


class SingleKeyAcidClient(_YbClient):
    """Per-key linearizable register: write / read / cas one row
    (single_key_acid.clj; CQL uses IF-conditions, SQL a guarded
    UPDATE)."""

    setup_stmts = (
        "CREATE TABLE IF NOT EXISTS registers (id INT PRIMARY KEY, "
        "val INT)",
    )

    def invoke(self, test, op):
        k, v = op.value
        try:
            if op.f == "read":
                out = self.runner.run(
                    f"SELECT val FROM registers WHERE id = {k}")
                vals = _int_lines(out)
                return op.copy(type="ok",
                               value=(k, vals[0] if vals else None))
            if op.f == "write":
                if self.dialect == "ycql":
                    # CQL INSERT is an upsert
                    self.runner.run(
                        f"INSERT INTO registers (id, val) VALUES "
                        f"({k}, {v})")
                else:
                    self.runner.run(
                        f"INSERT INTO registers (id, val) VALUES "
                        f"({k}, {v}) ON CONFLICT (id) DO UPDATE SET "
                        f"val = {v}")
                return op.copy(type="ok")
            old, new = v
            if self.dialect == "ycql":
                out = self.runner.run(
                    f"UPDATE registers SET val = {new} WHERE "
                    f"id = {k} IF val = {old}")
                applied = "true" in out.lower()
            else:
                out = self.runner.run(
                    f"UPDATE registers SET val = {new} WHERE "
                    f"id = {k} AND val = {old} RETURNING val")
                applied = bool(_int_lines(out))
            if applied:
                return op.copy(type="ok")
            return op.copy(type="fail", error="cas mismatch")
        except RemoteError as e:
            return _classify(op, e, op.f != "read")


# -- multi-key acid --------------------------------------------------------


class MultiRegister(models.Model):
    """Two registers written atomically; reads see both
    (multi_key_acid.clj's multi-register model)."""

    tabulable = True

    def __init__(self, vals=(None, None)):
        self.vals = tuple(vals)

    def step(self, op):
        if op.f == "write":
            return MultiRegister([op.value[0][1], op.value[1][1]])
        if op.value is None:
            return self
        want = (op.value[0][1], op.value[1][1])
        if want == self.vals:
            return self
        return models.inconsistent(
            f"read {want}, register holds {self.vals}")

    def __eq__(self, other):
        return (isinstance(other, MultiRegister)
                and self.vals == other.vals)

    def __hash__(self):
        return hash(self.vals)

    def __repr__(self):
        return f"MultiRegister{self.vals}"


class MultiKeyAcidClient(_YbClient):
    """Atomic two-subkey writes per key group; value is
    [[subkey, v], [subkey, v]] (multi_key_acid.clj)."""

    setup_stmts = (
        "CREATE TABLE IF NOT EXISTS multireg (id TEXT PRIMARY KEY, "
        "val INT)",
    )

    def invoke(self, test, op):
        k, v = op.value
        try:
            if op.f == "write":
                if self.dialect == "ycql":
                    stmts = "; ".join(
                        f"INSERT INTO multireg (id, val) VALUES "
                        f"('{k}_{sk}', {x})" for sk, x in v)
                    self.runner.run("BEGIN TRANSACTION " + stmts
                                    + "; END TRANSACTION;")
                else:
                    stmts = "; ".join(
                        f"INSERT INTO multireg (id, val) VALUES "
                        f"('{k}_{sk}', {x}) ON CONFLICT (id) DO "
                        f"UPDATE SET val = {x}" for sk, x in v)
                    self.runner.run(
                        "BEGIN TRANSACTION ISOLATION LEVEL "
                        "SERIALIZABLE; " + stmts + "; COMMIT;")
                return op.copy(type="ok")
            # ONE statement = one snapshot; a per-subkey SELECT loop
            # could observe an atomic write half-applied
            ids = ", ".join(f"'{k}_{sk}'" for sk, _x in v)
            out = self.runner.run(
                f"SELECT id, val FROM multireg WHERE id IN ({ids})")
            seen = {}
            for line in out.splitlines():
                m = re.match(
                    r"\s*(\S+?)_(\d+)\s*\|\s*(-?\d+)\s*$", line)
                if m:
                    seen[int(m.group(2))] = int(m.group(3))
            got = [[sk, seen.get(sk)] for sk, _x in v]
            return op.copy(type="ok", value=(k, got))
        except RemoteError as e:
            return _classify(op, e, op.f == "write")


# -- append (elle list-append) ---------------------------------------------


class AppendClient(_YbClient):
    """elle list-append over comma-concat text rows (ysql/append.clj);
    `per_table` spreads keys over tables (append_table.clj)."""

    per_table = False
    table_count = 3

    @property
    def setup_stmts(self):
        if self.per_table:
            return tuple(
                f"CREATE TABLE IF NOT EXISTS append{i} (k INT PRIMARY "
                "KEY, v TEXT)" for i in range(self.table_count))
        return ("CREATE TABLE IF NOT EXISTS append0 (k INT PRIMARY "
                "KEY, v TEXT)",)

    def _table(self, k):
        return (f"append{int(k) % self.table_count}" if self.per_table
                else "append0")

    def invoke(self, test, op):
        try:
            stmts = []
            for i, (f, k, v) in enumerate(op.value):
                if f == "append":
                    stmts.append(
                        f"INSERT INTO {self._table(k)} (k, v) VALUES "
                        f"({k}, '{v}') ON CONFLICT (k) DO UPDATE SET "
                        f"v = {self._table(k)}.v || ',{v}'")
                else:
                    # tagged scalar subquery: ALWAYS one output line,
                    # so zero-row reads can't shift later reads'
                    # positional alignment
                    stmts.append(
                        f"SELECT 'm{i}=' || COALESCE((SELECT v FROM "
                        f"{self._table(k)} WHERE k = {k}), '~')")
            out = self.runner.run(
                "BEGIN TRANSACTION ISOLATION LEVEL SERIALIZABLE; "
                + "; ".join(stmts) + "; COMMIT;")
            tagged = {}
            for line in out.splitlines():
                m = re.match(r"\s*m(\d+)=(.*)$", line.strip())
                if m:
                    tagged[int(m.group(1))] = m.group(2)
            res = [list(m_) for m_ in op.value]
            for i, (f, k, v) in enumerate(op.value):
                if f != "append":
                    raw = tagged.get(i, "~")
                    res[i][2] = ([int(x) for x in raw.split(",") if x]
                                 if raw != "~" else [])
            return op.copy(type="ok", value=res)
        except RemoteError as e:
            return _classify(op, e, True)


class AppendTableClient(AppendClient):
    per_table = True


class TxnWClient(_YbClient):
    """w/r micro-op txns for long-fork (ycql/ysql long_fork.clj):
    writes upsert single-int cells, reads come back tagged so
    zero-row reads can't misalign."""

    setup_stmts = (
        "CREATE TABLE IF NOT EXISTS lf (k INT PRIMARY KEY, v INT)",
    )

    def _invoke_ycql(self, op):
        # YCQL transactions accept only DML — no SELECT, no
        # expressions. long-fork txns are single-write or all-read
        # (long_fork.clj's generator shape), so: writes go in a
        # DML-only txn, reads as ONE SELECT .. IN (a single-statement
        # snapshot).
        writes = [(k, v) for f, k, v in op.value if f == "w"]
        res = [list(m_) for m_ in op.value]
        if writes:
            stmts = "; ".join(f"INSERT INTO lf (k, v) VALUES "
                              f"({k}, {v})" for k, v in writes)
            if len(writes) == 1:
                self.runner.run(stmts + ";")
            else:
                self.runner.run("BEGIN TRANSACTION " + stmts
                                + "; END TRANSACTION;")
        read_keys = [k for f, k, v in op.value if f == "r"]
        if read_keys:
            ks = ", ".join(str(k) for k in read_keys)
            out = self.runner.run(
                f"SELECT k, v FROM lf WHERE k IN ({ks})")
            seen = {}
            for line in out.splitlines():
                m = re.match(r"\s*(\d+)\s*\|\s*(-?\d+)\s*$",
                             line)
                if m:
                    seen[int(m.group(1))] = int(m.group(2))
            for i, (f, k, v) in enumerate(op.value):
                if f == "r":
                    res[i][2] = seen.get(k)
        return op.copy(type="ok", value=res)

    def invoke(self, test, op):
        try:
            if self.dialect == "ycql":
                return self._invoke_ycql(op)
            stmts = []
            for i, (f, k, v) in enumerate(op.value):
                if f == "w":
                    stmts.append(
                        f"INSERT INTO lf (k, v) VALUES ({k}, {v})"
                        f" ON CONFLICT (k) DO UPDATE SET v = {v}")
                else:
                    stmts.append(
                        f"SELECT 'm{i}=' || COALESCE((SELECT "
                        f"CAST(v AS TEXT) FROM lf WHERE k = {k}), "
                        "'~')")
            out = self.runner.run(
                "BEGIN TRANSACTION ISOLATION LEVEL SERIALIZABLE; "
                + "; ".join(stmts) + "; COMMIT;")
            tagged = {}
            for line in out.splitlines():
                m = re.match(r"\s*m(\d+)=(.*)$", line.strip())
                if m:
                    tagged[int(m.group(1))] = m.group(2)
            res = [list(m_) for m_ in op.value]
            for i, (f, k, v) in enumerate(op.value):
                if f == "r":
                    raw = tagged.get(i, "~")
                    res[i][2] = None if raw == "~" else int(raw)
            return op.copy(type="ok", value=res)
        except RemoteError as e:
            return _classify(op, e, True)


# -- default-value (DDL race) ----------------------------------------------


def check_default_values(hist) -> dict:
    """No read may observe NULL in the defaulted column
    (ysql/default_value.clj checker)."""
    bad = [op for op in hist
           if op.type == "ok" and op.f == "read"
           and isinstance(op.value, list)
           and any(v is None for v in op.value)]
    return {"valid?": not bad,
            "bad-reads": [o.to_dict() for o in bad[:8]]}


class DefaultValueClient(_YbClient):
    """Concurrent ALTER TABLE ADD COLUMN ... DEFAULT vs inserts vs
    full-column reads (ysql/default_value.clj)."""

    setup_stmts = (
        "CREATE TABLE IF NOT EXISTS dv (id SERIAL PRIMARY KEY)",
    )

    def invoke(self, test, op):
        try:
            if op.f == "insert":
                self.runner.run("INSERT INTO dv DEFAULT VALUES")
                return op.copy(type="ok")
            if op.f == "add-column":
                self.runner.run(
                    f"ALTER TABLE dv ADD COLUMN IF NOT EXISTS "
                    f"c{op.value} INT NOT NULL DEFAULT 0")
                return op.copy(type="ok")
            out = self.runner.run(
                "SELECT * FROM dv ORDER BY id DESC LIMIT 8")
            vals = []
            for line in out.splitlines():
                for cell in line.split("|")[1:]:
                    vals.append(int(cell) if cell.strip() else None)
            return op.copy(type="ok", value=vals)
        except RemoteError as e:
            return _classify(op, e, op.f != "read")


def default_value_workload(opts):
    o = dict(opts or {})
    cols = iter(range(10_000))

    def one():
        r = _random.random()
        if r < 0.45:
            return {"f": "insert", "value": None}
        if r < 0.55:
            return {"f": "add-column", "value": next(cols)}
        return {"f": "read", "value": None}

    return {
        "generator": gen.limit(o.get("ops", 200), one),
        "checker": chk.checker(
            lambda test, hist, copts: check_default_values(hist)),
        "client": DefaultValueClient(),
    }


def multi_key_acid_workload(opts):
    o = dict(opts or {})
    keys = o.get("keys", list(range(6)))

    def key_gen(k):
        rng = _random.Random(None if o.get("seed") is None
                             else repr((o.get("seed"), k)))

        def one():
            if rng.random() < 0.5:
                v = rng.randrange(5)
                return {"f": "write", "value": [[0, v], [1, v + 100]]}
            return {"f": "read", "value": [[0, None], [1, None]]}

        return gen.limit(o.get("ops_per_key", 40), one)

    return {
        "generator": independent.concurrent_generator(
            o.get("group_size", 3), keys, key_gen),
        "checker": independent.checker(chk.linearizable(
            {"model": MultiRegister()})),
        "client": MultiKeyAcidClient(),
    }


def single_key_acid_workload(opts):
    from ..workloads import register as register_wl

    o = dict(opts or {})
    w = register_wl.workload(dict(o, initial=None))
    w["client"] = SingleKeyAcidClient()
    return w


# ---------------------------------------------------------------------------
# The API x workload matrix (core.clj:75-105)
# ---------------------------------------------------------------------------


def _with(base_fn, client, **extra):
    def build(opts):
        w = base_fn(dict(opts or {}, **extra))
        w["client"] = client()
        return w

    return build


def _bank(opts):
    o = dict(opts or {})
    o.setdefault("negative-balances?", True)  # core.clj:80-82
    return bank_wl.workload(o)


WORKLOADS = {
    "ycql/counter": _with(counter_wl.workload, CounterClient),
    "ycql/set": _with(sets_wl.workload, SetClient),
    "ycql/set-index": _with(sets_wl.workload, SetIndexClient),
    "ycql/bank": _with(_bank, BankClient),
    "ycql/long-fork": _with(lf_wl.workload, TxnWClient),
    "ycql/single-key-acid": single_key_acid_workload,
    "ycql/multi-key-acid": multi_key_acid_workload,
    "ysql/counter": _with(counter_wl.workload, CounterClient),
    "ysql/set": _with(sets_wl.workload, SetClient),
    "ysql/bank": _with(_bank, BankClient),
    "ysql/bank-multitable": _with(_bank, MultiBankClient),
    "ysql/long-fork": _with(lf_wl.workload, TxnWClient),
    "ysql/single-key-acid": single_key_acid_workload,
    "ysql/multi-key-acid": multi_key_acid_workload,
    "ysql/append": _with(append_wl.workload, AppendClient),
    "ysql/append-table": _with(append_wl.workload, AppendTableClient),
    "ysql/default-value": default_value_workload,
}


def workload_for(name: str, opts: dict) -> dict:
    """Resolves 'api/workload' (or bare workload + --api opt) and pins
    the matching runner dialect onto the client."""
    if "/" not in name:
        name = f"{opts.get('api', 'ysql')}/{name}"
    api = name.split("/")[0]
    w = WORKLOADS[name](opts)
    w["client"].runner_factory = RUNNERS[api]
    return w, name


def nemesis_for(opts: dict, db) -> dict:
    from ..nemesis import combined

    faults = set(opts.get("faults") or ("partition", "kill"))
    o = dict(opts)
    o.update(db=db, faults=faults,
             interval=opts.get("nemesis_interval", 15))
    return combined.compose_packages(combined.nemesis_packages(o))


def yugabyte_test(opts: dict) -> dict:
    w, name = workload_for(opts.get("workload") or "ysql/append",
                           opts)
    db = YbDB(version=opts.get("version", VERSION),
              replicas=opts.get("replicas", 3))
    pkg = nemesis_for(opts, db)
    test = testing.noop_test()
    test.update(
        name=f"yugabyte-{name.replace('/', '-')}",
        os=debian.os,
        db=db,
        ssh=opts["ssh"],
        nodes=opts["nodes"],
        concurrency=opts["concurrency"],
        client=w["client"],
        nemesis=pkg["nemesis"],
        checker=chk.compose({"workload": w["checker"],
                             "stats": chk.stats(),
                             "perf": chk.perf(),
                             "timeline": chk.timeline()}),
        generator=_suite_generator(opts, w, pkg))
    for extra in ("total-amount", "accounts"):
        if extra in w:
            test[extra] = w[extra]
    return test


def _suite_generator(opts, w, pkg):
    nemesis_gen = pkg.get("generator")
    client_part = gen.stagger(1.0 / opts.get("rate", 15),
                              w["generator"])
    mix = gen.time_limit(
        opts.get("time_limit", 60),
        gen.clients(client_part, nemesis_gen)
        if nemesis_gen is not None else gen.clients(client_part))
    parts = [mix]
    final = w.get("final_generator")
    if final is not None:
        parts.append(gen.sleep(opts.get("recovery_time", 10)))
        parts.append(gen.clients(final))
    return parts[0] if len(parts) == 1 else gen.phases(*parts)


def _opts(p):
    p.add_argument("--workload", default=None,
                   help="api/workload (default ysql/append). "
                        + cli.one_of(WORKLOADS))
    p.add_argument("--api", default="ysql", choices=("ysql", "ycql"),
                   help="API for bare workload names")
    p.add_argument("--rate", type=float, default=15)
    p.add_argument("--version", default=VERSION)
    p.add_argument("--replicas", type=int, default=3)
    return p


def main(argv=None) -> None:
    commands = {}
    commands.update(cli.single_test_cmd(yugabyte_test,
                                        parser_fn=_opts))
    commands.update(cli.serve_cmd())
    cli.run_cli(commands, argv)


if __name__ == "__main__":
    main()
