"""Hazelcast test suite: CP-subsystem locks, semaphores, CAS longs,
id generators, and queues against a real coordination service.

Capability reference: hazelcast/src/jepsen/hazelcast.clj — the DB
builds + ships its own server jar and starts it with a --members list
(34-118); clients are per-structure (lock 258-327, fenced/reentrant
CP locks 329-420, CP semaphore 422-453, atomic long / reference CAS
169-256, queue 47-120 in the workload map, id-gen); the workload map
(652-768) pairs each client with a cycled acquire/release generator
and a linearizable checker over the matching mutex/semaphore model.

The op->model semantics (OwnerMutex, FencedMutex, ReentrantMutex,
Semaphore) live in jepsen_tpu.workloads.lock; this suite contributes
the DB automation and the wire clients. Like the reference — which
runs its OWN server project rather than stock hazelcast alone
(hazelcast.clj:34-66 `build-server!`) — the client side is a thin
bundled console jar speaking a line protocol:

    lock acquire <name>      -> OK <fence> | BUSY
    lock release <name>      -> OK | ERR <msg>
    sem acquire <name>       -> OK | BUSY
    sem release <name>       -> OK | ERR <msg>
    long read <name>         -> OK <v>
    long write <name> <v>    -> OK
    long cas <name> <a> <b>  -> OK | FAIL
    ref read <name>          -> OK <v>|nil
    ref write <name> <v>     -> OK
    ref cas <name> <a> <b>   -> OK | FAIL
    id next <name>           -> OK <id>
    q offer <name> <v>       -> OK
    q poll <name>            -> OK <v> | EMPTY

One JVM invocation per op (`java -jar client.jar --addresses ...
--session jepsen-p<process> --cmd ...`): CP lock/semaphore state is
bound to a CP SESSION, so the jar manages one NAMED session per jepsen
process through the CP Session Management API (create-if-absent on
first use) instead of the client's auto-session — otherwise every JVM
exit would end the session and auto-release held locks mid-test. The
server config stretches session-time-to-live to outlive think time
between a process's ops; a crashed process's session simply expires
(its locks release), exactly the reincarnation semantics the lock
models expect.
"""

from __future__ import annotations

import logging

from .. import checker as chk
from .. import cli, client as jclient, control, db as jdb
from .. import generator as gen
from .. import testing
from ..checker import models
from ..control import util as cu
from ..control.core import RemoteError
from ..os_setup import debian
from ..workloads import lock as lock_wl
from ..workloads import queue as queue_wl
from ..workloads import register as register_wl
from ..workloads import unique_ids as ids_wl

logger = logging.getLogger(__name__)

DIR = "/opt/hazelcast"
VERSION = "5.3.6"
URL = ("https://repository.hazelcast.com/download/hazelcast/"
       f"hazelcast-{VERSION}.tar.gz")
CLIENT_JAR = f"{DIR}/jepsen-client.jar"
LOG_FILE = f"{DIR}/server.log"
PID_FILE = f"{DIR}/server.pid"
CONFIG = f"{DIR}/config/hazelcast.yaml"
PORT = 5701


def member_config(test) -> str:
    """Server YAML: static member list + CP subsystem sized to the
    cluster (the reference passes --members on the command line,
    hazelcast.clj:78-89; CP needs >= 3 members for raft)."""
    nodes = test["nodes"]
    members = "\n".join(f"          - {n}:{PORT}" for n in nodes)
    cp = max(len(nodes), 3)
    return f"""hazelcast:
  cluster-name: jepsen
  network:
    port:
      port: {PORT}
    join:
      multicast:
        enabled: false
      tcp-ip:
        enabled: true
        member-list:
{members}
  cp-subsystem:
    cp-member-count: {cp}
    session-time-to-live-seconds: 600
    session-heartbeat-interval-seconds: 5
"""


class HzDB(jdb.DB):
    """Installs and runs hazelcast members (hazelcast.clj db, 98-118)."""

    supports_kill = True

    def __init__(self, version: str = VERSION):
        self.version = version

    def setup(self, test, node):
        with control.su():
            debian.install(["openjdk-17-jre-headless"])
            cu.install_archive(URL, DIR)
            cu.write_file(member_config(test), CONFIG)
        self.start(test, node)
        cu.await_tcp_port(PORT, timeout_secs=90)

    def teardown(self, test, node):
        self.kill(test, node)
        with control.su():
            control.exec_("rm", "-rf", LOG_FILE, PID_FILE,
                          check=False)

    def log_files(self, test, node):
        return [LOG_FILE]

    def start(self, test, node):
        with control.su():
            cu.start_daemon(
                {"chdir": DIR, "logfile": LOG_FILE,
                 "pidfile": PID_FILE},
                f"{DIR}/bin/hz", "start", "-c", CONFIG)

    def kill(self, test, node):
        with control.su():
            cu.grepkill("com.hazelcast")
            control.exec_("rm", "-rf", PID_FILE, check=False)


# ---------------------------------------------------------------------------
# Clients
# ---------------------------------------------------------------------------


class HzConsole:
    """One-shot line-protocol invocations of the bundled client jar,
    bound to one named CP session per jepsen process (see module
    docstring)."""

    def __init__(self, test, node, timeout: float = 10.0):
        self.node = node
        self.addresses = ",".join(f"{n}:{PORT}" for n in test["nodes"])
        self.timeout = timeout

    def cmd(self, line: str, session: str = "jepsen") -> str:
        out = control.exec_(
            "timeout", str(int(self.timeout)), "java", "-jar",
            CLIENT_JAR, "--addresses", self.addresses,
            "--session", session, "--cmd", line)
        return out.strip()


class _HzClient(jclient.Client):
    """Shared open/close: one console handle per (client, node)."""

    console_factory = HzConsole

    def __init__(self, console_factory=None):
        if console_factory is not None:
            self.console_factory = console_factory
        self.console = None

    def open(self, test, node):
        c = type(self)(self.console_factory)
        c.console = self.console_factory(test, node)
        return c

    def close(self, test):
        self.console = None


class LockClient(_HzClient):
    """acquire/release ops against one named CP lock; ok acquires
    carry {"fence": n} (hazelcast.clj lock/fenced-lock clients,
    258-420 — the fence is FencedLock.getFence)."""

    def __init__(self, console_factory=None, name: str = "jepsen.lock"):
        super().__init__(console_factory)
        self.name = name

    def open(self, test, node):
        c = super().open(test, node)
        c.name = self.name
        return c

    def invoke(self, test, op):
        try:
            out = self.console.cmd(
                f"lock {op.f} {self.name}",
                session=f"jepsen-p{op.process}")
        except RemoteError as e:
            return op.copy(type="info", error=str(e))
        if out.startswith("OK"):
            parts = out.split()
            if op.f == "acquire" and len(parts) > 1:
                return op.copy(type="ok",
                               value={"fence": int(parts[1])})
            return op.copy(type="ok")
        if out == "BUSY":
            return op.copy(type="fail", error="busy")
        return op.copy(type="fail", error=out)


class SemaphoreClient(_HzClient):
    """acquire/release against one named CP semaphore
    (hazelcast.clj cp-semaphore-client, 422-453)."""

    def __init__(self, console_factory=None,
                 name: str = "jepsen.semaphore"):
        super().__init__(console_factory)
        self.name = name

    def open(self, test, node):
        c = super().open(test, node)
        c.name = self.name
        return c

    def invoke(self, test, op):
        try:
            out = self.console.cmd(
                f"sem {op.f} {self.name}",
                session=f"jepsen-p{op.process}")
        except RemoteError as e:
            return op.copy(type="info", error=str(e))
        if out.startswith("OK"):
            return op.copy(type="ok")
        if out == "BUSY":
            return op.copy(type="fail", error="no permits")
        return op.copy(type="fail", error=out)


class CasLongClient(_HzClient):
    """read/write/cas on a CP IAtomicLong (hazelcast.clj
    cp-cas-long-client, 169-211)."""

    def __init__(self, console_factory=None,
                 name: str = "jepsen.cas-long"):
        super().__init__(console_factory)
        self.name = name

    def open(self, test, node):
        c = super().open(test, node)
        c.name = self.name
        return c

    def invoke(self, test, op):
        try:
            if op.f == "read":
                out = self.console.cmd(f"long read {self.name}")
                if out.startswith("OK"):
                    v = out.split()[1]
                    return op.copy(type="ok",
                                   value=None if v == "nil"
                                   else int(v))
            elif op.f == "write":
                out = self.console.cmd(
                    f"long write {self.name} {op.value}")
                if out.startswith("OK"):
                    return op.copy(type="ok")
            else:  # cas
                a, b = op.value
                out = self.console.cmd(f"long cas {self.name} {a} {b}")
                if out.startswith("OK"):
                    return op.copy(type="ok")
                if out == "FAIL":
                    return op.copy(type="fail", error="cas failed")
        except RemoteError as e:
            # reads fail safely; writes/cas are indeterminate
            t = "fail" if op.f == "read" else "info"
            return op.copy(type=t, error=str(e))
        return op.copy(type="fail", error=out)


class IdGenClient(_HzClient):
    """generate ops against a CP atomic-long id source (hazelcast.clj
    cp-id-gen-long / atomic-ref-ids, 232-256)."""

    def __init__(self, console_factory=None, name: str = "jepsen.ids"):
        super().__init__(console_factory)
        self.name = name

    def open(self, test, node):
        c = super().open(test, node)
        c.name = self.name
        return c

    def invoke(self, test, op):
        try:
            out = self.console.cmd(f"id next {self.name}")
        except RemoteError as e:
            return op.copy(type="info", error=str(e))
        if out.startswith("OK"):
            return op.copy(type="ok", value=int(out.split()[1]))
        return op.copy(type="fail", error=out)


class QueueClient(_HzClient):
    """enqueue/dequeue against a distributed queue (hazelcast.clj
    queue-client, total-queue checked)."""

    def __init__(self, console_factory=None, name: str = "jepsen.q"):
        super().__init__(console_factory)
        self.name = name

    def open(self, test, node):
        c = super().open(test, node)
        c.name = self.name
        return c

    def invoke(self, test, op):
        try:
            if op.f == "enqueue":
                out = self.console.cmd(
                    f"q offer {self.name} {op.value}")
                if out.startswith("OK"):
                    return op.copy(type="ok")
                return op.copy(type="info", error=out)
            if op.f == "drain":
                got = []
                while True:
                    try:
                        out = self.console.cmd(f"q poll {self.name}")
                    except RemoteError as e:
                        # elements polled so far WERE dequeued; losing
                        # them would misreport real dequeues as lost
                        return op.copy(type="info", error=str(e),
                                       value=got)
                    if out == "EMPTY":
                        return op.copy(type="ok", value=got)
                    if out.startswith("OK"):
                        got.append(int(out.split()[1]))
                    else:
                        return op.copy(type="info", error=out,
                                       value=got)
            out = self.console.cmd(f"q poll {self.name}")
        except RemoteError as e:
            return op.copy(type="info", error=str(e))
        if out == "EMPTY":
            return op.copy(type="fail", error="empty")
        if out.startswith("OK"):
            return op.copy(type="ok", value=int(out.split()[1]))
        return op.copy(type="info", error=out)


# ---------------------------------------------------------------------------
# Workloads (hazelcast.clj workloads map, 652-768)
# ---------------------------------------------------------------------------


def _lock_workload(opts, model, client, repeats=1):
    w = lock_wl._workload(dict(opts), model, repeats=repeats)
    w["client"] = client
    return w


def lock(opts):
    return _lock_workload(opts, models.mutex(),
                          LockClient(name="jepsen.lock"))


def owner_lock(opts):
    return _lock_workload(opts, lock_wl.OwnerMutex(),
                          LockClient(name="jepsen.cpLock1"))


def fenced_lock(opts):
    return _lock_workload(opts, lock_wl.FencedMutex(),
                          LockClient(name="jepsen.cpLock1"))


def reentrant_lock(opts):
    o = dict(opts)
    return _lock_workload(
        o, lock_wl.ReentrantMutex(limit=o.get("limit", 2)),
        LockClient(name="jepsen.cpLock2"), repeats=o.get("limit", 2))


def semaphore(opts):
    o = dict(opts)
    return _lock_workload(
        o, lock_wl.Semaphore(permits=o.get("permits", 2)),
        SemaphoreClient())


def _cas_workload(opts, client):
    """read/write/cas mix against ONE named CP long/reference,
    linearizable vs cas-register(0) — IAtomicLong starts at 0
    (hazelcast.clj cp-cas-long / cp-cas-reference, 169-231)."""
    import random as _random

    o = dict(opts)
    rng = _random.Random(o.get("seed"))
    g = gen.limit(o.get("ops", 300),
                  lambda: register_wl.cas_op_mix(rng))
    return {
        "generator": g,
        "checker": chk.linearizable({"model": models.cas_register(0)}),
        "client": client,
    }


class CasRefClient(_HzClient):
    """read/write/cas on a CP IAtomicReference (hazelcast.clj
    cp-cas-reference-client, 213-231): like the long, but the initial
    value is nil and reads may return nil."""

    def __init__(self, console_factory=None,
                 name: str = "jepsen.cas-ref"):
        super().__init__(console_factory)
        self.name = name

    def open(self, test, node):
        c = super().open(test, node)
        c.name = self.name
        return c

    def invoke(self, test, op):
        try:
            if op.f == "read":
                out = self.console.cmd(f"ref read {self.name}")
                if out.startswith("OK"):
                    v = out.split()[1]
                    return op.copy(type="ok",
                                   value=None if v == "nil"
                                   else int(v))
            elif op.f == "write":
                out = self.console.cmd(
                    f"ref write {self.name} {op.value}")
                if out.startswith("OK"):
                    return op.copy(type="ok")
            else:
                a, b = op.value
                out = self.console.cmd(f"ref cas {self.name} {a} {b}")
                if out.startswith("OK"):
                    return op.copy(type="ok")
                if out == "FAIL":
                    return op.copy(type="fail", error="cas failed")
        except RemoteError as e:
            t = "fail" if op.f == "read" else "info"
            return op.copy(type=t, error=str(e))
        return op.copy(type="fail", error=out)


def cas_long(opts):
    return _cas_workload(opts, CasLongClient())


def cas_reference(opts):
    """IAtomicReference starts at nil, so the model's initial value
    differs from cas_long's 0."""
    import random as _random

    o = dict(opts)
    rng = _random.Random(o.get("seed"))
    from ..workloads import register as register_wl

    g = gen.limit(o.get("ops", 300),
                  lambda: register_wl.cas_op_mix(rng))
    return {
        "generator": g,
        "checker": chk.linearizable(
            {"model": models.cas_register(None)}),
        "client": CasRefClient(),
    }


def id_gen(opts):
    w = ids_wl.workload(dict(opts))
    w["client"] = IdGenClient()
    return w


def queue(opts):
    w = queue_wl.workload(dict(opts))
    w["client"] = QueueClient()
    return w


WORKLOADS = {
    "lock": lock,
    "owner-lock": owner_lock,
    "fenced-lock": fenced_lock,
    "reentrant-lock": reentrant_lock,
    "semaphore": semaphore,
    "cas-long": cas_long,
    "cas-reference": cas_reference,
    "id-gen": id_gen,
    "queue": queue,
}


def nemesis_for(opts: dict, db) -> dict:
    from ..nemesis import combined

    faults = set(opts.get("faults") or ("partition",))
    o = dict(opts)
    o.update(db=db, faults=faults,
             interval=opts.get("nemesis_interval", 15))
    return combined.compose_packages(combined.nemesis_packages(o))


def hazelcast_test(opts: dict) -> dict:
    name = opts.get("workload") or "lock"
    w = WORKLOADS[name](opts)
    db = HzDB(version=opts.get("version", VERSION))
    pkg = nemesis_for(opts, db)
    test = testing.noop_test()
    test.update(
        name=f"hazelcast-{name}",
        os=debian.os,
        db=db,
        ssh=opts["ssh"],
        nodes=opts["nodes"],
        concurrency=opts["concurrency"],
        client=w["client"],
        nemesis=pkg["nemesis"],
        checker=chk.compose({"workload": w["checker"],
                             "stats": chk.stats(),
                             "perf": chk.perf(),
                             "timeline": chk.timeline()}),
        generator=_suite_generator(opts, w, pkg))
    return test


def _suite_generator(opts, w, pkg):
    nemesis_gen = pkg.get("generator")
    client_part = gen.stagger(1.0 / opts.get("rate", 10),
                              w["generator"])
    mix = gen.time_limit(
        opts.get("time_limit", 60),
        gen.clients(client_part, nemesis_gen)
        if nemesis_gen is not None else gen.clients(client_part))
    parts = [mix]
    final = w.get("final_generator")
    if final is not None:
        parts.append(gen.sleep(opts.get("recovery_time", 10)))
        parts.append(gen.clients(final))
    return parts[0] if len(parts) == 1 else gen.phases(*parts)


def _opts(p):
    p.add_argument("--workload", default=None,
                   help="Workload (default lock). "
                        + cli.one_of(WORKLOADS))
    p.add_argument("--rate", type=float, default=10)
    p.add_argument("--version", default=VERSION)
    return p


def main(argv=None) -> None:
    commands = {}
    commands.update(cli.single_test_cmd(hazelcast_test,
                                        parser_fn=_opts))
    commands.update(cli.serve_cmd())
    cli.run_cli(commands, argv)


if __name__ == "__main__":
    main()
