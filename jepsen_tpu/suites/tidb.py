"""TiDB test suite: elle list-append, bank and long-fork over the
mysql CLI against a pd/tikv/tidb cluster.

Capability reference: tidb/src/tidb/ — db.clj (one tarball shipping
pd-server/tikv-server/tidb-server; pd forms the quorum with
initial-cluster urls, tikv registers with pd, tidb fronts the mysql
protocol on port 4000), core.clj:32-60 (the canonical workloads map +
sweep shape), txn.clj/bank.clj/long_fork.clj (workload semantics).
The reference drives JDBC; here every transaction is one
`mysql -h <node> -P 4000` batch on the client's own node, with
tagged SELECTs carrying read results (the postgres/galera suite
transport stance — TiDB speaks the mysql dialect, so appends use
INSERT .. ON DUPLICATE KEY UPDATE CONCAT)."""

from __future__ import annotations

import logging
import re

from .. import checker as chk
from .. import cli, client as jclient, control, core, db as jdb
from .. import generator as gen
from .. import nemesis as jnemesis
from .. import testing, util as jutil, workloads
from . import common
from ..control import util as cu
from ..control.core import RemoteError
from ..core import primary
from ..os_setup import debian

logger = logging.getLogger(__name__)

VERSION = "v7.5.1"
DIR = "/opt/tidb"
PD_PORT = 2379
PD_PEER_PORT = 2380
KV_PORT = 20160
SQL_PORT = 4000
DB_NAME = "jepsen"
TABLE_COUNT = 3


def pd_initial_cluster(test) -> str:
    return ",".join(f"pd-{n}=http://{n}:{PD_PEER_PORT}"
                    for n in test["nodes"])


def pd_endpoints(test) -> str:
    return ",".join(f"{n}:{PD_PORT}" for n in test["nodes"])


class TidbDB(jdb.DB):
    """pd -> tikv -> tidb daemon stack per node (tidb/db.clj)."""

    supports_kill = True

    def __init__(self, version: str = VERSION):
        self.version = version

    def _start_all(self, test, node):
        cu.start_daemon(
            {"logfile": f"{DIR}/pd.log", "pidfile": f"{DIR}/pd.pid",
             "chdir": DIR},
            f"{DIR}/bin/pd-server",
            "--name", f"pd-{node}",
            "--data-dir", f"{DIR}/data/pd",
            "--client-urls", f"http://0.0.0.0:{PD_PORT}",
            "--advertise-client-urls", f"http://{node}:{PD_PORT}",
            "--peer-urls", f"http://0.0.0.0:{PD_PEER_PORT}",
            "--advertise-peer-urls", f"http://{node}:{PD_PEER_PORT}",
            "--initial-cluster", pd_initial_cluster(test))
        cu.await_tcp_port(PD_PORT, timeout_secs=120)
        cu.start_daemon(
            {"logfile": f"{DIR}/tikv.log", "pidfile": f"{DIR}/tikv.pid",
             "chdir": DIR},
            f"{DIR}/bin/tikv-server",
            "--pd", pd_endpoints(test),
            "--addr", f"0.0.0.0:{KV_PORT}",
            "--advertise-addr", f"{node}:{KV_PORT}",
            "--data-dir", f"{DIR}/data/tikv")
        cu.await_tcp_port(KV_PORT, timeout_secs=120)
        cu.start_daemon(
            {"logfile": f"{DIR}/tidb.log", "pidfile": f"{DIR}/tidb.pid",
             "chdir": DIR},
            f"{DIR}/bin/tidb-server",
            "-P", str(SQL_PORT),
            "--store", "tikv",
            "--path", pd_endpoints(test))
        cu.await_tcp_port(SQL_PORT, timeout_secs=180)

    def setup(self, test, node):
        logger.info("%s installing tidb %s", node, self.version)
        with control.su():
            debian.install(["mariadb-client"])  # the mysql CLI
            # the plain binary bundle (bin/{pd,tikv,tidb}-server),
            # NOT the tidb-community-server TiUP offline mirror whose
            # payload is nested per-component tarballs
            url = (f"https://download.pingcap.org/tidb-"
                   f"{self.version}-linux-amd64.tar.gz")
            cu.install_archive(url, DIR)
            self._start_all(test, node)
        core.synchronize(test)
        if node == primary(test):
            self._schema(node)
        core.synchronize(test)

    def _schema(self, node):
        stmts = [f"CREATE DATABASE IF NOT EXISTS {DB_NAME}"]
        for i in range(TABLE_COUNT):
            stmts.append(
                f"CREATE TABLE IF NOT EXISTS {DB_NAME}.txn{i} "
                "(id INT NOT NULL PRIMARY KEY, val TEXT)")
        stmts.append(f"CREATE TABLE IF NOT EXISTS {DB_NAME}.accounts "
                     "(id INT NOT NULL PRIMARY KEY, "
                     "balance BIGINT NOT NULL)")
        stmts.append(f"CREATE TABLE IF NOT EXISTS {DB_NAME}.lf "
                     "(k INT NOT NULL PRIMARY KEY, val INT)")
        stmts.append(f"CREATE TABLE IF NOT EXISTS {DB_NAME}.registers"
                     " (id INT NOT NULL PRIMARY KEY, val INT)")
        stmts.append(f"CREATE TABLE IF NOT EXISTS {DB_NAME}.sets "
                     "(id INT AUTO_INCREMENT PRIMARY KEY, val INT)")
        stmts.append(f"CREATE TABLE IF NOT EXISTS {DB_NAME}.setcas "
                     "(id INT NOT NULL PRIMARY KEY, val TEXT)")
        stmts.append(f"INSERT IGNORE INTO {DB_NAME}.setcas "
                     "VALUES (0, '')")
        stmts.append(f"CREATE TABLE IF NOT EXISTS {DB_NAME}.seq "
                     "(sk VARCHAR(64) NOT NULL PRIMARY KEY)")
        stmts.append(f"CREATE TABLE IF NOT EXISTS {DB_NAME}.mono "
                     "(val INT NOT NULL PRIMARY KEY, sts BIGINT, "
                     "node VARCHAR(16), process INT, tb INT)")
        for i in range(8):
            stmts.append(
                f"CREATE TABLE IF NOT EXISTS {DB_NAME}.bank{i} "
                "(id INT NOT NULL PRIMARY KEY, "
                "balance BIGINT NOT NULL)")
            stmts.append(f"INSERT IGNORE INTO {DB_NAME}.bank{i} "
                         "VALUES (0, 10)")
        rows = ",".join(f"({i}, 10)" for i in range(8))
        stmts.append(f"INSERT IGNORE INTO {DB_NAME}.accounts "
                     f"VALUES {rows}")
        for s in stmts:
            control.exec_("mysql", "-h", str(node), "-P",
                          str(SQL_PORT), "-u", "root", "-e", s)

    def teardown(self, test, node):
        logger.info("%s tearing down tidb", node)
        with control.su():
            for d in ("tidb", "tikv", "pd"):
                cu.grepkill(f"{d}-server")
            control.exec_("rm", "-rf", DIR)

    def kill(self, test, node):
        with control.su():
            for d in ("tidb", "tikv", "pd"):
                cu.grepkill(f"{d}-server")
        return "killed"

    def start(self, test, node):
        with control.su():
            self._start_all(test, node)
        return "started"

    def log_files(self, test, node):
        return [f"{DIR}/pd.log", f"{DIR}/tikv.log", f"{DIR}/tidb.log"]


# ---------------------------------------------------------------------------
# mysql transport
# ---------------------------------------------------------------------------

class TidbSql(common.SqlCli):
    """mysql batches against the node's tidb-server (mysql protocol,
    passwordless root)."""

    def __init__(self, test, node, timeout: float = 10.0):
        super().__init__(
            test, node,
            ["mysql", "-h", str(node), "-P", str(SQL_PORT),
             "-u", "root", "-D", DB_NAME, "-N", "-B", "-e"],
            timeout=timeout)


_classify = common.make_classifier([
    r"write conflict", r"deadlock", r"try again later",
    r"can't connect", r"connection refused",
    r"region is unavailable"])


def table_for(k) -> str:
    return f"txn{int(k) % TABLE_COUNT}"


class TidbTxnClient(jclient.Client):
    """Generic micro-op txn client for append AND long-fork mops:
    one BEGIN .. COMMIT batch, tagged SELECTs carrying reads.
    Append values join with ',' like stolon's CONCAT upsert; long-fork
    writes set the lf key."""

    def __init__(self, sql_factory=TidbSql):
        self.sql_factory = sql_factory
        self.sql = None

    def open(self, test, node):
        c = TidbTxnClient(self.sql_factory)
        c.sql = self.sql_factory(test, node)
        return c

    def close(self, test):
        if self.sql is not None:
            self.sql.close()

    def _mop_sql(self, i, f, k, v) -> str:
        if f == "r":
            t = table_for(k)
            return (f"SELECT CONCAT('m{i}=', COALESCE("
                    f"(SELECT val FROM {t} WHERE id = {int(k)}), "
                    f"'~'))")
        if f == "append":
            t = table_for(k)
            return (f"INSERT INTO {t} (id, val) VALUES "
                    f"({int(k)}, '{int(v)}') ON DUPLICATE KEY "
                    f"UPDATE val = CONCAT(val, ',', '{int(v)}')")
        if f == "w":  # long-fork single-key write
            return (f"INSERT INTO lf (k, val) VALUES "
                    f"({int(k)}, {int(v)}) ON DUPLICATE KEY "
                    f"UPDATE val = {int(v)}")
        if f == "r-lf":
            return (f"SELECT CONCAT('m{i}=', COALESCE("
                    f"(SELECT val FROM lf WHERE k = {int(k)}), '~'))")
        raise ValueError(f"unknown mop {f!r}")

    def invoke(self, test, op):
        mops = op.value
        lf = table_is_lf(test)
        stmts = []
        for i, (f, k, v) in enumerate(mops):
            f2 = "r-lf" if lf and f == "r" else f
            stmts.append(self._mop_sql(i, f2, k, v))
        sql = "BEGIN; " + "; ".join(stmts) + "; COMMIT;"
        try:
            out = self.sql.run(sql)
        except RemoteError as e:
            return _classify(op, e)
        reads = {}
        for line in out.splitlines():
            m = re.match(r"m(\d+)=(.*)$", line.strip())
            if m:
                raw = m.group(2)
                reads[int(m.group(1))] = raw
        done = []
        for i, (f, k, v) in enumerate(mops):
            if f == "r":
                raw = reads.get(i)
                if raw is None or raw == "~":
                    done.append(["r", k, None])
                elif lf:
                    done.append(["r", k, int(raw)])
                else:
                    done.append(
                        ["r", k,
                         [int(x) for x in raw.split(",") if x]])
            else:
                done.append([f, k, v])
        return op.copy(type="ok", value=done)


def table_is_lf(test) -> bool:
    """The long-fork workload routes reads at the lf table via the
    test map's 'lf-table' flag."""
    return bool((test or {}).get("lf-table"))


class TidbBankClient(jclient.Client):
    """Bank transfers with the galera-style SQL-variable guard (bank
    conservation under tidb's optimistic txns; tidb/bank.clj)."""

    def __init__(self, sql_factory=TidbSql):
        self.sql_factory = sql_factory
        self.sql = None

    def open(self, test, node):
        c = TidbBankClient(self.sql_factory)
        c.sql = self.sql_factory(test, node)
        return c

    def close(self, test):
        if self.sql is not None:
            self.sql.close()

    def invoke(self, test, op):
        try:
            if op.f == "read":
                out = self.sql.run(
                    "SELECT CONCAT('b=', COALESCE(GROUP_CONCAT("
                    "CONCAT(id, ':', balance) ORDER BY id "
                    "SEPARATOR ','), '')) FROM accounts;")
                m = re.search(r"b=(.*)$", out, re.M)
                if not m:
                    raise ValueError(f"unparseable read: {out!r}")
                balances = {}
                for part in m.group(1).split(","):
                    if part:
                        i, b = part.split(":")
                        balances[int(i)] = int(b)
                return op.copy(type="ok", value=balances)
            if op.f == "transfer":
                v = op.value
                f, t, a = (int(v["from"]), int(v["to"]),
                           int(v["amount"]))
                out = self.sql.run(
                    "BEGIN; "
                    f"SELECT balance INTO @b1 FROM accounts "
                    f"WHERE id = {f} FOR UPDATE; "
                    f"UPDATE accounts SET balance = balance - {a} "
                    f"WHERE id = {f} AND @b1 >= {a}; "
                    f"UPDATE accounts SET balance = balance + {a} "
                    f"WHERE id = {t} AND @b1 >= {a}; "
                    f"SELECT CONCAT('applied=', "
                    f"IF(@b1 >= {a}, 1, 0)); "
                    "COMMIT;")
                m = re.search(r"applied=(\d)", out)
                if not m:
                    raise ValueError(f"unparseable transfer: {out!r}")
                if m.group(1) == "1":
                    return op.copy(type="ok")
                return op.copy(type="fail", error="insufficient funds")
            raise ValueError(f"unknown f {op.f!r}")
        except RemoteError as e:
            return _classify(op, e)


# ---------------------------------------------------------------------------
# Workloads / test (tidb/core.clj:32-60 shape)
# ---------------------------------------------------------------------------

def append_workload(opts: dict) -> dict:
    w = workloads.txn_append.workload(
        {"ops": opts.get("ops", 2000),
         "key-count": opts.get("keys", 6),
         "seed": opts.get("seed")})
    w["client"] = TidbTxnClient()
    return w


def bank_workload(opts: dict) -> dict:
    from ..workloads import bank

    total = 8 * 10
    return {
        "client": TidbBankClient(),
        "generator": bank.generator(accounts=list(range(8)),
                                    seed=opts.get("seed")),
        "checker": chk.checker(
            lambda test, hist, o: bank.check_fast(hist, total)),
    }


def long_fork_workload(opts: dict) -> dict:
    w = workloads.long_fork.workload({"ops": opts.get("ops", 600)})
    w["client"] = TidbTxnClient()
    w["lf-table"] = True
    return w


class TidbRegisterClient(jclient.Client):
    """Per-key read/write/cas register rows (tidb/register.clj: a
    single-row compare-and-set over the registers table)."""

    def __init__(self, sql_factory=TidbSql):
        self.sql_factory = sql_factory
        self.sql = None

    def open(self, test, node):
        c = TidbRegisterClient(self.sql_factory)
        c.sql = self.sql_factory(test, node)
        return c

    def close(self, test):
        if self.sql is not None:
            self.sql.close()

    def invoke(self, test, op):
        k, v = op.value
        try:
            if op.f == "read":
                out = self.sql.run(
                    "SELECT CONCAT('v=', COALESCE((SELECT val FROM "
                    f"registers WHERE id = {int(k)}), '~'));")
                m = re.search(r"v=(.*)$", out, re.M)
                raw = m.group(1) if m else "~"
                return op.copy(type="ok", value=(
                    k, None if raw == "~" else int(raw)))
            if op.f == "write":
                self.sql.run(
                    f"INSERT INTO registers (id, val) VALUES "
                    f"({int(k)}, {int(v)}) ON DUPLICATE KEY UPDATE "
                    f"val = {int(v)};")
                return op.copy(type="ok")
            old_v, new_v = v
            out = self.sql.run(
                f"UPDATE registers SET val = {int(new_v)} WHERE "
                f"id = {int(k)} AND val = {int(old_v)}; "
                "SELECT CONCAT('n=', ROW_COUNT());")
            m = re.search(r"n=(-?\d+)", out)
            if m and int(m.group(1)) > 0:
                return op.copy(type="ok")
            return op.copy(type="fail", error="cas mismatch")
        except RemoteError as e:
            return _classify(op, e)


class TidbSetClient(jclient.Client):
    """Adds via plain inserts (tidb/set.clj workload) or via CAS
    append on one text blob row (set.clj cas-workload), reads all."""

    def __init__(self, sql_factory=TidbSql, cas: bool = False):
        self.sql_factory = sql_factory
        self.cas = cas
        self.sql = None

    def open(self, test, node):
        c = TidbSetClient(self.sql_factory, self.cas)
        c.sql = self.sql_factory(test, node)
        return c

    def close(self, test):
        if self.sql is not None:
            self.sql.close()

    def invoke(self, test, op):
        try:
            if op.f == "add":
                if self.cas:
                    self.sql.run(
                        "BEGIN; SELECT val INTO @v FROM setcas WHERE "
                        "id = 0 FOR UPDATE; UPDATE setcas SET val = "
                        f"CONCAT(@v, ',', '{int(op.value)}') WHERE "
                        "id = 0; COMMIT;")
                else:
                    self.sql.run("INSERT INTO sets (val) VALUES "
                                 f"({int(op.value)});")
                return op.copy(type="ok")
            if self.cas:
                out = self.sql.run("SELECT CONCAT('s=', val) FROM "
                                   "setcas WHERE id = 0;")
                m = re.search(r"s=(.*)$", out, re.M)
                raw = m.group(1) if m else ""
                vals = sorted(int(x) for x in raw.split(",") if x)
            else:
                out = self.sql.run("SELECT val FROM sets;")
                vals = sorted(int(x) for x in out.split()
                              if x.strip().lstrip('-').isdigit())
            return op.copy(type="ok", value=vals)
        except RemoteError as e:
            return _classify(op, e)


class TidbSequentialClient(jclient.Client):
    """sequential.clj contract: write k inserts each subkey in its own
    txn, read walks them reversed (see workloads.sequential)."""

    def __init__(self, sql_factory=TidbSql, key_count: int = 5):
        self.sql_factory = sql_factory
        self.key_count = key_count
        self.sql = None

    def open(self, test, node):
        c = TidbSequentialClient(self.sql_factory, self.key_count)
        c.sql = self.sql_factory(test, node)
        return c

    def close(self, test):
        if self.sql is not None:
            self.sql.close()

    def invoke(self, test, op):
        from ..workloads import sequential as seq_wl

        try:
            if op.f == "write":
                for sk in seq_wl.subkeys(self.key_count, op.value):
                    self.sql.run("INSERT IGNORE INTO seq (sk) VALUES "
                                 f"('{sk}');")
                return op.copy(type="ok")
            obs = []
            for sk in reversed(seq_wl.subkeys(self.key_count,
                                              op.value)):
                out = self.sql.run(
                    f"SELECT CONCAT('x=', COUNT(*)) FROM seq "
                    f"WHERE sk = '{sk}';")
                m = re.search(r"x=(\d+)", out)
                obs.append(sk if m and int(m.group(1)) else None)
            return op.copy(type="ok", value=(op.value, obs))
        except RemoteError as e:
            return _classify(op, e)


class TidbMonotonicClient(jclient.Client):
    """monotonic.clj contract: add reads MAX(val), inserts max+1 with
    the txn's commit timestamp (@@tidb_current_ts); final read returns
    rows ordered by sts (see workloads.monotonic)."""

    def __init__(self, sql_factory=TidbSql):
        self.sql_factory = sql_factory
        self.sql = None
        self.node = None

    def open(self, test, node):
        c = TidbMonotonicClient(self.sql_factory)
        c.sql = self.sql_factory(test, node)
        c.node = node
        return c

    def close(self, test):
        if self.sql is not None:
            self.sql.close()

    def invoke(self, test, op):
        try:
            if op.f == "add":
                out = self.sql.run(
                    "BEGIN; SELECT COALESCE(MAX(val), 0) + 1, "
                    "@@tidb_current_ts INTO @v, @ts FROM mono; "
                    "INSERT INTO mono (val, sts, node, process, tb) "
                    f"VALUES (@v, @ts, '{self.node}', "
                    f"{int(op.process)}, 0); "
                    "SELECT CONCAT('row=', @v, ':', @ts); COMMIT;")
                m = re.search(r"row=(\d+):(\d+)", out)
                if not m:
                    raise ValueError(f"unparseable add: {out!r}")
                return op.copy(type="ok", value={
                    "val": int(m.group(1)), "sts": int(m.group(2)),
                    "node": self.node, "process": op.process,
                    "tb": 0})
            out = self.sql.run(
                "SELECT CONCAT('r=', val, ':', sts, ':', node, ':', "
                "process, ':', tb) FROM mono ORDER BY sts, val;")
            rows = []
            for mm in re.finditer(
                    r"r=(\d+):(\d+):([\w.-]+):(\d+):(\d+)", out):
                rows.append({"val": int(mm.group(1)),
                             "sts": int(mm.group(2)),
                             "node": mm.group(3),
                             "process": int(mm.group(4)),
                             "tb": int(mm.group(5))})
            return op.copy(type="ok", value=rows)
        except RemoteError as e:
            return _classify(op, e)


class TidbTableClient(jclient.Client):
    """table.clj client: create-table / insert; an insert hitting
    'doesn't exist' for an acked table is the bug."""

    def __init__(self, sql_factory=TidbSql):
        self.sql_factory = sql_factory
        self.sql = None

    def open(self, test, node):
        c = TidbTableClient(self.sql_factory)
        c.sql = self.sql_factory(test, node)
        return c

    def close(self, test):
        if self.sql is not None:
            self.sql.close()

    def invoke(self, test, op):
        try:
            if op.f == "create-table":
                self.sql.run(
                    f"CREATE TABLE IF NOT EXISTS t{int(op.value)} "
                    "(id INT NOT NULL PRIMARY KEY, val INT);")
                return op.copy(type="ok")
            table, k = op.value
            try:
                self.sql.run(f"INSERT INTO t{int(table)} (id) "
                             f"VALUES ({int(k)});")
                return op.copy(type="ok")
            except RemoteError as e:
                msg = str(e)
                if re.search(r"doesn't exist", msg):
                    return op.copy(type="fail", error="doesn't-exist")
                if re.search(r"[Dd]uplicate", msg):
                    return op.copy(type="fail", error="duplicate-key")
                raise
        except RemoteError as e:
            return _classify(op, e)


class _TableGen(gen.Generator):
    """table.clj generator: mostly insert into the last table whose
    create COMPLETED ok; otherwise create the next table id. State
    feeds from completion events via update(), never from probes."""

    __slots__ = ("next_id", "created", "rng_seed", "n")

    def __init__(self, next_id: int = 1, created: int | None = None,
                 rng_seed=None, n: int = 0):
        self.next_id = next_id
        self.created = created
        self.rng_seed = rng_seed
        self.n = n

    def _rng(self):
        return jutil.seeded_rng(
            self.rng_seed if self.rng_seed is not None
            else "tidb-table", self.n)

    def op(self, test, ctx):
        insert = (self.created is not None
                  and self._rng().random() < 0.8)
        if insert:
            m = gen.fill_in_op(
                {"f": "insert",
                 "value": [self.created, self.n]}, ctx)
            if m is gen.PENDING:
                return gen.PENDING, self
            return m, _TableGen(self.next_id, self.created,
                                self.rng_seed, self.n + 1)
        m = gen.fill_in_op(
            {"f": "create-table", "value": self.next_id}, ctx)
        if m is gen.PENDING:
            return gen.PENDING, self
        return m, _TableGen(self.next_id + 1, self.created,
                            self.rng_seed, self.n + 1)

    def update(self, test, ctx, event):
        if (event.type == "ok" and event.f == "create-table"
                and (self.created is None
                     or event.value > self.created)):
            return _TableGen(self.next_id, event.value,
                             self.rng_seed, self.n)
        return self


def check_tables(hist) -> dict:
    """table.clj checker: no insert may fail with doesn't-exist."""
    bad = [op for op in hist
           if op.type == "fail" and op.get("error") == "doesn't-exist"]
    return {"valid?": not bad,
            "errors": [o.to_dict() for o in bad[:8]]}


def register_workload(opts: dict) -> dict:
    w = workloads.register.workload(
        {"keys": opts.get("keys", list(range(8))),
         "ops_per_key": opts.get("ops_per_key", 60),
         "group_size": opts.get("group_size", 5),
         "seed": opts.get("seed")})
    w["client"] = TidbRegisterClient()
    return w


def set_workload(opts: dict) -> dict:
    w = workloads.sets.workload({"ops": opts.get("ops", 400)})
    w["client"] = TidbSetClient()
    return w


def set_cas_workload(opts: dict) -> dict:
    w = workloads.sets.workload({"ops": opts.get("ops", 400)})
    w["client"] = TidbSetClient(cas=True)
    return w


def sequential_workload(opts: dict) -> dict:
    from ..workloads import sequential as seq_wl

    w = seq_wl.workload(dict(opts))
    w["client"] = TidbSequentialClient(
        key_count=opts.get("key-count", 5))
    return w


def monotonic_workload(opts: dict) -> dict:
    from ..workloads import monotonic as mono_wl

    w = mono_wl.workload(dict(opts))
    w["client"] = TidbMonotonicClient()
    return w


def txn_cycle_workload(opts: dict) -> dict:
    """monotonic.clj txn-workload: elle rw-register cycle search over
    generic read/write txns (the lf table carries single-int cells)."""
    w = workloads.txn_wr.workload(
        {"ops": opts.get("ops", 600), "seed": opts.get("seed")})
    w["client"] = TidbTxnClient()
    w["lf-table"] = True
    return w


def table_workload(opts: dict) -> dict:
    return {
        "generator": gen.limit(opts.get("ops", 200), _TableGen(
            rng_seed=opts.get("seed"))),
        "checker": chk.checker(
            lambda test, hist, o: check_tables(hist)),
        "client": TidbTableClient(),
    }


class TidbMultiBankClient(jclient.Client):
    """bank.clj multitable-workload: one bankN table per account;
    reads union all tables in ONE statement (one snapshot), transfers
    span two tables under the SQL-variable guard."""

    def __init__(self, sql_factory=TidbSql):
        self.sql_factory = sql_factory
        self.sql = None

    def open(self, test, node):
        c = TidbMultiBankClient(self.sql_factory)
        c.sql = self.sql_factory(test, node)
        return c

    def close(self, test):
        if self.sql is not None:
            self.sql.close()

    def invoke(self, test, op):
        try:
            if op.f == "read":
                union = " UNION ALL ".join(
                    f"SELECT {i} AS id, balance FROM bank{i} "
                    "WHERE id = 0" for i in range(8))
                out = self.sql.run(
                    "SELECT CONCAT('b=', GROUP_CONCAT(CONCAT(id, "
                    f"':', balance) ORDER BY id SEPARATOR ',')) "
                    f"FROM ({union}) t;")
                m = re.search(r"b=(.*)$", out, re.M)
                if not m:
                    raise ValueError(f"unparseable read: {out!r}")
                balances = {}
                for part in m.group(1).split(","):
                    if part:
                        i, b = part.split(":")
                        balances[int(i)] = int(b)
                return op.copy(type="ok", value=balances)
            v = op.value
            f, t, a = (int(v["from"]), int(v["to"]), int(v["amount"]))
            out = self.sql.run(
                "BEGIN; "
                f"SELECT balance INTO @b1 FROM bank{f} "
                "WHERE id = 0 FOR UPDATE; "
                f"UPDATE bank{f} SET balance = balance - {a} "
                f"WHERE id = 0 AND @b1 >= {a}; "
                f"UPDATE bank{t} SET balance = balance + {a} "
                f"WHERE id = 0 AND @b1 >= {a}; "
                f"SELECT CONCAT('applied=', IF(@b1 >= {a}, 1, 0)); "
                "COMMIT;")
            m = re.search(r"applied=(\d)", out)
            if not m:
                raise ValueError(f"unparseable transfer: {out!r}")
            if m.group(1) == "1":
                return op.copy(type="ok")
            return op.copy(type="fail", error="insufficient funds")
        except RemoteError as e:
            return _classify(op, e)


def bank_multitable_workload(opts: dict) -> dict:
    w = bank_workload(opts)
    w["client"] = TidbMultiBankClient()
    return w


WORKLOADS = {"append": append_workload,
             "bank": bank_workload,
             "bank-multitable": bank_multitable_workload,
             "long-fork": long_fork_workload,
             "monotonic": monotonic_workload,
             "txn-cycle": txn_cycle_workload,
             "register": register_workload,
             "set": set_workload,
             "set-cas": set_cas_workload,
             "sequential": sequential_workload,
             "table": table_workload}


def all_tests(opts: dict):
    """Workload x fault sweep (tidb/core.clj:47-60)."""
    names = ([opts["workload"]] if opts.get("workload")
             else sorted(WORKLOADS))
    fault_options = ([opts["faults"]] if opts.get("faults") is not None
                     else ([], ["partition"], ["kill"]))
    for _ in range(opts.get("test_count") or 1):
        for name in names:
            for faults in fault_options:
                yield tidb_test({**opts, "workload": name,
                                 "faults": list(faults)})


def nemesis_for(opts: dict, db) -> dict:
    """--nemesis faults compose through the package system so 'kill'
    really drives DB.kill/start (etcd's nemesis_for shape); empty =
    the classic partitioner schedule."""
    from ..nemesis import combined

    faults = set(opts.get("faults") or ())
    if not faults:
        return {"nemesis": jnemesis.partition_random_halves(),
                "generator": jnemesis.start_stop_cycle(10.0),
                "final_generator": None}
    pkgs = combined.nemesis_packages(
        {**opts, "db": db, "faults": faults,
         "interval": opts.get("nemesis_interval", 10)})
    return combined.compose_packages(pkgs)


def tidb_test(opts: dict) -> dict:
    name = opts.get("workload") or "append"
    w = WORKLOADS[name](opts)
    db = TidbDB(opts.get("version", VERSION))
    pkg = nemesis_for(opts, db)
    main = gen.time_limit(
        opts.get("time_limit", 30),
        gen.clients(
            gen.stagger(1.0 / opts.get("rate", 20), w["generator"]),
            pkg["generator"]))
    final = pkg.get("final_generator")
    generator = gen.phases(main, gen.nemesis(final)) if final \
        else main
    test = testing.noop_test()
    test.update(
        name=f"tidb-{name}",
        os=debian.os,
        db=db,
        ssh=opts["ssh"],
        nodes=opts["nodes"],
        concurrency=opts["concurrency"],
        client=w["client"],
        nemesis=pkg["nemesis"],
        checker=chk.compose({"workload": w["checker"],
                             "stats": chk.stats(),
                             "perf": chk.perf(),
                             "timeline": chk.timeline()}),
        generator=generator)
    if w.get("lf-table"):
        test["lf-table"] = True
    return test


def _opts(p):
    p.add_argument("--workload", default=None,
                   help="Workload (default append). "
                        + cli.one_of(WORKLOADS))
    p.add_argument("--version", default=VERSION,
                   help="tidb community-server version.")
    p.add_argument("--rate", type=float, default=20)
    p.add_argument("--nemesis", dest="faults", default=None,
                   help="Comma-separated fault list for test-all.")
    return p


def _opt_fn(opts: dict) -> dict:
    if opts.get("faults"):
        opts["faults"] = [f.strip()
                          for f in opts["faults"].split(",")
                          if f.strip()]
    return opts


def main(argv=None) -> None:
    commands = {}
    commands.update(cli.single_test_cmd(tidb_test, parser_fn=_opts,
                                        opt_fn=_opt_fn))
    commands.update(cli.test_all_cmd(all_tests, parser_fn=_opts,
                                     opt_fn=_opt_fn))
    commands.update(cli.serve_cmd())
    cli.run_cli(commands, argv)


if __name__ == "__main__":
    main()
