"""MongoDB test suite: document compare-and-set against a replica set,
with majority write concern and linearizable reads.

Capability reference: mongodb-smartos/src/jepsen/mongodb_smartos/ —
core.clj (tarball install + mongod --replSet, replica-set-initiate
with the need-all-members-up retry at 128-146, await-primary 228-232,
join! driven from the jepsen primary 261-281) and document_cas.clj
(document register: read / upsert write / query-guarded cas update
checking the modified count, 40-83; reads idempotent -> :fail in
with-errors). The reference links the monger/Java driver into the
JVM; here every op is one `mongosh --quiet --eval JSON.stringify(
db.runCommand(...))` on the client's node against the replica-set
connection string — the same driver-free control-plane transport as
the zookeeper/postgres/rabbitmq suites.
"""

from __future__ import annotations

import json
import logging
import random

from .. import checker as chk
from .. import cli, client as jclient, control, core, db as jdb
from .. import generator as gen
from .. import independent
from .. import nemesis as jnemesis
from .. import testing
from ..checker import models
from ..control import util as cu
from ..control.core import RemoteError
from ..core import primary
from ..os_setup import debian

logger = logging.getLogger(__name__)

VERSION = "7.0.14"
DIR = "/opt/mongodb"
MONGOD = f"{DIR}/bin/mongod"
MONGOSH = f"{DIR}/mongosh/bin/mongosh"
MONGOSH_VERSION = "2.3.1"
DATA_DIR = "/var/lib/mongodb"
LOGFILE = "/var/log/mongodb/mongod.log"
PIDFILE = "/var/run/mongod.pid"
PORT = 27017
REPL_SET = "rs0"
DB_NAME = "jepsen"
COLL = "jepsen"


def conn_string(test) -> str:
    hosts = ",".join(f"{n}:{PORT}" for n in test["nodes"])
    return f"mongodb://{hosts}/{DB_NAME}?replicaSet={REPL_SET}"


# ---------------------------------------------------------------------------
# mongosh transport
# ---------------------------------------------------------------------------

class MongoShell:
    """One runCommand per mongosh invocation on the client's node.
    `direct=True` targets the local mongod (for replica-set admin
    before a primary exists); otherwise the replica-set connection
    string routes to the current primary. Split out so tests can stub
    `run_command`."""

    def __init__(self, test, node, direct: bool = False,
                 timeout: float = 10.0):
        self.test = test
        self.node = node
        self.url = (f"mongodb://{node}:{PORT}/{DB_NAME}" if direct
                    else conn_string(test))
        self.timeout = timeout
        self.sess = control.session(test, node)

    def run_command(self, command: dict, admin: bool = False) -> dict:
        target = "db.getSiblingDB('admin')" if admin else "db"
        script = (f"JSON.stringify({target}.runCommand("
                  f"{json.dumps(command)}))")
        with control.with_session(self.test, self.node, self.sess):
            out = control.exec_(MONGOSH, "--quiet", self.url,
                                "--eval", script,
                                timeout=self.timeout)
        # mongosh may print connection banners despite --quiet; the
        # payload is the last JSON line
        for line in reversed(out.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
        raise ValueError(f"no JSON in mongosh output: {out!r}")

    def close(self):
        control.disconnect(self.sess)



class MongoDB(jdb.DB):
    """Tarball-installed mongod in one replica set; the test primary
    initiates and awaits election (core.clj join!, 261-281)."""

    supports_kill = True

    def __init__(self, version: str = VERSION,
                 shell_factory=MongoShell):
        self.version = version
        # injectable for clusterless tests; None skips the initiate/
        # await phase that needs a live server
        self.shell_factory = shell_factory

    def setup(self, test, node):
        logger.info("%s installing mongodb %s", node, self.version)
        with control.su():
            url = (f"https://fastdl.mongodb.org/linux/mongodb-linux-"
                   f"x86_64-debian11-{self.version}.tgz")
            cu.install_archive(url, DIR)
            # the server tarball ships no shell; fetch mongosh beside
            # it for the suite's transport
            cu.install_archive(
                f"https://downloads.mongodb.com/compass/"
                f"mongosh-{MONGOSH_VERSION}-linux-x64.tgz",
                f"{DIR}/mongosh")
            control.exec_("mkdir", "-p", DATA_DIR,
                          "/var/log/mongodb")
            cu.start_daemon(
                {"logfile": LOGFILE, "pidfile": PIDFILE, "chdir": DIR},
                MONGOD,
                "--replSet", REPL_SET,
                "--bind_ip_all",
                "--port", str(PORT),
                "--dbpath", DATA_DIR,
                "--logpath", LOGFILE)
        cu.await_tcp_port(PORT, timeout_secs=120)
        core.synchronize(test)  # all mongods up before initiate
        if node == primary(test) and self.shell_factory is not None:
            shell = self.shell_factory(test, node, direct=True)
            try:
                self._initiate(test, shell)
                self._await_primary(shell)
            finally:
                shell.close()
        core.synchronize(test)

    def _initiate(self, test, shell):
        """replSetInitiate, retrying while members are still coming up
        (core.clj replica-set-initiate!, 128-146)."""
        from .. import util

        members = [{"_id": i, "host": f"{n}:{PORT}"}
                   for i, n in enumerate(test["nodes"])]
        cfg = {"_id": REPL_SET, "members": members}

        def attempt():
            res = shell.run_command(
                {"replSetInitiate": cfg}, admin=True)
            if res.get("ok") != 1 and "already initialized" not in str(
                    res.get("errmsg", "")):
                raise RuntimeError(f"initiate failed: {res}")

        util.await_fn(attempt, timeout_secs=120,
                      log_message="waiting for replSetInitiate")

    def _await_primary(self, shell):
        """Block until an elected primary is visible
        (core.clj await-primary, 228-232)."""
        from .. import util

        def check():
            res = shell.run_command({"hello": 1}, admin=True)
            if not res.get("isWritablePrimary") and not res.get(
                    "primary"):
                raise RuntimeError("no primary yet")

        util.await_fn(check, timeout_secs=120,
                      log_message="waiting for mongo election")

    def teardown(self, test, node):
        logger.info("%s wiping mongodb", node)
        with control.su():
            cu.stop_daemon(MONGOD, PIDFILE)
            control.exec_("rm", "-rf", DATA_DIR, LOGFILE)

    def kill(self, test, node):
        with control.su():
            cu.grepkill("mongod")
        return "killed"

    def start(self, test, node):
        with control.su():
            control.exec_("mkdir", "-p", DATA_DIR, "/var/log/mongodb")
            cu.start_daemon(
                {"logfile": LOGFILE, "pidfile": PIDFILE, "chdir": DIR},
                MONGOD, "--replSet", REPL_SET, "--bind_ip_all",
                "--port", str(PORT), "--dbpath", DATA_DIR,
                "--logpath", LOGFILE)
        return "started"

    def log_files(self, test, node):
        return [LOGFILE]


_DEFINITE_MARKERS = ("connection refused", "notwritableprimary",
                     "not master", "no primary", "notprimary")


def _classify(op, e: Exception):
    msg = f"{getattr(e, 'err', '')} {getattr(e, 'out', '')} {e}".lower()
    if op.f == "read" or any(m in msg for m in _DEFINITE_MARKERS):
        return op.copy(type="fail", error=msg.strip()[:200])
    return op.copy(type="info", error=msg.strip()[:200])


def _update_reply_problem(res: dict):
    """Mongo can answer ok:1 while the update itself failed: per-document
    errors ride in writeErrors (definite — the write did not apply, e.g.
    E11000 from a concurrent upsert race) and unmet durability rides in
    writeConcernError (indefinite — applied locally, replication unknown).
    Returns ("fail"|"info", msg) or (None, None)."""
    we = res.get("writeErrors")
    if we:
        return "fail", str(we)[:200]
    wce = res.get("writeConcernError")
    if wce:
        return "info", str(wce)[:200]
    return None, None


class MongoCasClient(jclient.Client):
    """Per-key document register (document_cas.clj Client, 40-83):
    write is an upsert, cas a query-guarded update judged by the
    modified count, read a linearizable-read-concern find."""

    def __init__(self, shell_factory=MongoShell,
                 write_concern: str = "majority",
                 read_concern: str = "linearizable"):
        self.shell_factory = shell_factory
        self.write_concern = write_concern
        self.read_concern = read_concern
        self.shell = None

    def open(self, test, node):
        c = MongoCasClient(self.shell_factory, self.write_concern,
                           self.read_concern)
        c.shell = self.shell_factory(test, node)
        return c

    def close(self, test):
        if self.shell is not None:
            self.shell.close()

    def _wc(self) -> dict:
        w = self.write_concern
        return {"w": int(w)} if str(w).isdigit() else {"w": w}

    def invoke(self, test, op):
        if op.f not in ("read", "write", "cas"):
            raise ValueError(f"unknown f {op.f!r}")
        k, v = independent.key_(op.value), independent.value_(op.value)
        try:
            if op.f == "read":
                res = self.shell.run_command({
                    "find": COLL, "filter": {"_id": k}, "limit": 1,
                    "readConcern": {"level": self.read_concern}})
                if res.get("ok") != 1:
                    return op.copy(type="fail",
                                   error=str(res.get("errmsg")))
                docs = res.get("cursor", {}).get("firstBatch", [])
                val = docs[0].get("value") if docs else None
                return op.copy(type="ok",
                               value=independent.ktuple(k, val))
            if op.f == "write":
                res = self.shell.run_command({
                    "update": COLL,
                    "updates": [{"q": {"_id": k},
                                 "u": {"_id": k, "value": v},
                                 "upsert": True}],
                    "writeConcern": self._wc()})
                if res.get("ok") != 1:
                    raise RuntimeError(str(res.get("errmsg")))
                kind, msg = _update_reply_problem(res)
                if kind is not None:
                    return op.copy(type=kind, error=msg)
                if res.get("n", 0) < 1:
                    return op.copy(type="fail", error="upsert matched 0")
                return op.copy(type="ok")
            if op.f == "cas":
                old, new = v
                res = self.shell.run_command({
                    "update": COLL,
                    "updates": [{"q": {"_id": k, "value": old},
                                 "u": {"$set": {"value": new}}}],
                    "writeConcern": self._wc()})
                if res.get("ok") != 1:
                    raise RuntimeError(str(res.get("errmsg")))
                kind, msg = _update_reply_problem(res)
                if kind is not None:
                    return op.copy(type=kind, error=msg)
                n = res.get("nModified", res.get("n", 0))
                if n == 0:
                    return op.copy(type="fail")
                if n == 1:
                    return op.copy(type="ok")
                raise RuntimeError(f"cas touched {n} documents")
        except (RemoteError, RuntimeError) as e:
            # parse corruption (ValueError) deliberately propagates:
            # mangled output is evidence, not a clean network :fail
            return _classify(op, e)


# ---------------------------------------------------------------------------
# Workloads / test
# ---------------------------------------------------------------------------

def cas_workload(opts: dict) -> dict:
    """Linearizable per-key document registers; mix weights cas double
    like the reference's std mix [r w cas cas]."""
    rng = random.Random(opts.get("seed"))

    def r(_rng):
        return {"f": "read", "value": None}

    def w(rng):
        return {"f": "write", "value": rng.randrange(5)}

    def cas(rng):
        return {"f": "cas",
                "value": [rng.randrange(5), rng.randrange(5)]}

    keys = list(range(opts.get("keys", 3)))
    return {
        "client": MongoCasClient(
            write_concern=opts.get("write_concern", "majority"),
            read_concern=opts.get("read_concern", "linearizable")),
        "generator": independent.concurrent_generator(
            opts["concurrency"], keys,
            lambda k: gen.limit(
                opts.get("ops_per_key", 200),
                gen.mix([lambda: r(rng), lambda: w(rng),
                         lambda: cas(rng), lambda: cas(rng)]))),
        "checker": independent.checker(chk.linearizable(
            {"model": models.cas_register()})),
    }


WORKLOADS = {"cas": cas_workload}


def mongodb_test(opts: dict) -> dict:
    name = opts.get("workload", "cas")
    w = WORKLOADS[name](opts)
    test = testing.noop_test()
    test.update(
        name=f"mongodb-{name}",
        os=debian.os,
        db=MongoDB(opts.get("version", VERSION)),
        ssh=opts["ssh"],
        nodes=opts["nodes"],
        concurrency=opts["concurrency"],
        client=w["client"],
        nemesis=jnemesis.partition_random_halves(),
        checker=chk.compose({"workload": w["checker"],
                             "stats": chk.stats(),
                             "perf": chk.perf(),
                             "timeline": chk.timeline()}),
        generator=gen.time_limit(
            opts.get("time_limit", 30),
            gen.clients(
                gen.stagger(1.0 / opts.get("rate", 20),
                            w["generator"]),
                jnemesis.start_stop_cycle(10.0))))
    return test


def _opts(p):
    p.add_argument("--workload", default="cas",
                   help="Workload. " + cli.one_of(WORKLOADS))
    p.add_argument("--version", default=VERSION,
                   help="mongodb version tarball to install.")
    p.add_argument("--rate", type=float, default=20)
    p.add_argument("--write-concern", dest="write_concern",
                   default="majority",
                   help='w value: "majority" or an int ack count.')
    p.add_argument("--read-concern", dest="read_concern",
                   default="linearizable",
                   choices=["local", "majority", "linearizable"])
    return p


def main(argv=None) -> None:
    commands = {}
    commands.update(cli.single_test_cmd(mongodb_test, parser_fn=_opts))
    commands.update(cli.serve_cmd())
    cli.run_cli(commands, argv)


if __name__ == "__main__":
    main()
