"""CrateDB test suite: a CAS register over the HTTP `_sql` endpoint.

Capability reference: aphyr/jepsen crate (crate/src/jepsen/crate.clj
and the "Crate 0.54.9 version divergence" analysis) — a tarball
install with unicast discovery, an Elasticsearch-backed SQL layer, and
a register workload using Crate's optimistic concurrency (`_version`)
that exposed dirty reads and lost updates under partition. The
reference drives the Java client; here every op is one `curl` POST to
the node's `_sql` endpoint over the control plane (the CLI-transport
pattern of the raftis/disque suites), with conditional UPDATEs
standing in for the version-guarded writes.

Crate reads are eventually visible without an explicit `REFRESH
TABLE`, so the client refreshes before every read — the reference does
the same; without it, stale reads are a client artifact, not a
database anomaly.
"""

from __future__ import annotations

import json
import logging
import random

from .. import checker as chk
from .. import cli, client as jclient, control, db as jdb
from .. import generator as gen
from .. import nemesis as jnemesis
from .. import testing
from ..checker import models
from ..control import util as cu
from ..control.core import RemoteError
from ..os_setup import debian

logger = logging.getLogger(__name__)

VERSION = "5.7.2"
DIR = "/opt/crate"
LOGFILE = f"{DIR}/crate.log"
PIDFILE = f"{DIR}/crate.pid"
HTTP_PORT = 4200
TRANSPORT_PORT = 4300
TABLE = "jepsen_r"


class CrateDB(jdb.DB):
    """Tarball install + unicast-discovery cluster (crate.clj db)."""

    supports_kill = True

    def __init__(self, version: str = VERSION):
        self.version = version

    def _start(self, test, node):
        cu.start_daemon(
            {"logfile": LOGFILE, "pidfile": PIDFILE, "chdir": DIR},
            f"{DIR}/bin/crate",
            "-Cnetwork.host=0.0.0.0",
            f"-Cnode.name={node}",
            "-Ccluster.name=jepsen",
            f"-Chttp.port={HTTP_PORT}",
            f"-Ctransport.port={TRANSPORT_PORT}",
            "-Cdiscovery.seed_hosts="
            + ",".join(f"{n}:{TRANSPORT_PORT}"
                       for n in test["nodes"]),
            "-Ccluster.initial_master_nodes="
            + ",".join(str(n) for n in test["nodes"]))

    def setup(self, test, node):
        logger.info("%s installing crate %s", node, self.version)
        with control.su():
            debian.install(["openjdk-17-jre-headless", "curl"])
            url = (f"https://cdn.crate.io/downloads/releases/"
                   f"cratedb/x64_linux/crate-{self.version}.tar.gz")
            cu.install_archive(url, DIR)
            self._start(test, node)
        cu.await_tcp_port(HTTP_PORT, timeout_secs=120)
        # schema from the primary only, once the cluster formed
        if str(node) == str(test["nodes"][0]):
            CrateSql(test, node).stmt(
                f"CREATE TABLE IF NOT EXISTS {TABLE} "
                "(id INT PRIMARY KEY, val INT) "
                "CLUSTERED INTO 5 SHARDS "
                "WITH (number_of_replicas = "
                f"{len(test['nodes']) - 1})")

    def teardown(self, test, node):
        logger.info("%s tearing down crate", node)
        with control.su():
            cu.stop_daemon(f"{DIR}/bin/crate", PIDFILE)
            control.exec_("rm", "-rf", DIR)

    def kill(self, test, node):
        with control.su():
            cu.grepkill("crate")
        return "killed"

    def start(self, test, node):
        with control.su():
            self._start(test, node)
        return "started"

    def log_files(self, test, node):
        return [LOGFILE]


# ---------------------------------------------------------------------------
# the _sql-over-curl transport
# ---------------------------------------------------------------------------

class CrateSqlError(Exception):
    """Crate REJECTED the statement (an `error` JSON reply) — it
    definitely did not apply."""


class CrateSql:
    """One SQL statement = one curl POST to the node's `_sql`
    endpoint. Split out so tests can stub `stmt`. Non-retrying
    session: INSERT/UPDATE are not idempotent (the raftis RedisCli
    rationale)."""

    def __init__(self, test, node, timeout: float = 8.0):
        self.test = test
        self.node = node
        self.timeout = timeout
        self.sess = self._session(test, node)

    @staticmethod
    def _session(test, node):
        if test.get("remote") is not None or \
                (test.get("ssh") or {}).get("dummy"):
            return control.session(test, node)
        from ..control.scp import ScpRemote
        from ..control.ssh import SshRemote

        return ScpRemote(SshRemote()).connect(
            control.conn_spec(test, node))

    def stmt(self, sql: str, args: list | None = None) -> dict:
        body = json.dumps({"stmt": sql, "args": args or []})
        with control.with_session(self.test, self.node, self.sess):
            out = control.exec_(
                "curl", "-s", "--max-time",
                str(int(self.timeout)),
                "-H", "Content-Type: application/json",
                "-XPOST", f"http://{self.node}:{HTTP_PORT}/_sql",
                "-d", body, timeout=self.timeout + 2)
        try:
            reply = json.loads(out)
        except ValueError:
            raise RemoteError("non-JSON _sql reply", exit=0,
                              out=out[:200], err="", cmd="curl",
                              node=self.node)
        if isinstance(reply.get("error"), dict):
            raise CrateSqlError(
                str(reply["error"].get("message", reply["error"]))
                [:200])
        return reply

    def close(self):
        control.disconnect(self.sess)


_DEFINITE = ("connection refused", "could not connect",
             "couldn't connect", "no route", "empty reply")

# error classes Crate REJECTS before applying anything — only these
# make a write a definite :fail (the rethinkdb-suite rule: an opaque
# server error during a partition may have applied on the primary
# shard, so it must stay indeterminate :info, never a false definite)
_REJECTED = ("sqlparseexception", "columnunknown", "relationunknown",
             "relation unknown", "invalidcolumnname", "forbidden",
             "read-only", "unauthorized")


def _classify(op, e: Exception):
    msg = (str(e) if isinstance(e, CrateSqlError) else
           f"{getattr(e, 'err', '')} {getattr(e, 'out', '')} {e}"
           ).lower()
    if op.f == "read":
        return op.copy(type="fail", error=msg.strip()[:200])
    if isinstance(e, CrateSqlError):
        if any(m in msg for m in _REJECTED):
            return op.copy(type="fail", error=msg.strip()[:200])
        # opaque server-side error (internal timeout, shard failure):
        # the write may have applied — indeterminate
        return op.copy(type="info", error=msg.strip()[:200])
    if any(m in msg for m in _DEFINITE):
        return op.copy(type="fail", error=msg.strip()[:200])
    return op.copy(type="info", error=msg.strip()[:200])


class CrateRegisterClient(jclient.Client):
    """CAS register at row id=0 (crate.clj client): writes upsert,
    CAS is a conditional UPDATE whose rowcount proves whether it
    applied, reads REFRESH first (visibility, see module doc)."""

    def __init__(self, sql_factory=CrateSql):
        self.sql_factory = sql_factory
        self.sql = None

    def open(self, test, node):
        c = CrateRegisterClient(self.sql_factory)
        c.sql = self.sql_factory(test, node)
        return c

    def close(self, test):
        if self.sql is not None:
            self.sql.close()

    def invoke(self, test, op):
        try:
            if op.f == "read":
                self.sql.stmt(f"REFRESH TABLE {TABLE}")
                r = self.sql.stmt(
                    f"SELECT val FROM {TABLE} WHERE id = 0")
                rows = r.get("rows") or []
                return op.copy(type="ok",
                               value=rows[0][0] if rows else None)
            if op.f == "write":
                r = self.sql.stmt(
                    f"INSERT INTO {TABLE} (id, val) VALUES (0, ?) "
                    "ON CONFLICT (id) DO UPDATE SET val = ?",
                    [int(op.value), int(op.value)])
                if r.get("rowcount") != 1:
                    raise RemoteError("unexpected upsert rowcount",
                                      exit=0,
                                      out=str(r.get("rowcount")),
                                      err="", cmd="INSERT",
                                      node=None)
                return op.copy(type="ok")
            if op.f == "cas":
                frm, to = op.value
                # conditional write: rowcount 1 = applied, 0 = the
                # precondition failed (a definite :fail). REFRESH
                # first so the predicate sees the newest segment.
                self.sql.stmt(f"REFRESH TABLE {TABLE}")
                r = self.sql.stmt(
                    f"UPDATE {TABLE} SET val = ? "
                    "WHERE id = 0 AND val = ?",
                    [int(to), int(frm)])
                n = r.get("rowcount")
                if n not in (0, 1):
                    raise RemoteError("unexpected cas rowcount",
                                      exit=0, out=str(n), err="",
                                      cmd="UPDATE", node=None)
                return op.copy(type="ok" if n == 1 else "fail")
            raise ValueError(f"unknown f {op.f!r}")
        except (RemoteError, CrateSqlError) as e:
            return _classify(op, e)


# ---------------------------------------------------------------------------
# Workloads / test
# ---------------------------------------------------------------------------

def register_workload(opts: dict) -> dict:
    rng = random.Random(opts.get("seed"))

    def one():
        r = rng.random()
        if r < 0.4:
            return {"f": "read", "value": None}
        if r < 0.7:
            return {"f": "write", "value": rng.randrange(5)}
        return {"f": "cas", "value": [rng.randrange(5),
                                      rng.randrange(5)]}

    return {
        "client": CrateRegisterClient(),
        "generator": gen.limit(opts.get("ops", 500), one),
        "checker": chk.linearizable(
            {"model": models.cas_register()}),
    }


WORKLOADS = {"register": register_workload}


def crate_test(opts: dict) -> dict:
    name = opts.get("workload") or "register"
    w = WORKLOADS[name](opts)
    test = testing.noop_test()
    test.update(
        name=f"crate-{name}",
        os=debian.os,
        db=CrateDB(opts.get("version", VERSION)),
        ssh=opts["ssh"],
        nodes=opts["nodes"],
        concurrency=opts["concurrency"],
        client=w["client"],
        nemesis=jnemesis.partition_random_halves(),
        checker=chk.compose({"workload": w["checker"],
                             "stats": chk.stats(),
                             "perf": chk.perf(),
                             "timeline": chk.timeline()}),
        generator=gen.time_limit(
            opts.get("time_limit", 30),
            gen.clients(
                gen.stagger(1.0 / opts.get("rate", 20),
                            w["generator"]),
                jnemesis.start_stop_cycle(10.0))))
    return test


def _opts(p):
    p.add_argument("--workload", default=None,
                   help="Workload (default register). "
                        + cli.one_of(WORKLOADS))
    p.add_argument("--version", default=VERSION,
                   help="CrateDB release to install.")
    p.add_argument("--rate", type=float, default=20)
    return p


def main(argv=None) -> None:
    commands = {}
    commands.update(cli.single_test_cmd(crate_test, parser_fn=_opts))
    commands.update(cli.serve_cmd())
    commands.update(cli.coverage_cmd(list(WORKLOADS)))
    cli.run_cli(commands, argv)


if __name__ == "__main__":
    main()
