"""DB test suites: consumers of the framework that install and drive
real databases (the reference ships ~26 of these; see SURVEY.md 2.6)."""
