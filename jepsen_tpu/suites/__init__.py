"""DB test suites: consumers of the framework that install and drive
real databases (the reference ships ~26 of these; see SURVEY.md 2.6).

SUITES maps suite name -> module path; `load(name)` imports lazily (a
suite pulls in its client transport only when actually driven). Each
module exposes `main(argv)` — `python -m jepsen_tpu.suites.<name>
test ...` — plus a `<name>_test(opts)` builder. The registry is what
the coverage atlas and the campaign runner (ROADMAP item 5) enumerate
when naming gap-filling suite configs."""

from importlib import import_module

SUITES = {
    "aerospike": "jepsen_tpu.suites.aerospike",
    "cockroach": "jepsen_tpu.suites.cockroach",
    "consul": "jepsen_tpu.suites.consul",
    "crate": "jepsen_tpu.suites.crate",
    "dgraph": "jepsen_tpu.suites.dgraph",
    "disque": "jepsen_tpu.suites.disque",
    "elasticsearch": "jepsen_tpu.suites.elasticsearch",
    "etcd": "jepsen_tpu.suites.etcd",
    "galera": "jepsen_tpu.suites.galera",
    "hazelcast": "jepsen_tpu.suites.hazelcast",
    "mongodb": "jepsen_tpu.suites.mongodb",
    "postgres": "jepsen_tpu.suites.postgres",
    "rabbitmq": "jepsen_tpu.suites.rabbitmq",
    "raftis": "jepsen_tpu.suites.raftis",
    "redis-sentinel": "jepsen_tpu.suites.redis_sentinel",
    "rethinkdb": "jepsen_tpu.suites.rethinkdb",
    "stolon": "jepsen_tpu.suites.stolon",
    "tidb": "jepsen_tpu.suites.tidb",
    "voltdb": "jepsen_tpu.suites.voltdb",
    "yugabyte": "jepsen_tpu.suites.yugabyte",
    "zookeeper": "jepsen_tpu.suites.zookeeper",
}


def load(name: str):
    """Imports and returns a suite module by registry name."""
    if name not in SUITES:
        raise KeyError(f"unknown suite {name!r}; known: "
                       + ", ".join(sorted(SUITES)))
    return import_module(SUITES[name])
