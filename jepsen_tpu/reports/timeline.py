"""HTML Gantt timeline of a history, one column per process.

Capability reference: jepsen/src/jepsen/checker/timeline.clj — 10k op
cap (13-15), css styles (28-37), process pairing (39-58), op rendering
and layout constants (timescale 1e6 ns/px, col-width 100px, height
16px).
"""

from __future__ import annotations

import html as _html
import logging

from ..history import History, is_info, is_invoke

logger = logging.getLogger(__name__)

OP_LIMIT = 10_000
"""Maximum ops rendered (timeline.clj:13-15)."""

TIMESCALE = 1e6   # nanoseconds per pixel
COL_WIDTH = 100   # px
GUTTER_WIDTH = 106
HEIGHT = 16

STYLESHEET = """\
body        { font-family: sans-serif; font-size: 11px; }
.ops        { position: absolute; }
.op         { position: absolute; padding: 2px; border-radius: 2px;
              box-shadow: 0 1px 3px rgba(0,0,0,0.12),
                          0 1px 2px rgba(0,0,0,0.24);
              overflow: hidden; }
.op.invoke  { background: #eeeeee; }
.op.ok      { background: #6DB6FE; }
.op.info    { background: #FFAA26; }
.op.fail    { background: #FEB5DA; }
.op:target  { box-shadow: 0 14px 28px rgba(0,0,0,0.25),
                          0 10px 10px rgba(0,0,0,0.22); }
"""


def pairs(history) -> list:
    """[invoke, completion] / [info] / [invoke] pairs per process
    (timeline.clj:39-58)."""
    invocations: dict = {}
    out: list = []
    for o in history:
        if is_invoke(o):
            invocations[o.process] = o
        elif is_info(o) and o.process not in invocations:
            out.append([o])  # unmatched info
        else:
            inv = invocations.pop(o.process, None)
            if inv is not None:
                out.append([inv, o])
            else:
                out.append([o])
    # still-open invocations render as bars to the end
    out.extend([inv] for inv in invocations.values())
    return out


def _title(op, trace_lines=None) -> str:
    lines = [f"process {op.process}", f"type {op.type}", f"f {op.f}",
             f"index {op.index}", f"value {op.value!r}"]
    if op.ext:
        lines += [f"{k} {v!r}" for k, v in op.ext.items()]
    if trace_lines:
        lines.append("— trace —")
        lines.extend(trace_lines)
    return _html.escape("\n".join(lines), quote=True)


_TRACE_LINE_LIMIT = 8
"""Max per-op trace lines in a hover title."""


def trace_titles(optrace) -> dict:
    """{invocation op index: [hover line, ...]} from per-op trace
    records (jepsen_tpu.tracing) — what each op *did* (client calls,
    remote commands, retries, reconnects), surfaced where the op sits
    on the timeline."""
    from .. import tracing as jtracing

    out: dict = {}
    for opi, recs in jtracing.by_op(optrace or []).items():
        lines = [jtracing.describe(r) for r in recs
                 if r.get("kind") != "op"][:_TRACE_LINE_LIMIT]
        if lines:
            out[opi] = lines
    return out


def render_html(test, history: History, optrace=None) -> str:
    history = History(
        [o for o in history if o.type in
         ("invoke", "ok", "fail", "info")], assign_indices=False)
    truncated = False
    prs = pairs(history)
    if len(prs) > OP_LIMIT:
        prs = prs[:OP_LIMIT]
        truncated = True
    processes: list = []
    seen = set()
    for pair in prs:
        p = pair[0].process
        if p not in seen:
            seen.add(p)
            processes.append(p)
    col_of = {p: i for i, p in enumerate(processes)}
    tmax = max((o.time for o in history), default=0)
    titles = trace_titles(optrace)

    cells = []
    for pair in prs:
        first, last = pair[0], pair[-1]
        t0 = first.time
        t1 = last.time if len(pair) > 1 else tmax
        top = t0 / TIMESCALE
        h = max((t1 - t0) / TIMESCALE, HEIGHT)
        left = GUTTER_WIDTH * col_of[first.process]
        typ = last.type
        label = f"{first.process} {first.f} {first.value!r}"
        cells.append(
            f'<div id="op-{first.index}" class="op {typ}" '
            f'style="left:{left:.0f}px; top:{top:.1f}px; '
            f'width:{COL_WIDTH}px; height:{h:.1f}px" '
            f'title="{_title(last, titles.get(first.index))}">'
            f'{_html.escape(label)}</div>')

    headers = "".join(
        f'<div style="position:absolute; left:{GUTTER_WIDTH * i}px; '
        f'top:0; width:{COL_WIDTH}px; font-weight:bold">'
        f'{_html.escape(str(p))}</div>'
        for i, p in enumerate(processes))
    note = (f"<p><b>Truncated to {OP_LIMIT} operations.</b></p>"
            if truncated else "")
    name = _html.escape(str(test.get("name") or "test"))
    return (f"<!DOCTYPE html><html><head><meta charset='utf-8'>"
            f"<title>{name} timeline</title>"
            f"<style>{STYLESHEET}</style></head><body>"
            f"<h1>{name}</h1>{note}"
            f"<div style='position:relative; height:24px'>{headers}"
            f"</div><div class='ops' style='position:relative'>"
            + "".join(cells) + "</div></body></html>")


def html():
    """Checker writing timeline.html into the store dir
    (timeline.clj html)."""
    from ..checker import _Fn

    def run(test, history, opts):
        if not (test.get("store_dir") or test.get("name")):
            return {"valid?": True, "skipped": "no store directory"}
        from .. import store as jstore

        optrace = None
        if test.get("store_dir"):
            try:  # per-op trace detail in the hover titles, if traced
                optrace = jstore.load_optrace(test["store_dir"]) or None
            except OSError:
                optrace = None
        sub = (opts or {}).get("subdirectory")
        parts = ([sub, "timeline.html"] if sub else ["timeline.html"])
        out = jstore.path(test, *parts)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(render_html(test, history, optrace=optrace))
        return {"valid?": True, "file": str(out)}

    return _Fn(run)
