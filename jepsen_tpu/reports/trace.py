"""Chrome-trace / Perfetto export for stored runs.

Converts a run's three time-aligned records — telemetry spans, the
history's op invoke→complete lifetimes, and nemesis activation
windows — into one Chrome Trace Event Format JSON (`trace.json`)
openable directly in https://ui.perfetto.dev (or chrome://tracing).
Everything shares the test's linear clock (util.relative_time_nanos),
so a kernel launch, the client op it was checking, and the fault
window it raced all line up on one timeline.

Track layout (pid/tid are synthetic; names ride in `M` metadata
events, per the trace-event spec):

  harness  one thread-track per recorder thread, nesting spans as the
           usual flame layout (`X` complete events)
  clients  one track per process: each op is an `X` slice from its
           invocation to its completion, colored by completion type.
           When the run carried the per-op causal trace
           (optrace.jsonl, jepsen_tpu.tracing), each op slice grows
           nested child slices — the worker-side invoke, client
           calls, remote (SSH) commands with exit/retry args — plus
           instant markers for reconnects/partition events, all on
           the same linear clock so they nest by containment.
  nemesis  one track per nemesis spec, a slice per activation window
  device   one track per compiled kernel (wgl, scc, ...): each launch
           record (jepsen_tpu.tpu.profiler) is a slice carrying its
           FLOPs/bytes/phase-split attrs
  node <n> one process per DB node (jepsen_tpu.nodeprobe): counter
           tracks (`C` events) for CPU/memory/network/clock-offset —
           the offset series merges the probe ticks with the
           history's check-offsets observations — plus instant
           markers for tagged DB-log events, probe gaps, and
           quarantine-breaker transitions

CLI: `python -m jepsen_tpu trace <run>` writes `trace.json` into the
run's store directory (see doc/observability.md for the walkthrough);
`--ops 3,17` (or web.py's per-anomaly links) pre-filters the export to
the ops participating in an anomaly.
"""

from __future__ import annotations

import json
import logging
from pathlib import Path

from .. import util
from ..history import History, is_info, is_invoke, is_ok

logger = logging.getLogger(__name__)

TRACE_JSON = "trace.json"

# Perfetto/catapult reserved color names, keyed by completion type.
_CNAME = {"ok": "good", "info": "bad", "fail": "terrible"}

_PID_HARNESS = 1
_PID_CLIENTS = 2
_PID_NEMESIS = 3
_PID_DEVICE = 4
_PID_NODE_BASE = 10  # node i gets pid _PID_NODE_BASE + i
# the fleet flight recorder's session view (fleet_chrome_trace) —
# far from the node range so a combined viewer never collides
_PID_FLEET_TENANTS = 90
_PID_FLEET_SVC = 91


def _us(ns: int) -> float:
    """Trace-event timestamps are microseconds."""
    return ns / 1e3


class _Tids:
    """Allocates stable integer tids per track name, emitting the
    thread_name metadata event on first use."""

    def __init__(self, events: list, pid: int, sort_index: int = 0):
        self.events = events
        self.pid = pid
        self.by_name: dict = {}
        self.events.append({"ph": "M", "name": "process_sort_index",
                            "pid": pid, "tid": 0,
                            "args": {"sort_index": sort_index}})

    def tid(self, name: str) -> int:
        t = self.by_name.get(name)
        if t is None:
            t = self.by_name[name] = len(self.by_name) + 1
            self.events.append({"ph": "M", "name": "thread_name",
                                "pid": self.pid, "tid": t,
                                "args": {"name": str(name)}})
        return t


def _process_meta(events: list, pid: int, name: str) -> None:
    events.append({"ph": "M", "name": "process_name", "pid": pid,
                   "tid": 0, "args": {"name": name}})


def _span_events(events: list, spans) -> int:
    """Telemetry spans as one flame-track per recorder thread. Device
    launch records (`kernel:` spans) are excluded here — they get
    their own per-kernel device tracks (_device_events) instead of
    hiding inside the harness flame."""
    _process_meta(events, _PID_HARNESS, "harness")
    tids = _Tids(events, _PID_HARNESS, sort_index=0)
    n = 0
    for s in spans:
        if "t0" not in s or "t1" not in s:
            continue
        if str(s.get("name", "")).startswith("kernel:"):
            continue
        ev = {"ph": "X", "cat": "span",
              "name": str(s.get("name", "?")),
              "pid": _PID_HARNESS,
              "tid": tids.tid(s.get("thread") or "main"),
              "ts": _us(s["t0"]),
              "dur": max(_us(s["t1"] - s["t0"]), 0.001)}
        if s.get("attrs"):
            ev["args"] = {k: repr(v) for k, v in s["attrs"].items()}
        events.append(ev)
        n += 1
    return n


def _device_events(events: list, spans) -> int:
    """Device-launch records (the profiler's `kernel:<name>` telemetry
    spans) as one track per kernel: each launch is a slice carrying
    its cost/phase attrs (FLOPs, bytes, compile/compute split), so a
    kernel launch lines up against the checker phase and the ops it
    was checking on the shared clock."""
    launches = [s for s in spans
                if str(s.get("name", "")).startswith("kernel:")
                and "t0" in s and "t1" in s]
    if not launches:
        return 0
    _process_meta(events, _PID_DEVICE, "device")
    tids = _Tids(events, _PID_DEVICE, sort_index=3)
    n = 0
    for s in launches:
        kernel = str(s["name"])[len("kernel:"):]
        ev = {"ph": "X", "cat": "kernel",
              "name": kernel,
              "pid": _PID_DEVICE,
              "tid": tids.tid(kernel),
              "ts": _us(s["t0"]),
              "dur": max(_us(s["t1"] - s["t0"]), 0.001)}
        if s.get("attrs"):
            ev["args"] = {k: (v if isinstance(v, (int, float, str))
                              else repr(v))
                          for k, v in s["attrs"].items()}
        events.append(ev)
        n += 1
        # search-explorer counter track: the launch's BFS frontier
        # occupancy curve (jepsen_tpu.tpu.wgl._drain) spread over the
        # launch's wall window, one `C` track per kernel
        curve = (s.get("attrs") or {}).get("frontier_curve")
        if isinstance(curve, list) and curve and all(
                isinstance(x, (int, float)) for x in curve):
            track = f"{kernel} frontier"
            span_ns = max(s["t1"] - s["t0"], 1)
            step = span_ns / len(curve)
            for i, x in enumerate(curve):
                events.append({
                    "ph": "C", "name": track, "pid": _PID_DEVICE,
                    "tid": tids.tid(track),
                    "ts": _us(s["t0"] + i * step),
                    "args": {"frontier": float(x)}})
                n += 1
    return n


def _op_events(events: list, history, ops_filter=None) -> "_Tids":
    """Op lifetimes: one track per process, one slice per
    invoke→complete pair. Uncompleted invokes extend to history end
    (the same convention the timeline report uses). Returns the track
    allocator so the optrace child spans land on the same tracks."""
    _process_meta(events, _PID_CLIENTS, "clients")
    tids = _Tids(events, _PID_CLIENTS, sort_index=1)
    if not isinstance(history, History):
        history = History(history)
    tmax = history[-1].time if len(history) else 0
    n = 0
    for op in history:
        if not is_invoke(op):
            continue
        if ops_filter is not None and op.index not in ops_filter:
            continue
        comp = history.completion(op)
        t1 = comp.time if comp is not None else tmax
        ctype = ("info" if comp is None or is_info(comp)
                 else "ok" if is_ok(comp) else "fail")
        ev = {"ph": "X", "cat": "op",
              "name": str(op.f),
              "pid": _PID_CLIENTS,
              "tid": tids.tid(util.name_str(op.process)),
              "ts": _us(op.time),
              "dur": max(_us(t1 - op.time), 0.001),
              "cname": _CNAME[ctype],
              "args": {"type": ctype, "process": str(op.process),
                       "value": repr(op.value)}}
        if comp is not None and comp.value != op.value:
            ev["args"]["result"] = repr(comp.value)
        events.append(ev)
        n += 1
    logger.debug("trace: %d op slices", n)
    return tids


def _optrace_events(events: list, tids: "_Tids", records,
                    ops_filter=None) -> int:
    """Per-op causal trace records as nested slices under the op
    lifetimes: same pid/tid as the op's process track, so Perfetto
    nests them by time containment. Spans (op/client/remote kinds)
    become `X` slices carrying their attrs (cmd, node, exit, retries);
    events become `i` instant markers."""
    n = 0
    for rec in records or []:
        opi = rec.get("op")
        if ops_filter is not None and opi not in ops_filter:
            continue
        if rec.get("process") is None or "t0" not in rec:
            continue  # context-free events have no op track to sit on
        kind = str(rec.get("kind", "span"))
        # the op-kind record is the worker-side invoke nested inside
        # the history's op-lifetime slice (cat "op") — name it apart
        base = {"cat": "invoke" if kind == "op" else kind,
                "name": str(rec.get("name", "?")),
                "pid": _PID_CLIENTS,
                "tid": tids.tid(str(rec["process"])),
                "ts": _us(rec["t0"])}
        args = {"trace": rec.get("trace"), "span": rec.get("span")}
        if rec.get("status"):
            args["status"] = str(rec["status"])
        for k, v in (rec.get("attrs") or {}).items():
            args[k] = v if isinstance(v, (int, float, str)) else repr(v)
        base["args"] = args
        if rec.get("kind") == "event":
            base.update(ph="i", s="t")
        else:
            if "t1" not in rec:
                continue
            base.update(ph="X",
                        dur=max(_us(rec["t1"] - rec["t0"]), 0.001))
        events.append(base)
        n += 1
    return n


def _nemesis_events(events: list, test, history) -> int:
    """Fault-activation windows, one track per nemesis spec — the same
    intervals reports/perf.py shades."""
    from .perf import _nemesis_specs

    if not isinstance(history, History):
        history = History(history)
    specs = _nemesis_specs(test or {}) or [
        {"name": "nemesis", "start": {"start"}, "stop": {"stop"}}]
    _process_meta(events, _PID_NEMESIS, "nemesis")
    tids = _Tids(events, _PID_NEMESIS, sort_index=2)
    tmax = history[-1].time if len(history) else 0
    n = 0
    for spec in specs:
        name = spec.get("name") or "nemesis"
        ints = util.nemesis_intervals(
            history, [{"start": spec["start"], "stop": spec["stop"]}])
        for start, stop in ints:
            t1 = stop.time if stop is not None else tmax
            events.append({
                "ph": "X", "cat": "nemesis",
                "name": str(name),
                "pid": _PID_NEMESIS, "tid": tids.tid(str(name)),
                "ts": _us(start.time),
                "dur": max(_us(t1 - start.time), 0.001),
                "cname": "terrible",
                "args": {"start": str(start.f),
                         "stop": str(stop.f) if stop else "(open)"}})
            n += 1
    return n


def _node_events(events: list, noderecs, history=None) -> int:
    """Node-plane records (jepsen_tpu.nodeprobe) as one process per DB
    node: `C` counter events for the resource series, instant markers
    for log events / gaps / breaker transitions. The clock-offset
    counter uses the MERGED series (probe ticks + the history's
    check-offsets observations), so skew readings that previously sat
    unrendered in the history finally land on the timeline."""
    from .. import nodeprobe

    noderecs = list(noderecs or [])
    offsets = nodeprobe.clock_series(noderecs, history)
    nodes = sorted({str(r.get("node")) for r in noderecs}
                   | set(offsets))
    if not nodes:
        return 0
    n = 0
    for i, node in enumerate(nodes):
        pid = _PID_NODE_BASE + i
        _process_meta(events, pid, f"node {node}")
        tids = _Tids(events, pid, sort_index=10 + i)
        mark_tid = tids.tid("events")

        def counter(name, t, value):
            events.append({"ph": "C", "name": name, "pid": pid,
                           "tid": tids.tid(name), "ts": _us(t),
                           "args": {name: value}})

        for t, off in offsets.get(node, []):
            counter("clock_offset_ms", t, round(off * 1e3, 3))
            n += 1
        for rec in noderecs:
            if str(rec.get("node")) != node:
                continue
            kind = rec.get("kind")
            t = rec.get("t", 0)
            if kind == "sample":
                busy = (rec.get("cpu") or {}).get("busy")
                if busy is not None:
                    counter("cpu_busy", t, busy)
                used = (rec.get("mem") or {}).get("used_frac")
                if used is not None:
                    counter("mem_used_frac", t, used)
                net = rec.get("net") or {}
                if "rx_bytes_s" in net:
                    counter("net_rx_bytes_s", t, net["rx_bytes_s"])
                if "tx_bytes_s" in net:
                    counter("net_tx_bytes_s", t, net["tx_bytes_s"])
                n += 1
            elif kind == "log":
                events.append({
                    "ph": "i", "s": "t", "cat": "node-log",
                    "name": f"log:{rec.get('class')}",
                    "pid": pid, "tid": mark_tid, "ts": _us(t),
                    "args": {"file": str(rec.get("file")),
                             "line": str(rec.get("line"))[:200],
                             "ts_source": str(rec.get("ts"))}})
                n += 1
            elif kind == "gap":
                events.append({
                    "ph": "i", "s": "t", "cat": "node-gap",
                    "name": f"gap:{rec.get('reason')}",
                    "pid": pid, "tid": mark_tid, "ts": _us(t),
                    "args": {}})
                n += 1
            elif kind == "breaker":
                events.append({
                    "ph": "i", "s": "t", "cat": "node-breaker",
                    "name": f"breaker:{rec.get('state')}",
                    "pid": pid, "tid": mark_tid, "ts": _us(t),
                    "args": {}})
                n += 1
    return n


def expand_op_filter(history, ops) -> set | None:
    """An anomaly's op references may be completion indices; the trace
    and timeline join on invocation indices. Expands the given index
    set so each index's pair is included too."""
    if ops is None:
        return None
    if not isinstance(history, History):
        history = History(history)
    out = set(int(i) for i in ops)
    for op in history:
        if op.index in out:
            try:
                pair = (history.completion(op) if is_invoke(op)
                        else history.invocation(op))
            except KeyError:
                pair = None
            if pair is not None:
                out.add(pair.index)
    return out


def chrome_trace(test: dict | None, history, spans,
                 optrace=None, ops=None, noderecs=None) -> dict:
    """The complete trace document for a run. `test` may be the loaded
    test.json dict (for nemesis plot specs), `history` a History or op
    list, `spans` telemetry span records, `optrace` per-op trace
    records (jepsen_tpu.tracing), `noderecs` node-plane records
    (jepsen_tpu.nodeprobe). `ops`: restrict the client tracks to
    these op indices — the pre-filtered anomaly drill-down view."""
    history = history if history is not None else []
    ops_filter = expand_op_filter(history, ops)
    events: list[dict] = []
    n_spans = _span_events(events, spans or [])
    n_dev = _device_events(events, spans or [])
    tids = _op_events(events, history, ops_filter)
    n_rec = _optrace_events(events, tids, optrace, ops_filter)
    n_nem = _nemesis_events(events, test, history)
    n_node = _node_events(events, noderecs, history)
    logger.info("trace: %d spans, %d device launches, %d optrace "
                "records, %d nemesis windows, %d node records",
                n_spans, n_dev, n_rec, n_nem, n_node)
    return {"traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"source": "jepsen_tpu",
                          "test": str((test or {}).get("name"))}}


def write_trace(run_dir, out_path=None, ops=None) -> Path:
    """Loads a stored run and writes its trace.json; returns the
    path. Works on runs that predate telemetry (spans just come back
    empty) and on crashed runs (history read is torn-tolerant). `ops`
    pre-filters the client tracks to the given op indices (anomaly
    provenance drill-down)."""
    from .. import store as jstore

    d = Path(run_dir)
    test = jstore.load(d)
    events, _metrics = jstore.load_telemetry(d)
    optrace = jstore.load_optrace(d)
    noderecs = jstore.load_nodes(d)
    doc = chrome_trace(test, test.get("history") or [], events,
                       optrace=optrace, ops=ops, noderecs=noderecs)
    out = Path(out_path) if out_path else d / TRACE_JSON
    with open(out, "w") as f:
        json.dump(doc, f)
    return out


def fleet_chrome_trace(records) -> dict:
    """The fleet flight recorder's session view: renders
    fleet/flightrec records (FlightRecorder.records()) as a Chrome
    trace — one track per tenant (chunk ack spans + verdict spans,
    args carrying the latency decomposition), a device-launch track
    with a batch-occupancy counter, and WAL + scheduler swimlanes.
    Timestamps rebase to the earliest record so the raw monotonic
    clock starts at zero. The document passes
    validate_chrome_trace."""
    recs = [r for r in records or [] if isinstance(r, dict)
            and isinstance(r.get("t0"), int)
            and isinstance(r.get("t1"), int)]
    events: list[dict] = []
    _process_meta(events, _PID_FLEET_TENANTS, "fleet tenants")
    _process_meta(events, _PID_FLEET_SVC, "fleet service")
    ten = _Tids(events, _PID_FLEET_TENANTS, sort_index=0)
    svc = _Tids(events, _PID_FLEET_SVC, sort_index=1)
    t_base = min((r["t0"] for r in recs), default=0)

    def ts(ns: int) -> float:
        return _us(ns - t_base)

    for r in recs:
        kind = r.get("kind")
        dur = max(_us(r["t1"] - r["t0"]), 0.001)
        if kind == "chunk":
            args = {k: r[k] for k in ("wal_ms", "ack_ms", "ops")
                    if k in r}
            if r.get("trace") is not None:
                args["trace"] = str(r["trace"])
            events.append({
                "ph": "X", "cat": "fleet.chunk",
                "name": f"chunk {r.get('run')}#{r.get('seq')}",
                "pid": _PID_FLEET_TENANTS,
                "tid": ten.tid(str(r.get("tenant"))),
                "ts": ts(r["t0"]), "dur": dur, "args": args})
            wal_ms = r.get("wal_ms")
            if isinstance(wal_ms, (int, float)) and wal_ms > 0:
                # the append's fsync share, right-aligned at the ack
                events.append({
                    "ph": "X", "cat": "fleet.wal", "name": "append",
                    "pid": _PID_FLEET_SVC, "tid": svc.tid("wal"),
                    "ts": max(ts(r["t1"]) - wal_ms * 1e3, 0.0),
                    "dur": max(wal_ms * 1e3, 0.001)})
        elif kind == "launch":
            args = {k: r[k] for k in
                    ("cls", "reason", "rows", "capacity",
                     "occupancy", "device_ms", "certify_ms")
                    if k in r}
            args["tenants"] = ",".join(
                str(t) for t in (r.get("tenants") or []))
            events.append({
                "ph": "X", "cat": "fleet.launch",
                "name": f"{r.get('cls')} [{r.get('reason')}]",
                "pid": _PID_FLEET_SVC,
                "tid": svc.tid("device launches"),
                "ts": ts(r["t0"]), "dur": dur, "args": args})
            occ = r.get("occupancy")
            if isinstance(occ, (int, float)):
                events.append({
                    "ph": "C", "name": "batch occupancy",
                    "pid": _PID_FLEET_SVC,
                    "tid": svc.tid("batch occupancy"),
                    "ts": ts(r["t0"]),
                    "args": {str(r.get("cls")): float(occ)}})
            # the decision log: WHY this launch fired, as an instant
            # on the scheduler swimlane
            events.append({
                "ph": "i", "cat": "fleet.decision", "s": "t",
                "name": str(r.get("reason")),
                "pid": _PID_FLEET_SVC, "tid": svc.tid("scheduler"),
                "ts": ts(r["t0"])})
        elif kind == "verdict":
            lat = r.get("latency") or {}
            args = {k: v for k, v in lat.items()
                    if isinstance(v, (int, float))
                    and not isinstance(v, bool)}
            if lat.get("replay"):
                args["replay"] = 1
            events.append({
                "ph": "X", "cat": "fleet.verdict",
                "name": f"verdict {r.get('run')}",
                "pid": _PID_FLEET_TENANTS,
                "tid": ten.tid(str(r.get("tenant"))),
                "ts": ts(r["t0"]), "dur": dur, "args": args})
    return {"traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"source": "jepsen_tpu.fleet.flightrec"}}


def validate_chrome_trace(doc: dict) -> int:
    """Schema check for an exported Chrome-trace document: required
    keys per event phase, non-negative microsecond timestamps and
    durations, and metadata referential integrity (every slice's
    pid/tid carries process_name/thread_name metadata). Returns the
    event count; raises ValueError on the first violation."""
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        raise ValueError("traceEvents must be a list")
    named_pids: set = set()
    named_tids: set = set()
    for i, ev in enumerate(events):
        ph = ev.get("ph")
        if ph not in ("X", "M", "i", "C"):
            raise ValueError(f"event {i}: unknown ph {ph!r}")
        if "name" not in ev or "pid" not in ev:
            raise ValueError(f"event {i}: missing name/pid: {ev}")
        if ph == "M":
            if not isinstance(ev.get("args"), dict):
                raise ValueError(f"metadata event {i} missing args")
            if ev["name"] == "process_name":
                named_pids.add(ev["pid"])
            elif ev["name"] == "thread_name":
                named_tids.add((ev["pid"], ev["tid"]))
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            raise ValueError(f"event {i}: bad ts {ts!r}")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                raise ValueError(f"event {i}: bad dur {dur!r}")
        if ph == "C":
            # counter events (node resource/skew series): args is the
            # series map and every value must be numeric
            args = ev.get("args")
            if not isinstance(args, dict) or not args or not all(
                    isinstance(v, (int, float))
                    for v in args.values()):
                raise ValueError(
                    f"counter event {i}: non-numeric args "
                    f"{ev.get('args')!r}")
        if ev["pid"] not in named_pids:
            raise ValueError(f"event {i}: pid {ev['pid']} unnamed")
        if (ev["pid"], ev.get("tid")) not in named_tids:
            raise ValueError(
                f"event {i}: tid {ev.get('tid')} unnamed in pid "
                f"{ev['pid']}")
    return len(events)
