"""Report rendering: latency/rate graphs, HTML timeline, clock plots.

The reference keeps these under jepsen.checker.* (checker/perf.clj,
checker/timeline.clj, checker/clock.clj); they live in their own
package here because Python can't have both checker.perf() (the
checker constructor, checker.clj latency-graph/rate-graph) and a
checker.perf submodule.
"""
