"""Clock-skew-over-time plots.

Capability reference: jepsen/src/jepsen/checker/clock.clj —
history->datasets building {node: [[t, offset], ...]} from ops carrying
'clock-offsets' (14-35), step plots per node with nemesis shading
(48-76). Renders via matplotlib instead of gnuplot.
"""

from __future__ import annotations

import logging

from .. import util
from .perf import _figure, _save, _shade_nemeses

logger = logging.getLogger(__name__)


def history_to_datasets(history) -> dict:
    """{node: [[t-seconds, offset], ...]} from 'clock-offsets' ops
    (clock.clj:14-35)."""
    if not len(history):
        return {}
    final_time = util.nanos_to_secs(history[-1].time)
    series: dict = {}
    for op in history:
        offsets = op.get("clock-offsets")
        if not offsets:
            continue
        t = util.nanos_to_secs(op.time)
        for node, offset in offsets.items():
            series.setdefault(node, []).append([t, offset])
    # extend each series to the end of the test so steps render fully
    for pts in series.values():
        pts.append([final_time, pts[-1][1]])
    return series


def short_node_names(nodes) -> list:
    """Strips common trailing domain components (clock.clj:37-46)."""
    split = [str(n).split(".") for n in nodes]
    if not split:
        return []
    # drop the longest common proper suffix
    k = 0
    while (k < min(len(s) for s in split) - 1
           and len({tuple(s[len(s) - k - 1:]) for s in split}) == 1):
        k += 1
    return [".".join(s[:len(s) - k]) for s in split]


def merge_nodeprobe(datasets: dict, test) -> dict:
    """Folds the node probe's per-tick clock offsets (nodes.jsonl,
    jepsen_tpu.nodeprobe) into the check-offsets datasets, so the skew
    plot shows the continuously-sampled series, not just the nemesis's
    occasional observations. Points merge time-sorted per node."""
    from .. import nodeprobe

    records = nodeprobe.load_records(test.get("store_dir"))
    if not records:
        return datasets
    merged = nodeprobe.clock_series(records)  # probe points only —
    # the history's check-offsets already live in `datasets`
    if not merged:
        return datasets
    out = {n: list(pts) for n, pts in datasets.items()}
    for node, pts in merged.items():
        out.setdefault(node, []).extend(
            [util.nanos_to_secs(t), off] for t, off in pts)
    for pts in out.values():
        pts.sort(key=lambda p: p[0])
    return out


def plot(test, history, opts=None) -> dict:
    """Writes clock-skew.png (clock.clj plot!): the history's
    check-offsets observations merged with the node probe's sampled
    offset series."""
    if not (test.get("store_dir") or test.get("name")):
        return {"valid?": True, "skipped": "no store directory"}
    datasets = merge_nodeprobe(history_to_datasets(history), test)
    if not datasets:
        return {"valid?": True}
    nodes = sorted(datasets, key=str)
    names = short_node_names(nodes)
    plt, fig, ax = _figure()
    ax.set_ylabel("Skew (s)")
    ax.set_title(f"{test.get('name') or 'test'} clock skew")
    for node, name in zip(nodes, names):
        pts = datasets[node]
        ax.step([t for t, _ in pts], [v for _, v in pts],
                where="post", lw=1.2, label=name, zorder=2)
    _shade_nemeses(ax, test, history)
    ax.legend(loc="upper right", fontsize=8)
    path = _save(plt, fig, test, opts, "clock-skew.png")
    return {"valid?": True, "file": path}
