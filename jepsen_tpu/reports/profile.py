"""Rendering for stored device-kernel profiles.

Consumes the `profiler.<kernel>.*` counters/gauges the device profiler
(jepsen_tpu.tpu.profiler) aggregates into a run's metrics.json, plus
the per-launch `kernel:<name>` spans in telemetry.jsonl, and renders
the per-kernel cost/occupancy table behind `python -m jepsen_tpu
profile <run-dir>` and web.py's kernel-profile section. Pure functions
over loaded artifacts — no recorder access."""

from __future__ import annotations

import html as _html


def kernel_stats(metrics: dict | None) -> dict[str, dict]:
    """{kernel: {field: value}} parsed back out of a metrics.json's
    profiler counters and gauges. Kernel names are dot-free by
    construction, so the counter name splits unambiguously."""
    out: dict[str, dict] = {}
    for section in ("counters", "gauges"):
        for name, v in ((metrics or {}).get(section) or {}).items():
            parts = name.split(".")
            if parts[0] != "profiler" or len(parts) < 3:
                continue
            kernel = parts[1]
            field = ".".join(parts[2:])
            if not isinstance(v, (int, float)):
                continue
            out.setdefault(kernel, {})[field] = v
    return out


def _fmt_count(v) -> str:
    if v is None:
        return "-"
    v = float(v)
    for unit, scale in (("G", 1e9), ("M", 1e6), ("k", 1e3)):
        if v >= scale:
            return f"{v / scale:.1f}{unit}"
    return f"{v:.0f}"


def _fmt_bytes(v) -> str:
    if v is None:
        return "-"
    v = float(v)
    for unit, scale in (("GB", 1 << 30), ("MB", 1 << 20),
                        ("kB", 1 << 10)):
        if v >= scale:
            return f"{v / scale:.1f}{unit}"
    return f"{v:.0f}B"


def _fmt_ms(ns) -> str:
    if not ns:
        return "-"
    if ns >= 1e9:
        return f"{ns / 1e9:.2f}s"
    return f"{ns / 1e6:.1f}ms"


def kernel_rows(metrics: dict | None) -> list[dict]:
    """One display row per kernel: formatted cost totals, cache hit
    rate, and the wall-time split across pipeline phases (encode /
    H2D / dispatch / compute / D2H, as % of the summed phase time —
    dispatch includes compile on a bucket's first launch, which the
    separate compile column calls out)."""
    rows = []
    for kernel, st in sorted(kernel_stats(metrics).items()):
        hits = int(st.get("compile.hit", 0))
        misses = int(st.get("compile.miss", 0))
        looked = hits + misses
        phases = [("encode", st.get("encode_ns", 0)),
                  ("h2d", st.get("h2d_ns", 0)),
                  ("dispatch", st.get("dispatch_ns", 0)),
                  ("compute", st.get("compute_ns", 0)),
                  ("d2h", st.get("d2h_ns", 0))]
        total_ph = sum(v for _n, v in phases)
        split = " ".join(f"{n} {v / total_ph * 100:.0f}%"
                         for n, v in phases if v) if total_ph else "-"
        rows.append({
            "kernel": kernel,
            "launches": int(st.get("launches", 0)),
            "cache": (f"{hits}/{looked}" if looked else "-"),
            "flops": _fmt_count(st.get("flops")),
            "bytes": _fmt_bytes(st.get("bytes")),
            "peak_mem": _fmt_bytes(st.get("peak_memory_bytes")),
            "compile": _fmt_ms(st.get("compile_ns")),
            "wall": _fmt_ms(st.get("wall_ns")),
            "split": split,
            "iterations": _fmt_count(st.get("iterations"))
            if st.get("iterations") else "-",
            # the search explorer's per-kernel shape: peak BFS frontier
            # occupancy, states explored, and dedup hits
            # (jepsen_tpu.tpu.wgl._drain / doc/observability.md)
            "frontier": _fmt_count(st.get("frontier_peak"))
            if st.get("frontier_peak") else "-",
            "states": _fmt_count(st.get("states"))
            if st.get("states") else "-",
            "dedup": _fmt_count(st.get("dedup_hits"))
            if st.get("dedup_hits") else "-",
        })
    return rows


_COLS = (("kernel", "kernel"), ("launches", "launches"),
         ("cache", "cache hit"), ("flops", "FLOPs"),
         ("bytes", "bytes"), ("peak_mem", "peak mem"),
         ("compile", "compile"), ("wall", "wall"),
         ("split", "wall split"), ("iterations", "iters"),
         ("frontier", "frontier"), ("states", "states"),
         ("dedup", "dedup"))


def slowest_launches(events, top: int = 5) -> list[dict]:
    """The `top` slowest per-launch records from a run's telemetry
    spans (name `kernel:<k>`), slowest first."""
    launches = [e for e in events or []
                if str(e.get("name", "")).startswith("kernel:")
                and "t1" in e]
    launches.sort(key=lambda e: e["t1"] - e["t0"], reverse=True)
    return launches[:top]


def profile_text(events, metrics: dict | None) -> str:
    """The `profile` CLI's output: the per-kernel table plus the
    slowest individual launches with their attrs."""
    rows = kernel_rows(metrics)
    if not rows:
        return ("(no kernel launches profiled — the run predates the "
                "profiler, or no device kernel ran)")
    widths = {k: max(len(h), *(len(str(r[k])) for r in rows))
              for k, h in _COLS}
    out = ["  ".join(h.ljust(widths[k]) for k, h in _COLS),
           "  ".join("-" * widths[k] for k, _h in _COLS)]
    for r in rows:
        out.append("  ".join(str(r[k]).ljust(widths[k])
                             for k, _h in _COLS))
    slow = slowest_launches(events)
    if slow:
        out += ["", "# Slowest launches", ""]
        for e in slow:
            attrs = e.get("attrs") or {}
            extra = " ".join(
                f"{k}={v}" for k, v in sorted(attrs.items())
                if k not in ("bucket",) and not k.endswith("_ns"))
            out.append(f"{e['name'][len('kernel:'):]:<12} "
                       f"{_fmt_ms(e['t1'] - e['t0']):>8}  {extra}")
    return "\n".join(out)


def profile_html(metrics: dict | None) -> str:
    """The kernel-profile section for web.py run pages (empty string
    when the run has no profiled launches)."""
    rows = kernel_rows(metrics)
    if not rows:
        return ""
    head = "".join(f"<th>{_html.escape(h)}</th>" for _k, h in _COLS)
    body = "".join(
        "<tr>" + "".join(f"<td>{_html.escape(str(r[k]))}</td>"
                         for k, _h in _COLS) + "</tr>"
        for r in rows)
    return ("<h2>kernel profile</h2><table>"
            f"<tr>{head}</tr>{body}</table>")


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

def _prom_name(name: str) -> str:
    out = []
    for ch in name:
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    s = "".join(out)
    if s and s[0].isdigit():
        s = "_" + s
    return "jepsen_tpu_" + s


def prometheus_text(metrics: dict | None, run: str | None = None
                    ) -> str:
    """A metrics.json rendered in Prometheus text exposition format
    (the /metrics endpoint — fleet-scrape groundwork): counters and
    numeric gauges as flat samples, span aggregates as labeled
    count/total samples. The optional `run` label names the source
    run directory."""
    if run:
        run = str(run).replace("\\", "_").replace('"', "_")
    label = f'{{run="{run}"}}' if run else ""
    lines: list[str] = []
    for name, v in sorted(((metrics or {}).get("counters") or {})
                          .items()):
        if isinstance(v, (int, float)):
            pn = _prom_name(name)
            lines.append(f"# TYPE {pn} counter")
            lines.append(f"{pn}{label} {v}")
    for name, v in sorted(((metrics or {}).get("gauges") or {})
                          .items()):
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            pn = _prom_name(name)
            lines.append(f"# TYPE {pn} gauge")
            lines.append(f"{pn}{label} {v}")
    span_label = '{span="%s"' + (f',run="{run}"' if run else "") + "}"
    for name, agg in sorted(((metrics or {}).get("spans") or {})
                            .items()):
        if not isinstance(agg, dict):
            continue
        safe = name.replace("\\", "_").replace('"', "_")
        for field in ("count", "total_ns"):
            if isinstance(agg.get(field), (int, float)):
                pn = f"jepsen_tpu_span_{field}"
                lines.append(
                    f"{pn}{span_label % safe} {agg[field]}")
    return "\n".join(lines) + "\n"


def validate_prometheus_text(text: str) -> int:
    """Scrape-parses a Prometheus exposition document: every
    non-comment line must be `name{labels}? value`. Returns the sample
    count; raises ValueError on the first bad line. Used by tier-1 to
    pin the /metrics contract."""
    import re

    sample = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
        r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\""
        r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\")*\})?"
        r" [0-9.eE+-]+(\.[0-9]+)?$")
    n = 0
    for i, line in enumerate(text.splitlines()):
        if not line.strip() or line.startswith("#"):
            continue
        if not sample.match(line):
            raise ValueError(f"line {i}: not a prometheus sample: "
                             f"{line!r}")
        n += 1
    return n
