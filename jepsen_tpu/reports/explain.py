"""Human-readable anomaly explanation artifacts.

Capability reference: the reference writes elle's anomaly files and
graphviz cycle plots into store/<test>/elle/ (append.clj:17-27 passes
:directory to elle.list-append/check) and renders the linearizability
counterexample — the stuck configs around the first un-linearizable
op — as an SVG (knossos.linear.report/render-analysis!, invoked from
jepsen/src/jepsen/checker.clj:222-229).

Here both artifacts are dependency-free: anomaly files are plain text,
cycle plots are hand-rolled SVG (circular layout) plus graphviz dot
text, and the linearizability counterexample is an SVG timeline of the
ops in flight at the stuck point, one lane per process.

Anomaly provenance: when the run carried the per-op causal trace
(optrace.jsonl, jepsen_tpu.tracing), each anomaly's participating op
indices (the `op-indices` the checkers attach) resolve into *trace
excerpts* — the client calls, remote commands, retries and fault
events behind exactly those ops — written next to the anomaly files
and linked from the web UI.
"""

from __future__ import annotations

import html
import math
from pathlib import Path

# ---------------------------------------------------------------------------
# elle anomaly artifacts
# ---------------------------------------------------------------------------


def _fmt_op(op) -> str:
    if op is None:
        return "nil"
    if hasattr(op, "to_dict"):
        op = op.to_dict()
    return repr(op)


def _fmt_record(rec) -> str:
    if isinstance(rec, dict):
        lines = []
        for k, v in rec.items():
            if k in ("op", "writer", "previous-ok"):
                lines.append(f"  {k}: {_fmt_op(v)}")
            elif k == "cycle":
                lines.append("  cycle:")
                lines.extend(f"    {_fmt_op(o)}" for o in v)
            elif k == "steps":
                lines.append("  steps:")
                lines.extend(
                    f"    T{s['from']} -{s['type']}-> T{s['to']}"
                    for s in v)
            else:
                lines.append(f"  {k}: {v!r}")
        return "\n".join(lines)
    return f"  {rec!r}"


def _fingerprint(obj) -> str:
    """Short deterministic content tag so concurrent per-key checkers
    sharing one store directory never clobber each other's artifacts
    (the checkpoint files solve the same collision the same way)."""
    import zlib

    return f"{zlib.crc32(repr(obj).encode()) & 0xffffffff:08x}"


def write_elle_artifacts(store_dir, result: dict,
                         subdir: str = "elle") -> list[str]:
    """Writes one text file per anomaly type plus cycle plots (SVG +
    dot) into <store_dir>/<subdir>/, filenames tagged with a content
    fingerprint; returns the written paths. No-op (empty list) for
    valid results."""
    anomalies = (result or {}).get("anomalies") or {}
    if not anomalies:
        return []
    out_dir = Path(store_dir) / subdir
    out_dir.mkdir(parents=True, exist_ok=True)
    # fingerprint the CONTENT (records carry op indices), not just the
    # type names — per-key checks often share the same anomaly types
    fp = _fingerprint(sorted((k, repr(v)) for k, v in anomalies.items()))
    written: list[str] = []
    for name, records in sorted(anomalies.items()):
        p = out_dir / f"{name}-{fp}.txt"
        body = [f"{name}: {len(records)} instance(s)", ""]
        for i, rec in enumerate(records):
            body.append(f"-- instance {i} " + "-" * 40)
            body.append(_fmt_record(rec))
            body.append("")
        p.write_text("\n".join(body))
        written.append(str(p))
    # cycle plots for cycle-shaped anomalies (they carry "steps")
    cyc_idx = 0
    dot_lines = ["digraph anomalies {", "  rankdir=LR;"]
    have_cycles = False
    for name, records in sorted(anomalies.items()):
        for rec in records:
            steps = rec.get("steps") if isinstance(rec, dict) else None
            if not steps:
                continue
            have_cycles = True
            svg = _cycle_svg(name, steps, rec.get("cycle"))
            p = out_dir / f"cycle-{name}-{fp}-{cyc_idx}.svg"
            p.write_text(svg)
            written.append(str(p))
            for s in steps:
                dot_lines.append(
                    f'  "T{s["from"]}" -> "T{s["to"]}"'
                    f' [label="{s["type"]}"];  /* {name} */')
            cyc_idx += 1
    if have_cycles:
        dot_lines.append("}")
        p = out_dir / f"cycles-{fp}.dot"
        p.write_text("\n".join(dot_lines))
        written.append(str(p))
    return written


# ---------------------------------------------------------------------------
# Per-anomaly trace excerpts (anomaly provenance)
# ---------------------------------------------------------------------------

_EXCERPT_RECORDS_PER_OP = 12


def trace_excerpt_lines(by_op: dict, indices) -> list[str]:
    """Text lines describing the trace records behind the given op
    (invocation) indices: for each op, its root span then every
    client/remote span and event, one compact line each
    (tracing.describe)."""
    from .. import tracing as jtracing

    lines: list[str] = []
    for i in indices:
        recs = by_op.get(i)
        if not recs:
            lines.append(f"op {i}: (no trace records)")
            continue
        lines.append(f"op {i}:")
        recs = sorted(recs, key=lambda r: (r.get("t0", 0),
                                           r.get("span", 0)))
        for rec in recs[:_EXCERPT_RECORDS_PER_OP]:
            lines.append(f"  {jtracing.describe(rec)}")
        if len(recs) > _EXCERPT_RECORDS_PER_OP:
            lines.append(f"  … {len(recs) - _EXCERPT_RECORDS_PER_OP} "
                         "more record(s)")
    return lines


def _load_by_op(store_dir, optrace):
    from .. import tracing as jtracing

    if optrace is None:
        from .. import store as jstore

        optrace = jstore.load_optrace(store_dir)
    return jtracing.by_op(optrace or [])


# node-plane context around an anomaly: events this far outside the
# ops' own window still make the excerpt (an OOM-kill 2s before the
# lost write is exactly the context the excerpt exists for)
_NODE_CONTEXT_SLACK_NS = 2_000_000_000
_NODE_CONTEXT_LIMIT = 16


def node_context_lines(noderecs, t0_ns: int, t1_ns: int,
                       slack_ns: int = _NODE_CONTEXT_SLACK_NS
                       ) -> list[str]:
    """Text lines for the node observability plane's events (tagged
    DB-log lines, probe gaps, breaker transitions — jepsen_tpu.
    nodeprobe) inside [t0-slack, t1+slack]: what the NODES were doing
    while the anomaly's ops ran. Empty when the run had no node plane
    or nothing happened in the window."""
    lo, hi = t0_ns - slack_ns, t1_ns + slack_ns
    picked = []
    for rec in noderecs or []:
        kind = rec.get("kind")
        if kind not in ("log", "gap", "breaker"):
            continue
        t = rec.get("t", 0)
        if not lo <= t <= hi:
            continue
        if kind == "log":
            desc = (f"{rec.get('class')} ({rec.get('ts')} ts): "
                    f"{str(rec.get('line'))[:140]}")
        elif kind == "gap":
            desc = f"probe gap: {rec.get('reason')}"
        else:
            desc = f"breaker -> {rec.get('state')}"
        picked.append((t, f"  t={t / 1e9:+.3f}s {rec.get('node')}: "
                          f"{desc}"))
    if not picked:
        return []
    picked.sort()
    lines = ["", f"node events in the op window ({len(picked)}; "
                 "jepsen_tpu.nodeprobe):"]
    lines.extend(line for _t, line in picked[:_NODE_CONTEXT_LIMIT])
    if len(picked) > _NODE_CONTEXT_LIMIT:
        lines.append(f"  … {len(picked) - _NODE_CONTEXT_LIMIT} "
                     "more event(s)")
    return lines


def _op_window(by_op: dict, indices) -> tuple[int, int] | None:
    """The [min t0, max t1] span of the trace records behind the given
    op indices — the anomaly's op window node context keys on."""
    t0 = t1 = None
    for i in indices:
        for rec in by_op.get(i) or []:
            a = rec.get("t0")
            b = rec.get("t1", a)
            if a is None:
                continue
            t0 = a if t0 is None else min(t0, a)
            t1 = b if t1 is None else max(t1, b if b is not None
                                          else a)
    return (t0, t1) if t0 is not None else None


def _load_noderecs(store_dir, noderecs):
    if noderecs is not None:
        return noderecs
    from .. import nodeprobe

    return nodeprobe.load_records(store_dir)


def write_trace_excerpts(store_dir, result: dict, optrace=None,
                         subdir: str = "elle",
                         noderecs=None) -> list[str]:
    """Resolves each anomaly's op-indices into a per-anomaly trace
    excerpt file (<name>-trace-<fp>.txt next to the anomaly files);
    when the run carried the node observability plane (nodes.jsonl),
    the node events inside the anomaly's op window ride along in the
    same excerpt. Returns the written paths. No-op when the run wasn't
    traced or no record carries op-indices."""
    anomalies = (result or {}).get("anomalies") or {}
    if not anomalies:
        return []
    by_op = _load_by_op(store_dir, optrace)
    if not by_op:
        return []
    noderecs = _load_noderecs(store_dir, noderecs)
    out_dir = Path(store_dir) / subdir
    fp = _fingerprint(sorted((k, repr(v)) for k, v in anomalies.items()))
    written: list[str] = []
    for name, records in sorted(anomalies.items()):
        idxs = sorted({i for rec in records if isinstance(rec, dict)
                       for i in rec.get("op-indices") or []})
        if not idxs:
            continue
        body = [f"{name}: trace excerpts for participating ops "
                f"{idxs}", ""]
        body.extend(trace_excerpt_lines(by_op, idxs))
        window = _op_window(by_op, idxs)
        if window is not None:
            body.extend(node_context_lines(noderecs, *window))
        out_dir.mkdir(parents=True, exist_ok=True)
        p = out_dir / f"{name}-trace-{fp}.txt"
        p.write_text("\n".join(body) + "\n")
        written.append(str(p))
    return written


def write_linear_trace_excerpt(store_dir, analysis: dict,
                               optrace=None) -> str | None:
    """The linearizability counterexample's trace excerpt: the stuck
    op, its predecessor, and the pending ops (analysis['op-indices'],
    attached by tpu/wgl), resolved against the per-op trace. Returns
    the path written, or None when untraced/valid."""
    idxs = (analysis or {}).get("op-indices") or []
    if not idxs or analysis.get("valid?") is not False:
        return None
    by_op = _load_by_op(store_dir, optrace)
    if not any(i in by_op for i in idxs):
        return None
    fp = _fingerprint(tuple(idxs))
    body = [f"linearizability counterexample: trace excerpts for "
            f"participating ops {sorted(idxs)}", ""]
    search = (analysis or {}).get("search")
    if isinstance(search, dict) and \
            search.get("witness-position") is not None:
        # where in the history the search got stuck (the explorer's
        # witness percentile) — localization context for the reader
        body.insert(1, "witnessed at "
                    f"{search['witness-position'] * 100:.1f}% of the "
                    f"history (entry {search.get('witness-entry')} of "
                    f"{search.get('entries')})")
    body.extend(trace_excerpt_lines(by_op, sorted(idxs)))
    window = _op_window(by_op, sorted(idxs))
    if window is not None:
        body.extend(node_context_lines(
            _load_noderecs(store_dir, None), *window))
    p = Path(store_dir) / f"linear-counterexample-trace-{fp}.txt"
    p.write_text("\n".join(body) + "\n")
    return str(p)


def _cycle_svg(name: str, steps: list[dict], cycle_ops=None) -> str:
    """A circular-layout SVG of one dependency cycle."""
    nodes = []
    for s in steps:
        for t in (s["from"], s["to"]):
            if t not in nodes:
                nodes.append(t)
    n = max(len(nodes), 1)
    R, cx, cy = 150, 260, 200
    pos = {t: (cx + R * math.cos(2 * math.pi * i / n - math.pi / 2),
               cy + R * math.sin(2 * math.pi * i / n - math.pi / 2))
           for i, t in enumerate(nodes)}
    ops_by_node = {}
    if cycle_ops:
        for s, op in zip(steps, cycle_ops):
            ops_by_node[s["from"]] = op
    parts = [
        '<svg xmlns="http://www.w3.org/2000/svg" width="520" '
        'height="420" font-family="monospace" font-size="11">',
        f'<text x="10" y="20" font-size="14">{html.escape(name)} '
        f'cycle ({len(steps)} edges)</text>',
        '<defs><marker id="arr" viewBox="0 0 10 10" refX="9" refY="5" '
        'markerWidth="7" markerHeight="7" orient="auto-start-reverse">'
        '<path d="M 0 0 L 10 5 L 0 10 z" fill="#444"/></marker></defs>',
    ]
    for s in steps:
        x1, y1 = pos[s["from"]]
        x2, y2 = pos[s["to"]]
        # shorten toward the node circle so the arrowhead shows
        dx, dy = x2 - x1, y2 - y1
        d = math.hypot(dx, dy) or 1.0
        sx, sy = x1 + dx / d * 22, y1 + dy / d * 22
        ex, ey = x2 - dx / d * 22, y2 - dy / d * 22
        parts.append(
            f'<line x1="{sx:.0f}" y1="{sy:.0f}" x2="{ex:.0f}" '
            f'y2="{ey:.0f}" stroke="#444" marker-end="url(#arr)"/>')
        mx, my = (sx + ex) / 2, (sy + ey) / 2
        parts.append(
            f'<text x="{mx:.0f}" y="{my - 4:.0f}" fill="#a00" '
            f'text-anchor="middle">{html.escape(str(s["type"]))}</text>')
    for t in nodes:
        x, y = pos[t]
        parts.append(
            f'<circle cx="{x:.0f}" cy="{y:.0f}" r="20" fill="#eef" '
            'stroke="#447"/>')
        parts.append(
            f'<text x="{x:.0f}" y="{y + 4:.0f}" '
            f'text-anchor="middle">T{t}</text>')
        op = ops_by_node.get(t)
        if op is not None:
            label = html.escape(_short_op(op))
            parts.append(
                f'<text x="{x:.0f}" y="{y + 34:.0f}" font-size="9" '
                f'text-anchor="middle">{label}</text>')
    parts.append("</svg>")
    return "\n".join(parts)


def _short_op(op, limit: int = 40) -> str:
    try:
        v = op.value if hasattr(op, "value") else op.get("value")
    except Exception:  # noqa: BLE001
        v = None
    s = repr(v)
    return s[:limit] + ("…" if len(s) > limit else "")


# ---------------------------------------------------------------------------
# linearizability counterexample
# ---------------------------------------------------------------------------


def render_linear_svg(analysis: dict, path) -> str | None:
    """Renders the stuck point of a failed linearizability check — the
    first un-linearizable op, its predecessor, and the ops pending in
    each surviving config, one lane per process — to an SVG file.
    Returns the path written, or None for valid/witness-less analyses.
    Mirrors what knossos.linear.report/render-analysis! conveys
    (checker.clj:222-229): WHAT couldn't linearize, WHEN, and what the
    model could have been."""
    if not analysis or analysis.get("valid?") is not False:
        return None
    crash_op = analysis.get("op")
    configs = analysis.get("configs") or []
    prev_ok = analysis.get("previous-ok")
    if crash_op is None and not configs:
        return None

    # collect (op, role) participants
    rows: list[tuple] = []
    if prev_ok is not None:
        rows.append((prev_ok, "previous-ok"))
    if crash_op is not None:
        rows.append((crash_op, "unlinearizable"))
    for ci, cfg in enumerate(configs):
        for op in cfg.get("pending", []):
            rows.append((op, f"pending (config {ci})"))
    seen = set()
    uniq: list[tuple] = []
    for op, role in rows:
        key = (id(op) if not hasattr(op, "index") else op.index, role)
        if key in seen:
            continue
        seen.add(key)
        uniq.append((op, role))

    def op_attr(op, name, default=None):
        if hasattr(op, name):
            return getattr(op, name)
        if isinstance(op, dict):
            return op.get(name, default)
        return default

    procs: list = []
    for op, _ in uniq:
        p = op_attr(op, "process")
        if p not in procs:
            procs.append(p)
    idxs = [op_attr(op, "index", 0) or 0 for op, _ in uniq]
    lo, hi = (min(idxs), max(idxs)) if idxs else (0, 1)
    span = max(hi - lo, 1)

    lane_h, left, width = 34, 90, 640
    height = 90 + lane_h * max(len(procs), 1) + 30 * max(len(configs), 1)
    parts = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{width + 40}" '
        f'height="{height}" font-family="monospace" font-size="11">',
        '<text x="10" y="18" font-size="14">linearizability '
        'counterexample</text>',
        f'<text x="10" y="34" fill="#666">history indices {lo}..{hi}'
        '</text>',
    ]
    colors = {"previous-ok": "#2a7", "unlinearizable": "#d22"}
    for li, p in enumerate(procs):
        y = 60 + li * lane_h
        parts.append(
            f'<text x="8" y="{y + 4}" fill="#444">proc {p}</text>')
        parts.append(
            f'<line x1="{left}" y1="{y}" x2="{width}" y2="{y}" '
            'stroke="#ddd"/>')
    for op, role in uniq:
        p = op_attr(op, "process")
        li = procs.index(p)
        y = 60 + li * lane_h
        idx = op_attr(op, "index", 0) or 0
        x = left + (idx - lo) / span * (width - left - 60)
        c = colors.get(role, "#48c")
        parts.append(
            f'<circle cx="{x:.0f}" cy="{y}" r="6" fill="{c}"/>')
        f = op_attr(op, "f")
        v = op_attr(op, "value")
        label = html.escape(f"{f} {v!r}"[:36])
        parts.append(
            f'<text x="{x + 10:.0f}" y="{y - 8}" fill="{c}">'
            f'{label}</text>')
        parts.append(
            f'<text x="{x + 10:.0f}" y="{y + 14}" font-size="9" '
            f'fill="#888">{html.escape(role)}</text>')
    y0 = 60 + len(procs) * lane_h + 16
    for ci, cfg in enumerate(configs):
        model = cfg.get("model")
        parts.append(
            f'<text x="10" y="{y0 + ci * 24}" fill="#555">config {ci}: '
            f'model={html.escape(repr(model)[:60])} '
            f'pending={len(cfg.get("pending", []))}</text>')
    if "failed-segment" in analysis:
        parts.append(
            f'<text x="10" y="{height - 10}" fill="#555">failed '
            f'segment {analysis["failed-segment"]} '
            f'(entries {analysis.get("segment-range")})</text>')
    parts.append("</svg>")
    out = Path(path)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text("\n".join(parts))
    return str(out)
