"""Rendering for stored telemetry: span trees + metric tables.

Consumes the artifacts jepsen_tpu.telemetry writes into a test's
store directory (telemetry.jsonl / metrics.json) and renders them two
ways: a plain-text span-tree summary for the CLI `telemetry`
subcommand, and an HTML page for web.py's per-test telemetry view.
Pure functions over the loaded records — no recorder access — so they
work equally on a live Telemetry.events() list and on artifacts read
back from disk.
"""

from __future__ import annotations

import html as _html


def _ms(ns: int) -> str:
    if ns >= 1_000_000_000:
        return f"{ns / 1e9:.2f}s"
    return f"{ns / 1e6:.1f}ms"


def span_tree(events) -> list[tuple[int, dict]]:
    """(depth, span) rows in tree order: roots by start time, children
    nested under their parent. Spans whose parent never completed (or
    arrived out of order) degrade to roots rather than vanishing."""
    events = [e for e in events if "t0" in e]
    by_id = {e.get("id"): e for e in events}
    children: dict = {}
    roots = []
    for e in events:
        p = e.get("parent")
        if p is not None and p in by_id:
            children.setdefault(p, []).append(e)
        else:
            roots.append(e)
    rows: list[tuple[int, dict]] = []

    def walk(e, depth):
        rows.append((depth, e))
        for c in sorted(children.get(e.get("id"), []),
                        key=lambda x: x["t0"]):
            walk(c, depth + 1)

    for r in sorted(roots, key=lambda x: x["t0"]):
        walk(r, 0)
    return rows


def filter_spans(events, min_ms: float | None = None,
                 top: int | None = None) -> list:
    """Span-volume control for deep kernel traces (per-launch records
    multiply span counts): keeps spans at least `min_ms` long and/or
    the `top` N longest, PLUS every ancestor of a kept span (so the
    phase context survives the pruning). Open spans always survive —
    they're what a live run is doing right now. No-op when neither
    filter is given."""
    if min_ms is None and top is None:
        return list(events)
    events = [e for e in events if "t0" in e]
    by_id = {e.get("id"): e for e in events}

    def dur_ms(e):
        return (e["t1"] - e["t0"]) / 1e6 if "t1" in e else None

    seeds = [e for e in events
             if dur_ms(e) is None
             or min_ms is None or dur_ms(e) >= min_ms]
    if top is not None:
        closed = sorted((e for e in seeds if dur_ms(e) is not None),
                        key=dur_ms, reverse=True)[:max(top, 0)]
        seeds = [e for e in seeds if dur_ms(e) is None] + closed
    keep = set()
    for e in seeds:
        sid = e.get("id")
        # walk ancestors; the depth bound guards a parent cycle in a
        # corrupt artifact
        for _ in range(64):
            if sid is None or sid in keep:
                break
            keep.add(sid)
            parent = by_id.get(sid)
            sid = parent.get("parent") if parent else None
    return [e for e in events if e.get("id") in keep]


def span_tree_lines(events, min_ms: float | None = None,
                    top: int | None = None) -> list[str]:
    lines = []
    for depth, e in span_tree(filter_spans(events, min_ms, top)):
        dur = _ms(e["t1"] - e["t0"]) if "t1" in e else "(open)"
        extra = ""
        if e.get("attrs"):
            extra = "  " + " ".join(f"{k}={v}" for k, v in
                                    sorted(e["attrs"].items()))
        thread = e.get("thread") or ""
        tcol = f"  [{thread}]" if depth == 0 and thread else ""
        lines.append(f"{'  ' * depth}{e.get('name', '?')}  "
                     f"{dur}{extra}{tcol}")
    return lines


def _metric_rows(metrics: dict) -> list[tuple[str, str, str]]:
    """(section, name, value) rows for counters + gauges + span
    aggregates, kernel metrics grouped first."""
    rows: list[tuple[str, str, str]] = []
    counters = (metrics or {}).get("counters", {})
    gauges = (metrics or {}).get("gauges", {})
    for name in sorted(counters):
        v = counters[name]
        shown = _ms(v) if name.endswith("_ns") else str(v)
        rows.append(("counter", name, shown))
    for name in sorted(gauges):
        rows.append(("gauge", name, str(gauges[name])))
    for name, agg in sorted((metrics or {}).get("spans", {}).items()):
        rows.append(("span", name,
                     f"x{agg['count']}  total {_ms(agg['total_ns'])}  "
                     f"max {_ms(agg['max_ns'])}"))
    return rows


def telemetry_text(events, metrics: dict | None,
                   min_ms: float | None = None,
                   top: int | None = None) -> str:
    """The CLI `telemetry` subcommand's output: span tree (optionally
    pruned by --min-ms / --top, see filter_spans), then the aggregated
    counters/gauges/span table."""
    out = ["# Spans", ""]
    lines = span_tree_lines(events, min_ms=min_ms, top=top)
    if (min_ms is not None or top is not None) and events:
        shown = len(lines)
        out.insert(1, f"(filtered: showing {shown} of "
                      f"{sum(1 for e in events if 't0' in e)} spans)")
    out.extend(lines or ["(no spans recorded)"])
    out += ["", "# Metrics", ""]
    rows = _metric_rows(metrics or {})
    if not rows:
        out.append("(no metrics recorded)")
    else:
        width = max(len(n) for _s, n, _v in rows)
        for section, name, value in rows:
            out.append(f"{section:<8} {name:<{width}}  {value}")
    return "\n".join(out)


def telemetry_html(title: str, events, metrics: dict | None) -> str:
    """The web UI's per-test telemetry page: phase/kernel breakdown as
    a nested span tree plus a metrics table."""
    tree_rows = []
    for depth, e in span_tree(events):
        dur = _ms(e["t1"] - e["t0"]) if "t1" in e else "(open)"
        name = _html.escape(str(e.get("name", "?")))
        attrs = ""
        if e.get("attrs"):
            attrs = _html.escape(
                " ".join(f"{k}={v}" for k, v in sorted(
                    e["attrs"].items())))
        tree_rows.append(
            f"<tr><td style='padding-left:{depth * 18 + 4}px'>"
            f"{name}</td><td>{dur}</td>"
            f"<td class='dim'>{attrs}</td></tr>")
    metric_rows = [
        f"<tr><td class='dim'>{_html.escape(section)}</td>"
        f"<td>{_html.escape(name)}</td>"
        f"<td>{_html.escape(value)}</td></tr>"
        for section, name, value in _metric_rows(metrics or {})]
    return (
        "<!DOCTYPE html><html><head><meta charset='utf-8'>"
        f"<title>telemetry — {_html.escape(title)}</title><style>"
        "body { font-family: sans-serif } "
        "table { border-collapse: collapse; margin-bottom: 2em } "
        "td, th { padding: 3px 10px; text-align: left; "
        "border-bottom: 1px solid #eee; font-size: 14px } "
        ".dim { color: #888 }"
        "</style></head><body>"
        f"<h1>telemetry — {_html.escape(title)}</h1>"
        "<h2>Spans</h2><table><tr><th>span</th><th>duration</th>"
        "<th>attrs</th></tr>"
        + "".join(tree_rows or ["<tr><td colspan=3>(none)</td></tr>"])
        + "</table><h2>Metrics</h2>"
        "<table><tr><th></th><th>name</th><th>value</th></tr>"
        + "".join(metric_rows
                  or ["<tr><td colspan=3>(none)</td></tr>"])
        + "</table></body></html>")
