"""Latency and rate graphs over histories.

Capability reference: jepsen/src/jepsen/checker/perf.clj — time
bucketing (22-50), quantiles (52-88), invokes-by-f-type folds
(96-140), latency point + quantile graphs and rate graphs (the rest),
nemesis activity shading from package :perf specs (with-nemeses).
The reference renders through gnuplot; we use matplotlib (Agg) and
write PNGs into the test's store directory.
"""

from __future__ import annotations

import logging
import math
from collections import defaultdict

from .. import util
from ..history import (History, is_fail, is_info, is_invoke, is_ok)

logger = logging.getLogger(__name__)

DEFAULT_NEMESIS_COLOR = "#cccccc"
NEMESIS_ALPHA = 0.6

TYPE_COLORS = {"ok": "#81BFFC", "info": "#FFA400", "fail": "#FF1E90"}
TYPE_MARKERS = {"ok": "+", "info": "x", "fail": "."}

QUANTILES = [0.5, 0.95, 0.99, 1.0]

DT = 10.0  # rate/quantile bucket width, seconds


def bucket_scale(dt: float, b: int) -> float:
    """Midpoint time of bucket b (perf.clj:22-27)."""
    return b * dt + dt / 2


def bucket_time(dt: float, t: float) -> float:
    """Midpoint time of the bucket containing t (perf.clj:29-33)."""
    return bucket_scale(dt, int(t // dt))


def bucket_points(dt: float, points) -> dict:
    """{bucket-midpoint: [point, ...]} ordered by time
    (perf.clj:42-49)."""
    out: dict = defaultdict(list)
    for p in points:
        out[bucket_time(dt, p[0])].append(p)
    return dict(sorted(out.items()))


def quantiles(qs, values) -> dict:
    """{q: value-at-quantile} (perf.clj:52-63)."""
    s = sorted(values)
    if not s:
        return {}
    n = len(s)
    return {q: s[min(n - 1, int(math.floor(n * q)))] for q in qs}


def latencies_to_quantiles(dt: float, qs, points) -> dict:
    """{q: [[bucket-time, latency-at-q], ...]} (perf.clj:65-88)."""
    assert all(0 <= q <= 1 for q in qs)
    buckets = [(t, quantiles(qs, [p[1] for p in ps]))
               for t, ps in bucket_points(dt, points).items()]
    return {q: [[t, b.get(q)] for t, b in buckets] for q in qs}


def invokes_by_f_type(history: History) -> dict:
    """{f: {type: [(invoke-op, completion-op), ...]}} for client
    invocations (perf.clj invokes-by-f-type)."""
    out: dict = defaultdict(lambda: defaultdict(list))
    for o in history:
        if not is_invoke(o):
            continue
        comp = history.completion(o)
        if comp is None:
            continue
        t = ("ok" if is_ok(comp) else
             "info" if is_info(comp) else "fail")
        out[o.f][t].append((o, comp))
    return {f: dict(ts) for f, ts in out.items()}


def _latency_points(pairs) -> list:
    """[time-s, latency-ms] per (invoke, completion) pair."""
    return [[util.nanos_to_secs(inv.time),
             (comp.time - inv.time) / 1e6] for inv, comp in pairs]


def _nemesis_specs(test) -> list:
    """Normalized perf specs from test['plot']['nemeses'] (the package
    'perf' sets, as tuples or dicts)."""
    specs = ((test.get("plot") or {}).get("nemeses")) or []
    out = []
    for s in specs:
        if isinstance(s, tuple):
            name, start, stop, color = (list(s) + [None] * 4)[:4]
            out.append({"name": name, "start": set(start or ()),
                        "stop": set(stop or ()),
                        "color": color or DEFAULT_NEMESIS_COLOR})
        else:
            out.append({"name": s.get("name"),
                        "start": set(s.get("start") or ()),
                        "stop": set(s.get("stop") or ()),
                        "fs": set(s.get("fs") or ()),
                        "color": s.get("color",
                                       DEFAULT_NEMESIS_COLOR)})
    return out


def _shade_nemeses(ax, test, history) -> None:
    """Shades nemesis activity intervals (perf.clj with-nemeses)."""
    specs = _nemesis_specs(test)
    if not specs:
        specs = [{"name": "nemesis", "start": {"start"},
                  "stop": {"stop"}, "color": DEFAULT_NEMESIS_COLOR}]
    tmax = (util.nanos_to_secs(history[-1].time) if len(history) else 0)
    for spec in specs:
        ints = util.nemesis_intervals(
            history, [{"start": spec["start"], "stop": spec["stop"]}])
        for start, stop in ints:
            x0 = util.nanos_to_secs(start.time)
            x1 = (util.nanos_to_secs(stop.time) if stop is not None
                  else tmax)
            ax.axvspan(x0, x1, color=spec["color"],
                       alpha=1 - NEMESIS_ALPHA, lw=0, zorder=0)


def _figure():
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(9, 5), dpi=110)
    ax.set_xlabel("Time (s)")
    ax.grid(True, which="both", alpha=0.25)
    return plt, fig, ax


def _save(plt, fig, test, opts, filename):
    from .. import store as jstore

    sub = (opts or {}).get("subdirectory")
    parts = ([sub, filename] if sub else [filename])
    out = jstore.path(test, *parts)
    out.parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(out, bbox_inches="tight")
    plt.close(fig)
    return str(out)


def point_graph(test, history: History, opts=None) -> dict:
    """Raw latency scatter, colored by f x completion type, log-scale ms
    (perf.clj point-graph!). Writes latency-raw.png."""
    history = history.client_ops()
    by_ft = invokes_by_f_type(history)
    if not by_ft:
        return {"valid?": True}
    plt, fig, ax = _figure()
    ax.set_ylabel("Latency (ms)")
    ax.set_yscale("log")
    ax.set_title(f"{test.get('name') or 'test'} latency (raw)")
    fs = sorted(by_ft, key=str)
    for f in fs:
        for t, pairs in sorted(by_ft[f].items()):
            pts = _latency_points(pairs)
            if not pts:
                continue
            ax.scatter([p[0] for p in pts], [p[1] for p in pts],
                       s=14, marker=TYPE_MARKERS[t],
                       color=TYPE_COLORS[t],
                       alpha=0.8 if len(pts) < 5000 else 0.3,
                       label=f"{f} {t}", zorder=2)
    _shade_nemeses(ax, test, history)
    ax.legend(loc="upper right", fontsize=7, ncol=max(1, len(fs)))
    path = _save(plt, fig, test, opts, "latency-raw.png")
    return {"valid?": True, "file": path}


def quantile_graph(test, history: History, opts=None) -> dict:
    """Latency quantiles (0.5/0.95/0.99/1.0) over time windows
    (perf.clj quantile-graph!). Writes latency-quantiles.png."""
    history = history.client_ops()
    pairs = [(o, history.completion(o)) for o in history
             if is_invoke(o)]
    pairs = [(i, c) for i, c in pairs if c is not None]
    if not pairs:
        return {"valid?": True}
    pts = _latency_points(pairs)
    dt = (opts or {}).get("dt", DT)
    qmaps = latencies_to_quantiles(dt, QUANTILES, pts)
    plt, fig, ax = _figure()
    ax.set_ylabel("Latency (ms)")
    ax.set_yscale("log")
    ax.set_title(f"{test.get('name') or 'test'} latency (quantiles)")
    for q in QUANTILES:
        series = [(t, v) for t, v in qmaps[q] if v is not None]
        ax.plot([t for t, _ in series], [v for _, v in series],
                marker="o", ms=3, lw=1.2, label=f"q={q}", zorder=2)
    _shade_nemeses(ax, test, history)
    ax.legend(loc="upper right", fontsize=8)
    path = _save(plt, fig, test, opts, "latency-quantiles.png")
    return {"valid?": True, "file": path}


def rate_preview(test, history: History, opts=None) -> dict:
    """Throughput (ops/s) per f x type in DT-second buckets
    (perf.clj rate-graph!). Writes rate.png."""
    history = history.client_ops()
    dt = (opts or {}).get("dt", DT)
    rates: dict = defaultdict(lambda: defaultdict(float))
    fs = set()
    for o in history:
        if is_invoke(o):
            continue
        t = ("ok" if is_ok(o) else "info" if is_info(o) else "fail")
        b = bucket_time(dt, util.nanos_to_secs(o.time))
        rates[(o.f, t)][b] += 1 / dt
        fs.add(o.f)
    if not rates:
        return {"valid?": True}
    plt, fig, ax = _figure()
    ax.set_ylabel("Throughput (ops/s)")
    ax.set_title(f"{test.get('name') or 'test'} rate")
    for (f, t), buckets in sorted(rates.items(), key=str):
        series = sorted(buckets.items())
        ax.plot([x for x, _ in series], [y for _, y in series],
                marker="o", ms=3, lw=1.2, color=TYPE_COLORS[t],
                alpha={"ok": 1.0, "info": 0.6, "fail": 0.4}[t],
                label=f"{f} {t}", zorder=2)
    _shade_nemeses(ax, test, history)
    ax.legend(loc="upper right", fontsize=7)
    path = _save(plt, fig, test, opts, "rate.png")
    return {"valid?": True, "file": path}


def monitor_preview(test, history: History, opts=None) -> dict:
    """The live monitor's time-series as a post-hoc plot: throughput
    (ops/s) on the left axis, in-flight op count on the right, with
    the same nemesis shading as the latency/rate graphs so fault
    windows line up across all of them. Writes monitor.png. Reads the
    points the sampler streamed (timeseries.jsonl) — the run's live
    view, preserved."""
    from .. import store as jstore

    d = test.get("store_dir")
    if not d:
        return {"valid?": True}
    points = jstore.load_timeseries(d)
    series = [(util.nanos_to_secs(p["t"]), p.get("ops_s"),
               len(p.get("inflight") or {}))
              for p in points if "t" in p]
    series = [(t, r, infl) for t, r, infl in series if r is not None]
    if not series:
        return {"valid?": True}
    plt, fig, ax = _figure()
    ax.set_ylabel("Throughput (ops/s)")
    ax.set_title(f"{test.get('name') or 'test'} live monitor")
    ax.plot([t for t, _, _ in series], [r for _, r, _ in series],
            marker="o", ms=3, lw=1.2, color=TYPE_COLORS["ok"],
            label="ops/s", zorder=2)
    ax2 = ax.twinx()
    ax2.set_ylabel("In-flight ops")
    ax2.step([t for t, _, _ in series], [i for _, _, i in series],
             where="post", lw=1.0, color=TYPE_COLORS["info"],
             alpha=0.8, label="in-flight", zorder=2)
    _shade_nemeses(ax, test, history)
    h1, l1 = ax.get_legend_handles_labels()
    h2, l2 = ax2.get_legend_handles_labels()
    ax.legend(h1 + h2, l1 + l2, loc="upper right", fontsize=8)
    path = _save(plt, fig, test, opts, "monitor.png")
    return {"valid?": True, "file": path, "points": len(series)}


def balances_preview(test, history: History, opts=None) -> dict:
    """Per-account balance over time from the bank workload's ok reads
    (the bank.clj:150-176 plot analog: one line per account, every
    read a sample point), with the shared nemesis shading so a balance
    excursion lines up with the fault window that caused it. Writes
    bank-balances.png."""
    history = history.client_ops()
    series: dict = defaultdict(list)  # account -> [(t, balance)]
    for o in history:
        if is_ok(o) and o.f == "read" and isinstance(o.value, dict):
            t = util.nanos_to_secs(o.time)
            for acct, bal in o.value.items():
                series[acct].append((t, bal))
    if not series:
        return {"valid?": True}
    plt, fig, ax = _figure()
    ax.set_ylabel("Balance")
    ax.set_title(f"{test.get('name') or 'test'} account balances")
    for acct in sorted(series, key=str):
        pts = series[acct]
        ax.plot([t for t, _ in pts], [b for _, b in pts],
                lw=1.0, alpha=0.8, label=f"acct {acct}", zorder=2)
    ax.axhline(0, color="#888", lw=0.8, ls="--", zorder=1)
    _shade_nemeses(ax, test, history)
    ax.legend(loc="upper right", fontsize=7,
              ncol=max(1, len(series) // 8 + 1))
    path = _save(plt, fig, test, opts, "bank-balances.png")
    return {"valid?": True, "file": path,
            "accounts": len(series)}


def balance_graph(graph_opts=None):
    """Checker rendering the bank balance-over-time plot (the
    jepsen/tests/bank.clj plot bundle entry)."""
    from ..checker import _Fn

    def run(test, history, opts):
        if not _plottable(test):
            return {"valid?": True, "skipped": "no store directory"}
        o = {**(graph_opts or {}), **(opts or {})}
        r = balances_preview(test, history, o)
        return {"valid?": True,
                "files": [p for p in [r.get("file")] if p]}

    return _Fn(run)


def _plottable(test) -> bool:
    """Plots need a store directory to land in."""
    return bool(test.get("store_dir") or test.get("name"))


def latency_graph(graph_opts=None):
    """Checker rendering latency-raw + latency-quantiles
    (checker.clj latency-graph)."""
    from ..checker import _Fn

    def run(test, history, opts):
        if not _plottable(test):
            return {"valid?": True, "skipped": "no store directory"}
        o = {**(graph_opts or {}), **(opts or {})}
        raw = point_graph(test, history, o)
        q = quantile_graph(test, history, o)
        return {"valid?": True,
                "files": [p for p in [raw.get("file"), q.get("file")]
                          if p]}

    return _Fn(run)


def rate_graph(graph_opts=None):
    """Checker rendering the rate graph (checker.clj rate-graph)."""
    from ..checker import _Fn

    def run(test, history, opts):
        if not _plottable(test):
            return {"valid?": True, "skipped": "no store directory"}
        o = {**(graph_opts or {}), **(opts or {})}
        r = rate_preview(test, history, o)
        return {"valid?": True,
                "files": [p for p in [r.get("file")] if p]}

    return _Fn(run)


def monitor_graph(graph_opts=None):
    """Checker rendering the live-monitor throughput/in-flight plot
    (no reference analog — the series only exists because the monitor
    sampled it)."""
    from ..checker import _Fn

    def run(test, history, opts):
        if not _plottable(test):
            return {"valid?": True, "skipped": "no store directory"}
        o = {**(graph_opts or {}), **(opts or {})}
        r = monitor_preview(test, history, o)
        return {"valid?": True,
                "files": [p for p in [r.get("file")] if p]}

    return _Fn(run)
