"""Node observability plane renderers: per-node summaries, the CLI
table, and the web run page's per-node lanes.

The lanes are the correlation view the node plane exists for: one
strip per DB node on the run's shared clock — CPU utilization shading,
tagged DB-log event ticks, honest gap ticks where the node couldn't be
probed — under the nemesis fault windows the coverage record captured,
so "the election fired two seconds into the partition, on the node
whose memory was vanishing" is one glance, not three files. See
jepsen_tpu.nodeprobe and doc/observability.md.
"""

from __future__ import annotations

import html as _html
from typing import Iterable

from .. import nodeprobe


# ---------------------------------------------------------------------------
# Summaries
# ---------------------------------------------------------------------------

def summarize(records: Iterable[dict]) -> dict[str, dict]:
    """{node: summary} over nodes.jsonl records: sample/gap/log
    counts, resource extremes, the clock-offset range, and the last
    breaker state seen."""
    out: dict[str, dict] = {}
    for rec in records or []:
        node = str(rec.get("node"))
        s = out.setdefault(node, {
            "samples": 0, "gaps": {}, "logs": {},
            "cpu_max": None, "mem_used_max": None,
            "offset_min": None, "offset_max": None,
            "breaker": None, "t_last": 0})
        s["t_last"] = max(s["t_last"], rec.get("t", 0))
        kind = rec.get("kind")
        if kind == "sample":
            s["samples"] += 1
            busy = (rec.get("cpu") or {}).get("busy")
            if busy is not None:
                s["cpu_max"] = max(s["cpu_max"] or 0.0, busy)
            used = (rec.get("mem") or {}).get("used_frac")
            if used is not None:
                s["mem_used_max"] = max(s["mem_used_max"] or 0.0, used)
            off = rec.get("clock_offset_s")
            if off is not None:
                s["offset_min"] = (off if s["offset_min"] is None
                                   else min(s["offset_min"], off))
                s["offset_max"] = (off if s["offset_max"] is None
                                   else max(s["offset_max"], off))
        elif kind == "gap":
            r = str(rec.get("reason"))
            s["gaps"][r] = s["gaps"].get(r, 0) + 1
        elif kind == "log":
            c = str(rec.get("class"))
            s["logs"][c] = s["logs"].get(c, 0) + 1
        elif kind == "breaker":
            s["breaker"] = rec.get("state")
    return out


def nodes_text(records, history=None) -> str:
    """The `nodes` CLI body: one row per node plus the merged
    clock-skew bound (probe offsets + the history's check-offsets
    observations)."""
    summaries = summarize(records)
    if not summaries:
        return ("(no node-plane records — run with nodeprobe enabled, "
                "e.g. `python -m jepsen_tpu test --no-ssh`)")
    lines = [f"{'node':<10} {'samples':>7} {'gaps':>5} {'cpu max':>8} "
             f"{'mem max':>8} {'offset range (s)':>20}  log events"]
    lines.append("-" * len(lines[0]))
    for node in sorted(summaries):
        s = summaries[node]
        gaps = sum(s["gaps"].values())
        cpu = f"{s['cpu_max']:.0%}" if s["cpu_max"] is not None else "-"
        mem = (f"{s['mem_used_max']:.0%}"
               if s["mem_used_max"] is not None else "-")
        if s["offset_min"] is not None:
            off = f"{s['offset_min']:+.3f}..{s['offset_max']:+.3f}"
        else:
            off = "-"
        logs = ", ".join(f"{c}×{n}" for c, n in sorted(
            s["logs"].items())) or "-"
        badge = f" [{s['breaker']}]" if s["breaker"] not in (
            None, "closed") else ""
        lines.append(f"{node:<10} {s['samples']:>7} {gaps:>5} "
                     f"{cpu:>8} {mem:>8} {off:>20}  {logs}{badge}")
    bound = nodeprobe.clock_skew_bound(records, history)
    lines.append("")
    if bound is not None:
        lines.append(f"clock-skew-bound: {bound:.6f}s (worst |offset| "
                     "across probe + check-offsets series — stamped "
                     "on realtime verdicts)")
    else:
        lines.append("clock-skew-bound: (no clock observations)")
    gaps_total = sum(sum(s["gaps"].values())
                     for s in summaries.values())
    if gaps_total:
        lines.append(f"gap markers: {gaps_total} (missing samples are "
                     "recorded, never interpolated)")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Web lanes
# ---------------------------------------------------------------------------

_LANE_W = 640
_LANE_H = 18
_LEFT = 80
_MAX_LANE_SAMPLES = 400  # per node; ~550px of lane can't show more

_CLASS_COLOR = {"panic-assert": "#d22", "oom-kill": "#b36",
                "corruption": "#80d", "election": "#26c",
                "restart": "#2a7"}


def _x(t: int, t_max: int) -> float:
    return _LEFT + (t / t_max) * (_LANE_W - _LEFT - 10)


def lanes_html(records, faults=None, bound=None) -> str:
    """The per-node lanes section: an SVG per run with one lane per
    node (CPU shading, log-event ticks, gap ticks) under the nemesis
    fault windows (the coverage record's `faults` list). `bound`
    overrides the skew caption with the verdict's stamped merged
    bound (probe + check-offsets); without it the caption falls back
    to the probe series alone. Empty string when there are no
    records."""
    records = list(records or [])
    if not records:
        return ""
    by_node: dict[str, list] = {}
    t_max = 1
    for rec in records:
        by_node.setdefault(str(rec.get("node")), []).append(rec)
        t_max = max(t_max, rec.get("t", 0))
    windows = []
    for f in faults or []:
        for w in f.get("windows") or []:
            t0 = w[0]
            t1 = w[1] if w[1] is not None else t_max
            t_max = max(t_max, t1)
            windows.append((str(f.get("kind")), t0, t1))
    summaries = summarize(records)
    nodes = sorted(by_node)
    head_h = 16 if windows else 4
    height = head_h + len(nodes) * (_LANE_H + 6) + 8
    parts = [f"<svg xmlns='http://www.w3.org/2000/svg' "
             f"width='{_LANE_W}' height='{height}' "
             f"font-family='monospace' font-size='10'>"]
    # nemesis fault windows span every lane (the coverage record is
    # the authority on what was injected when)
    for kind, t0, t1 in windows:
        x0, x1 = _x(t0, t_max), _x(t1, t_max)
        parts.append(
            f"<rect x='{x0:.0f}' y='{head_h}' "
            f"width='{max(x1 - x0, 2):.0f}' "
            f"height='{height - head_h - 4}' fill='#FEB5DA' "
            f"fill-opacity='0.35'><title>{_html.escape(kind)} "
            f"window</title></rect>")
        parts.append(f"<text x='{x0:.0f}' y='{head_h - 4}' "
                     f"fill='#b36'>{_html.escape(kind)}</text>")
    for i, node in enumerate(nodes):
        y = head_h + i * (_LANE_H + 6) + 4
        badge = summaries.get(node, {}).get("breaker")
        label = node + (f" [{badge}]" if badge not in (None, "closed")
                        else "")
        parts.append(f"<text x='4' y='{y + 12}' fill='#444'>"
                     f"{_html.escape(label)}</text>")
        parts.append(f"<rect x='{_LEFT}' y='{y}' "
                     f"width='{_LANE_W - _LEFT - 10}' "
                     f"height='{_LANE_H}' fill='#f6f6f6'/>")
        recs = sorted(by_node[node], key=lambda r: r.get("t", 0))
        # bound the SVG: the lane is ~550px wide, so beyond ~400
        # samples extra rects only bloat the page. Stride-sample the
        # resource strip; event/gap/breaker ticks are never dropped.
        samples = [r for r in recs if r.get("kind") == "sample"]
        if len(samples) > _MAX_LANE_SAMPLES:
            stride = -(-len(samples) // _MAX_LANE_SAMPLES)
            keep = set(map(id, samples[::stride]))
            recs = [r for r in recs if r.get("kind") != "sample"
                    or id(r) in keep]
        prev_x = None
        for rec in recs:
            x = _x(rec.get("t", 0), t_max)
            kind = rec.get("kind")
            if kind == "sample":
                busy = (rec.get("cpu") or {}).get("busy")
                if busy is not None and prev_x is not None:
                    # cpu strip: the segment since the previous sample,
                    # shaded by utilization
                    shade = int(230 - 170 * min(busy, 1.0))
                    parts.append(
                        f"<rect x='{prev_x:.0f}' y='{y}' "
                        f"width='{max(x - prev_x, 1):.0f}' "
                        f"height='{_LANE_H}' "
                        f"fill='rgb({shade},{shade},255)'>"
                        f"<title>{_html.escape(node)} cpu "
                        f"{busy:.0%}</title></rect>")
                prev_x = x
            elif kind == "gap":
                # an honest gap tick: the probe could NOT see this
                # node here (no interpolation)
                parts.append(
                    f"<rect x='{x:.0f}' y='{y}' width='3' "
                    f"height='{_LANE_H}' fill='#999'>"
                    f"<title>gap: {_html.escape(str(rec.get('reason')))}"
                    f"</title></rect>")
                prev_x = None  # never shade across a gap
            elif kind == "log":
                cls = str(rec.get("class"))
                color = _CLASS_COLOR.get(cls, "#222")
                title = _html.escape(
                    f"{cls}: {str(rec.get('line'))[:120]}")
                parts.append(
                    f"<rect x='{x:.0f}' y='{y - 2}' width='2' "
                    f"height='{_LANE_H + 4}' fill='{color}'>"
                    f"<title>{title}</title></rect>")
            elif kind == "breaker":
                parts.append(
                    f"<rect x='{x:.0f}' y='{y - 2}' width='2' "
                    f"height='{_LANE_H + 4}' fill='#FFAA26'>"
                    f"<title>breaker → "
                    f"{_html.escape(str(rec.get('state')))}"
                    f"</title></rect>")
    parts.append("</svg>")
    legend = ("<p><small>lanes: blue shading = CPU busy, colored "
              "ticks = tagged DB-log events ("
              + ", ".join(f"<span style='color:{c}'>{cls}</span>"
                          for cls, c in _CLASS_COLOR.items())
              + "), gray = probe gap (node unreachable/quarantined — "
                "never interpolated), pink bands = nemesis fault "
                "windows</small></p>")
    if bound is None:
        bound = nodeprobe.clock_skew_bound(records)
    skew = (f"<p><small>clock-skew-bound: {bound:.6f}s "
            "(probe + check-offsets merged series)</small></p>"
            if bound is not None else "")
    return ("<h2>nodes</h2>" + "".join(parts) + legend + skew)
