"""Web UI over the store: browse tests, results, and artifacts.

Capability reference: jepsen/src/jepsen/web.clj — home page scanning
the store with cheap header reads (51-112), per-test file browser with
a path-traversal guard (288-388), zip download of a test directory
(340-381), app routes '/' and '/files/' (431-446).

Beyond the reference: a `/telemetry/<run>` span/metrics page, a
`/live/<run>` dashboard that streams an *in-progress* run over
Server-Sent Events by tailing the live monitor's timeseries.jsonl
(jepsen_tpu.monitor flushes each point, so the server — typically a
separate process from the test — sees them as they land; `/live/`
with no run path follows the store's `current` symlink), and a
`/trace/<run>?ops=...` endpoint serving the Chrome-trace/Perfetto
JSON, optionally pre-filtered to an anomaly's participating ops — the
run page lists each anomaly with such drill-down links (anomaly
provenance, jepsen_tpu.tracing).
"""

from __future__ import annotations

import html as _html
import io
import json
import logging
import os
import threading
import time
import zipfile
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from urllib.parse import parse_qs, unquote, urlsplit

from . import store as jstore

logger = logging.getLogger(__name__)

# SSE tail tuning: poll cadence for new points, idle heartbeat, and a
# hard cap so an abandoned client can't pin a thread forever.
SSE_POLL_S = 0.25
SSE_HEARTBEAT_S = 10.0
SSE_MAX_S = 6 * 3600.0
# the /fleet live charts poll the fleet server itself, so they tick
# slower than the file-tail cadence above
FLEET_SSE_POLL_S = 2.0


def fast_tests(base: Path | None = None) -> list:
    """Cheap per-test summaries for the home page (web.clj:51-112):
    reads only results.json, never the history. `flags` surfaces the
    run's robustness story: 'degraded' (nodes were quarantined),
    'resumed' (results come from offline `analyze`), 'recoverable' (no
    results but an op log survives — `analyze --resume` can finish the
    job; doc/robustness.md). A run whose log is still being written
    (quiet for < RECOVERABLE_QUIET_S) is live, not crashed, and is
    not flagged."""
    out = []
    for td in jstore.tests(base=base):
        res = None
        try:
            res = jstore.load_results(td)
        except (OSError, json.JSONDecodeError):
            pass
        flags = []
        if isinstance(res, dict):
            if res.get("degraded"):
                flags.append("degraded")
            if (res.get("analysis") or {}).get("offline?"):
                flags.append("resumed")
        elif _looks_recoverable(td):
            flags.append("recoverable")
        out.append({"name": td.parent.name, "time": td.name,
                    "dir": td, "flags": flags,
                    "valid": (res or {}).get("valid?", "incomplete")})
    return out


# a resultless run whose store went quiet this long is crashed, not
# live — only then does the home page advertise `analyze --resume`
RECOVERABLE_QUIET_S = 60.0


def _run_pid_alive(td: Path) -> bool:
    """True if the run's recorded control process still exists — a
    live run, however quiet (a single checker can compute for minutes
    without touching any file). Pid reuse can only make a CRASHED run
    look live (missed flag), never a live run look crashed."""
    try:
        pid = int((td / "run.pid").read_text().strip())
    except (OSError, ValueError):
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, different owner
    except OSError:
        return False
    return True


def _looks_recoverable(td: Path) -> bool:
    if not (td / "history.jlog").exists():
        return False  # nothing to recover
    if _run_pid_alive(td):
        return False  # still running, just quiet
    # For runs without a pid marker (older stores), fall back to
    # quietness over EVERY artifact a live run keeps writing: the op
    # log goes quiet when the op phase ends, but analysis still logs
    # (jepsen.log) and streams partial results — a >60s-analysis run
    # must not be advertised as crashed
    last = 0.0
    for name in ("history.jlog", "jepsen.log", "results.partial.jlog",
                 "telemetry.jsonl", "timeseries.jsonl"):
        try:
            last = max(last, (td / name).stat().st_mtime)
        except OSError:
            pass
    return time.time() - last > RECOVERABLE_QUIET_S


def _valid_color(valid) -> str:
    return {True: "#6DB6FE", False: "#FEB5DA",
            "unknown": "#FFAA26"}.get(valid, "#eeeeee")


# coverage heatmap cell colors: witnessed shares the invalid pink
# (an anomaly was found), clean the valid blue, indeterminate the
# unknown orange; never-exercised gaps stay blank
_STATUS_COLOR = {"witnessed": "#FEB5DA", "clean": "#6DB6FE",
                 "unknown": "#FFAA26", "gap": "#f4f4f4"}


def _atlas_cells(base: Path):
    from . import coverage as jcoverage

    entries = jcoverage.read_atlas(Path(base) / jcoverage.ATLAS_FILE)
    return jcoverage.aggregate(entries)


def coverage_html(cells, all_workloads=None) -> str:
    """The /coverage/ heatmap: fault kinds × workloads, each cell
    colored by its folded status and deep-linking to the cell detail
    page (witnessing runs + anomaly classes)."""
    from . import coverage as jcoverage

    faults, wls = jcoverage._axes(cells, all_workloads)
    head = "".join(
        f"<th><div>{_html.escape(k)}</div></th>" for k in faults)
    rows = []
    for w in wls:
        tds = []
        for k in faults:
            st = jcoverage.cell_status(cells, k, w)
            runs = sum(c["runs"] for (ck, cw, _a), c in cells.items()
                       if ck == k and cw == w)
            label = {"witnessed": "X", "clean": "o",
                     "unknown": "?", "gap": ""}[st]
            title = _html.escape(f"{k} × {w}: {st}, {runs} cell-runs")
            tds.append(
                f"<td style='background:{_STATUS_COLOR[st]}' "
                f"title='{title}'>"
                f"<a href='/coverage/{_html.escape(k)}/"
                f"{_html.escape(w)}'>{label or '&nbsp;'}</a></td>")
        rows.append(f"<tr><td class='wl'>{_html.escape(w)}</td>"
                    + "".join(tds) + "</tr>")
    return ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
            "<title>coverage atlas</title><style>"
            "body { font-family: sans-serif } "
            "table { border-collapse: collapse } "
            "td, th { padding: 3px 6px; border: 1px solid #fff; "
            "font-size: 12px; text-align: center } "
            "td.wl { text-align: left } "
            "th div { writing-mode: vertical-rl; "
            "transform: rotate(180deg); } "
            "td a { color: inherit; text-decoration: none; "
            "display: block }"
            "</style></head><body><h1>coverage atlas</h1>"
            "<p>fault kind × workload; X = anomaly witnessed, "
            "o = checked clean, ? = indeterminate, blank = never "
            "exercised. Cells link to witnessing runs.</p>"
            "<table><tr><th>workload</th>" + head + "</tr>"
            + "".join(rows) + "</table>"
            "<p><a href='/'>home</a></p></body></html>")


def coverage_cell_html(cells, fault: str, workload: str) -> str:
    """One cell's drill-down: per-anomaly-class outcomes with links to
    the witnessing runs (whose pages carry the anomaly excerpts and
    pre-filtered trace views)."""
    rows = []
    for (k, w, cls), c in sorted(cells.items()):
        if k != fault or w != workload:
            continue
        links = " ".join(
            f"<a href='/files/{_html.escape(r)}/'>{_html.escape(r)}"
            "</a>" for r in c["witnesses"][:8])
        frac = c.get("earliest-witness-frac")
        at = (f"{frac * 100:.0f}%"
              if isinstance(frac, (int, float)) else "-")
        rows.append(
            "<tr>"
            f"<td>{_html.escape(cls)}</td><td>{c['runs']}</td>"
            f"<td>{c['witnessed']}</td><td>{c['clean']}</td>"
            f"<td>{c['unknown']}</td><td>{at}</td>"
            f"<td>{links}</td></tr>")
    body = ("<table><tr><th>anomaly class</th><th>runs</th>"
            "<th>witnessed</th><th>clean</th><th>unknown</th>"
            "<th>earliest witness</th>"
            "<th>witnessing runs</th></tr>" + "".join(rows)
            + "</table>") if rows else \
        "<p>never exercised — a coverage gap.</p>"
    return ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
            f"<title>{_html.escape(fault)} × "
            f"{_html.escape(workload)}</title><style>"
            "body { font-family: sans-serif } "
            "table { border-collapse: collapse } "
            "td, th { padding: 3px 10px; text-align: left; "
            "border-bottom: 1px solid #eee; font-size: 13px }"
            "</style></head><body>"
            f"<h1>{_html.escape(fault)} × {_html.escape(workload)}"
            "</h1>" + body
            + "<p><a href='/coverage/'>atlas</a></p></body></html>")


def home_html(base: Path | None = None) -> str:
    rows = []
    for t in fast_tests(base):
        rel = f"{t['name']}/{t['time']}"
        rows.append(
            f"<tr style='background:{_valid_color(t['valid'])}'>"
            f"<td>{_html.escape(t['name'])}</td>"
            f"<td><a href='/files/{_html.escape(rel)}/'>"
            f"{_html.escape(t['time'])}</a></td>"
            f"<td>{_html.escape(str(t['valid']))}"
            + (f" <small>[{_html.escape(', '.join(t['flags']))}]"
               f"</small>" if t["flags"] else "") + "</td>"
            f"<td><a href='/files/{_html.escape(rel)}/results.json'>"
            f"results</a></td>"
            f"<td><a href='/files/{_html.escape(rel)}/jepsen.log'>log"
            f"</a></td>"
            f"<td><a href='/telemetry/{_html.escape(rel)}'>telemetry"
            f"</a></td>"
            f"<td><a href='/live/{_html.escape(rel)}'>live</a></td>"
            f"<td><a href='/zip/{_html.escape(rel)}'>zip</a></td>"
            f"</tr>")
    return ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
            "<title>Jepsen</title><style>"
            "body { font-family: sans-serif } "
            "table { border-collapse: collapse } "
            "td, th { padding: 4px 10px; text-align: left }"
            "</style></head><body><h1>Jepsen</h1>"
            "<p><a href='/coverage/'>coverage atlas</a> · "
            "<a href='/lint'>graftlint</a> · "
            "<a href='/fleet'>fleet</a></p><table>"
            "<tr><th>Test</th><th>Time</th><th>Valid?</th>"
            "<th colspan=5>Artifacts</th></tr>"
            + "".join(rows) + "</table></body></html>")


def _fleet_stats(base: Path):
    """(stats, addr) of the fleet server advertised under
    <base>/fleet/fleet.addr, or (None, reason)."""
    addr_file = Path(base or "store") / "fleet" / "fleet.addr"
    try:
        addr = addr_file.read_text().splitlines()[0].strip()
    except (OSError, IndexError):
        return None, "no fleet server running (no fleet.addr)"
    try:
        from .fleet.client import FleetClient

        from .control.retry import RetryBudget

        # one short attempt, no retries: a stale fleet.addr pointing
        # at a hung host must not stall every /metrics scrape
        c = FleetClient(addr, "web", "status", io_timeout_s=3.0,
                        observe=True, connect_timeout_s=1.5,
                        budget=RetryBudget(0))
        st = c.status()
        c.close()
        return st, addr
    except Exception as e:  # noqa: BLE001 — stale addr file etc.
        return None, f"fleet at {addr} unreachable: {e}"


def fleet_event_payload(st: dict) -> dict:
    """One SSE sample for the /fleet page's live charts: the flight
    recorder's headline latency quantiles, per-class batch occupancy,
    and the decision-log counts (jepsen_tpu.fleet.flightrec)."""
    fr = (st or {}).get("flightrec") or {}
    out: dict = {"enabled": bool(fr.get("enabled"))}
    if not out["enabled"]:
        return out
    for key in ("verdict_ms", "ack_ms"):
        d = fr.get(key) or {}
        out[key] = {q: d.get(q) for q in ("p50", "p99")}
    out["occupancy"] = {c: (v or {}).get("occupancy")
                        for c, v in (fr.get("classes") or {}).items()}
    out["launches"] = fr.get("launches", 0)
    out["decisions"] = fr.get("decisions") or {}
    return out


# the /fleet page's live section: latency sparkline + occupancy
# timeline fed by the SSE endpoint (/fleet?events=1)
_FLEET_LIVE_JS = (
    "<h3>live</h3>"
    "<p>verdict p99 ms <canvas id='lat' width='360' height='48'>"
    "</canvas> &nbsp; batch occupancy <canvas id='occ' width='360'"
    " height='48'></canvas></p>"
    "<script>\n"
    "var lat = [], occS = [], occF = [];\n"
    "function draw(cv, series, max) {\n"
    "  var c = cv.getContext('2d'), w = cv.width, h = cv.height;\n"
    "  c.clearRect(0, 0, w, h);\n"
    "  series.forEach(function (s) {\n"
    "    if (!s.pts.length) return;\n"
    "    c.strokeStyle = s.color; c.beginPath();\n"
    "    s.pts.forEach(function (v, i) {\n"
    "      var x = i * w / Math.max(s.pts.length - 1, 1);\n"
    "      var y = h - 2 - (h - 4) * Math.min(v / max, 1);\n"
    "      i ? c.lineTo(x, y) : c.moveTo(x, y);\n"
    "    });\n"
    "    c.stroke();\n"
    "  });\n"
    "}\n"
    "var es = new EventSource('/fleet?events=1');\n"
    "es.onmessage = function (m) {\n"
    "  var d = JSON.parse(m.data);\n"
    "  if (!d.enabled) return;\n"
    "  if (d.verdict_ms && d.verdict_ms.p99 != null)\n"
    "    lat.push(d.verdict_ms.p99);\n"
    "  occS.push((d.occupancy && d.occupancy['slice']) || 0);\n"
    "  occF.push((d.occupancy && d.occupancy['final']) || 0);\n"
    "  [lat, occS, occF].forEach(function (a) {\n"
    "    while (a.length > 120) a.shift(); });\n"
    "  draw(document.getElementById('lat'),\n"
    "       [{pts: lat, color: '#1668dc'}],\n"
    "       Math.max.apply(null, lat.concat([1])));\n"
    "  draw(document.getElementById('occ'),\n"
    "       [{pts: occS, color: '#2aa198'},\n"
    "        {pts: occF, color: '#d33682'}], 1);\n"
    "};\n"
    "</script>")


def _flightrec_html(fr: dict) -> str:
    """The /fleet page's flight-recorder section (doc/fleet.md, 'The
    fleet flight recorder')."""
    if not fr.get("enabled"):
        return ("<h2>flight recorder</h2><p><em>disabled "
                "(FleetServer(flightrec=False))</em></p>")

    def cell(d, q):
        v = (d or {}).get(q)
        return "–" if v is None else f"{v:g}"

    def qrow(label, d):
        return (f"<tr><td>{label}</td>"
                + "".join(f"<td>{cell(d, q)}</td>"
                          for q in ("p50", "p95", "p99"))
                + f"<td>{(d or {}).get('n', 0)}</td></tr>")

    tenant_rows = "".join(
        f"<tr><td>{_html.escape(t)}</td>"
        f"<td>{cell(v.get('verdict_ms'), 'p50')}</td>"
        f"<td>{cell(v.get('verdict_ms'), 'p99')}</td>"
        f"<td>{cell(v.get('ack_ms'), 'p99')}</td></tr>"
        for t, v in sorted((fr.get("tenants") or {}).items()))
    cls_rows = "".join(
        f"<tr><td>{_html.escape(c)}</td>"
        f"<td>{v.get('launches', 0)}</td>"
        f"<td>{v.get('rows_per_launch', 0)}</td>"
        f"<td>{round(100 * (v.get('occupancy') or 0.0), 1)}%</td>"
        "</tr>"
        for c, v in sorted((fr.get("classes") or {}).items()))
    dec = fr.get("decisions") or {}
    qua = fr.get("quarantine") or {}
    idle = fr.get("idle") or {}
    return (
        "<h2>flight recorder</h2>"
        "<table><tr><th>latency</th><th>p50</th><th>p95</th>"
        "<th>p99</th><th>n</th></tr>"
        + qrow("verdict ms", fr.get("verdict_ms"))
        + qrow("ack ms", fr.get("ack_ms"))
        + "</table><table><tr><th>tenant</th><th>verdict p50</th>"
        "<th>verdict p99</th><th>ack p99</th></tr>" + tenant_rows
        + "</table><table><tr><th>class</th><th>launches</th>"
        "<th>rows/launch</th><th>occupancy</th></tr>" + cls_rows
        + "</table><p>decisions: "
        + " · ".join(f"{r} {dec.get(r, 0)}"
                     for r in ("full", "timeout", "drain", "breaker",
                               "quarantine"))
        + f" · quarantine events {qua.get('quarantined', 0)} in / "
        f"{qua.get('released', 0)} out"
        + f" · device idle {idle.get('gaps', 0)} gaps, "
        f"{idle.get('total_ms', 0.0)} ms</p>"
        + _FLEET_LIVE_JS)


def fleet_html(base: Path | None = None) -> str:
    """The fleet status page: service counters, per-tenant quota use,
    live streaming-check state, scheduler batching stats
    (jepsen_tpu.fleet; doc/fleet.md)."""
    st, info = _fleet_stats(base or Path("store"))
    head = ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
            "<title>fleet</title><style>"
            "body { font-family: sans-serif } "
            "table { border-collapse: collapse; margin: 8px 0 } "
            "td, th { padding: 3px 10px; text-align: left; "
            "border-bottom: 1px solid #ddd }"
            "</style></head><body><h1>analysis fleet</h1>"
            "<p><a href='/'>&larr; runs</a></p>")
    if st is None:
        return (head + f"<p><em>{_html.escape(str(info))}</em></p>"
                "<p>start one with <code>python -m jepsen_tpu fleet "
                "serve</code></p></body></html>")
    sch = st.get("scheduler") or {}
    rows = "".join(
        f"<tr><td>{_html.escape(t)}</td>"
        + "".join(f"<td>{s.get(k, 0)}</td>"
                  for k in ("streams", "chunks", "ops", "verdicts",
                            "rejected"))
        + "</tr>"
        for t, s in sorted((st.get("tenants") or {}).items()))
    streams = "".join(
        f"<tr><td>{_html.escape(k)}</td>"
        f"<td>{_html.escape(str(v.get('state')))}</td>"
        f"<td>{v.get('checked-frac')}</td><td>{v.get('ops')}</td>"
        f"</tr>"
        for k, v in sorted((st.get("streams") or {}).items()))
    quarantined = sch.get("quarantine") or []
    qrows = "".join(
        f"<tr><td>{_html.escape(str(q.get('tenant')))}/"
        f"{_html.escape(str(q.get('run')))}</td>"
        f"<td>{q.get('probes', 0)}</td>"
        f"<td>{_html.escape(str(q.get('error'))[:120])}</td></tr>"
        for q in quarantined)
    qsection = (
        "<h2>quarantined runs</h2>"
        "<p>poison-isolated to the solo host lane (doc/robustness.md"
        " — the fleet breaker stays closed for everyone else)</p>"
        "<table><tr><th>tenant/run</th><th>probes</th>"
        "<th>error</th></tr>" + qrows + "</table>"
    ) if quarantined else ""
    return (head
            + f"<p>server at <code>{_html.escape(str(info))}</code>"
            f" · {st.get('runs', 0)} runs · "
            f"{st.get('active_streams', 0)} active streams · "
            f"{st.get('verdicts', 0)} verdicts · "
            f"{st.get('rejected', 0)} rejected · "
            f"{st.get('recovered', 0)} recovered</p>"
            "<h2>scheduler</h2><p>"
            + " · ".join(f"{k} {sch.get(k, 0)}" for k in
                         ("launches", "items", "slice_rows",
                          "final_hists", "cross_tenant_launches",
                          "pending"))
            + (" · <b>device breaker OPEN</b>"
               if sch.get("breaker_open") else "")
            + "</p><h2>tenants</h2><table><tr><th>tenant</th>"
            "<th>streams</th><th>chunks</th><th>ops</th>"
            "<th>verdicts</th><th>rejected</th></tr>" + rows
            + "</table>" + qsection
            + "<h2>live streaming checks</h2>"
            "<table><tr><th>tenant/run</th><th>state</th>"
            "<th>checked-frac</th><th>ops</th></tr>" + streams
            + "</table>"
            + _flightrec_html(st.get("flightrec") or {})
            + "</body></html>")


def anomaly_index(res, prefix: str = "", depth: int = 0) -> list:
    """[(label, [op indices])] for every anomaly/counterexample in a
    results map that carries provenance (`op-indices`, attached by the
    elle/wgl/set checkers) — what the per-run page links to
    pre-filtered trace and timeline views."""
    out: list = []
    if not isinstance(res, dict) or depth > 4:
        return out
    anomalies = res.get("anomalies")
    if isinstance(anomalies, dict):
        for name, recs in sorted(anomalies.items(), key=str):
            idxs = sorted({int(i) for rec in recs
                           if isinstance(rec, dict)
                           for i in rec.get("op-indices") or []})
            if idxs:
                out.append((f"{prefix}{name}", idxs))
    if res.get("valid?") is False and res.get("op-indices"):
        out.append((f"{prefix}counterexample",
                    sorted(int(i) for i in res["op-indices"])))
    lost = res.get("lost-op-indices")
    if isinstance(lost, dict):
        idxs = sorted({int(i) for v in lost.values() for i in v})
        if idxs:
            out.append((f"{prefix}lost-elements", idxs))
    for k, v in sorted(res.items(), key=lambda kv: str(kv[0])):
        if isinstance(v, dict) and k not in ("anomalies",
                                             "lost-op-indices"):
            out.extend(anomaly_index(v, prefix=f"{k}/",
                                     depth=depth + 1))
    return out


def _anomaly_html(rel: str, d: Path) -> str:
    """The per-run anomaly-provenance section: each anomaly links to a
    pre-filtered Perfetto export (/trace/<run>?ops=...) and to the
    timeline anchored at its first participating op."""
    try:
        res = jstore.load_results(d)
    except (OSError, json.JSONDecodeError):
        res = None
    links = anomaly_index(res) if res else []
    if not links:
        return ""
    rows = []
    for label, idxs in links[:32]:
        qs = ",".join(str(i) for i in idxs[:64])
        preview = ", ".join(str(i) for i in idxs[:8]) + (
            "…" if len(idxs) > 8 else "")
        rows.append(
            f"<li><b>{_html.escape(label)}</b> (ops {preview}) — "
            f"<a href='/trace/{_html.escape(rel)}?ops={qs}'>perfetto"
            f"</a> · <a href='/files/{_html.escape(rel)}/timeline.html"
            f"#op-{idxs[0]}'>timeline</a></li>")
    return ("<h2>anomalies</h2><p>op references link to the traced "
            "ops behind each anomaly</p><ul>" + "".join(rows)
            + "</ul>")


def _profile_html(d: Path, rel: str) -> str:
    """The per-run kernel-profile section (device launches, cost +
    cache + wall-split table) from the run's metrics.json, with a link
    to the Prometheus exposition of the same metrics."""
    from . import telemetry as jtel
    from .reports import profile as rprofile

    metrics = jtel.read_metrics(d / jtel.METRICS_FILE)
    section = rprofile.profile_html(metrics)
    if not section:
        return ""
    return (section + f"<p><a href='/metrics?run={_html.escape(rel)}'>"
            "prometheus metrics</a></p>")


def _sparkline_svg(curve, width: int = 240, height: int = 36) -> str:
    """An inline polyline sparkline for a frontier-occupancy curve."""
    vals = [float(x) for x in curve]
    top = max(vals) or 1.0
    n = max(len(vals) - 1, 1)
    pts = " ".join(
        f"{i * width / n:.1f},{height - v / top * (height - 2):.1f}"
        for i, v in enumerate(vals))
    return (f"<svg width='{width}' height='{height}' "
            "style='vertical-align:middle'>"
            f"<polyline points='{pts}' fill='none' "
            "stroke='#6DB6FE' stroke-width='1.5'/></svg>")


def search_index(res, prefix: str = "", depth: int = 0) -> list:
    """[(label, search-dict)] for every checker result carrying
    search-dynamics stats (witness position; jepsen_tpu.tpu.wgl)."""
    out: list = []
    if not isinstance(res, dict) or depth > 5:
        return out
    s = res.get("search")
    if isinstance(s, dict) and s.get("witness-position") is not None:
        out.append((prefix or "result", s))
    for k, v in sorted(res.items(), key=lambda kv: str(kv[0])):
        if isinstance(v, dict) and k not in ("anomalies", "search"):
            out.extend(search_index(v, prefix=f"{prefix}/{k}"
                                    if prefix else str(k),
                                    depth=depth + 1))
    return out


def certificate_rows(res) -> list:
    """[(path, status)] for every certified result in a results tree
    (status: 'certified', 'error: ...', or 'absent: ...')."""
    from .tpu import certify as jcertify

    rows = []
    for path, r in jcertify.iter_certificates(res or {}):
        cert = r.get("certificate") or {}
        if "absent" in cert:
            rows.append((path, f"absent: {cert['absent']}"))
        elif r.get("certificate-error"):
            rows.append((path, f"ERROR: {r['certificate-error']}"))
        elif r.get("certified"):
            rows.append((path, "certified"))
        else:
            rows.append((path, "unvalidated"))
    return rows


def _explorer_html(d: Path, rel: str) -> str:
    """The search-explorer panel: per-kernel frontier-growth
    sparklines (from the profiler's kernel:<k> telemetry spans), the
    witness-position markers each invalid verdict carries, and the
    run's verdict-certificate statuses (doc/observability.md)."""
    try:
        events, _metrics = jstore.load_telemetry(d)
    except Exception:  # noqa: BLE001 — panel must not 500 the page
        events = []
    curves = []
    for e in events or []:
        name = str(e.get("name", ""))
        attrs = e.get("attrs") or {}
        curve = attrs.get("frontier_curve")
        if (name.startswith("kernel:") and isinstance(curve, list)
                and curve):
            curves.append((e.get("t1", 0) - e.get("t0", 0),
                           name[len("kernel:"):], curve, attrs))
    curves.sort(key=lambda c: -c[0])
    try:
        res = jstore.load_results(d)
    except (OSError, json.JSONDecodeError):
        res = None
    witnesses = search_index(res) if res else []
    certs = certificate_rows(res) if res else []
    if not curves and not witnesses and not certs:
        return ""
    parts = ["<h2>search explorer</h2>"]
    if curves:
        parts.append("<p>frontier growth per BFS level (largest "
                     "launches)</p><ul>")
        for _dur, kernel, curve, attrs in curves[:4]:
            levels = attrs.get("iterations", "?")
            label = (f"{kernel}: peak "
                     f"{attrs.get('frontier_peak', '?')} configs, "
                     f"{levels} levels, "
                     f"{attrs.get('states_explored', '?')} states")
            parts.append(f"<li>{_sparkline_svg(curve)} "
                         f"{_html.escape(label)}</li>")
        parts.append("</ul>")
    for label, s in witnesses[:8]:
        frac = float(s["witness-position"])
        pct = round(frac * 100, 1)
        marker = (
            "<svg width='240' height='12' "
            "style='vertical-align:middle'>"
            "<rect x='0' y='4' width='240' height='4' fill='#eee'/>"
            f"<rect x='{frac * 240 - 1.5:.1f}' y='0' width='3' "
            "height='12' fill='#FEB5DA'/></svg>")
        parts.append(f"<p>{marker} <b>{_html.escape(label)}</b>: "
                     f"witnessed at {pct}% of the history</p>")
    if certs:
        items = "".join(
            f"<li><b>{_html.escape(p)}</b>: {_html.escape(st)}</li>"
            for p, st in certs[:16])
        parts.append("<p>verdict certificates "
                     f"(<a href='/files/{_html.escape(rel)}/"
                     "results.json'>proofs ride in results.json</a>)"
                     f"</p><ul>{items}</ul>")
    return "".join(parts)


# graftlint report cache: the report describes the CODE, not a run,
# so one compute per process serves every page (?refresh=1 re-lints).
_lint_cache: dict = {}
_lint_lock = threading.Lock()


def _lint_baseline_path() -> Path:
    return Path(__file__).resolve().parent.parent / "lint-baseline.json"


def _compute_lint_report():
    from .analysis import driver

    rep = driver.run_lint()
    bp = _lint_baseline_path()
    if bp.exists():
        driver.gate(rep, bp)
    return rep


def _lint_report(refresh: bool = False):
    """Synchronous report for /lint (the user asked for it). The
    compute runs OUTSIDE _lint_lock so concurrent page requests never
    queue behind a trace; a racing duplicate compute is harmless."""
    with _lint_lock:
        rep = _lint_cache.get("rep")
    if rep is not None and not refresh:
        return rep
    rep = _compute_lint_report()
    with _lint_lock:
        _lint_cache["rep"] = rep
    return rep


def _lint_report_cached():
    """Non-blocking report for the run-page panel: the cached report,
    or None after kicking off a one-shot background warm — a run page
    must never stall multiple seconds on jax import + kernel tracing
    inside the request handler."""
    with _lint_lock:
        rep = _lint_cache.get("rep")
        if rep is not None:
            return rep
        if _lint_cache.get("warming"):
            return None
        _lint_cache["warming"] = True

    def warm():
        try:
            r = _compute_lint_report()
            with _lint_lock:
                _lint_cache["rep"] = r
        except Exception:  # noqa: BLE001 — warm is best-effort
            logger.exception("lint warm failed")
        finally:
            with _lint_lock:
                _lint_cache["warming"] = False

    threading.Thread(target=warm, name="jepsen-lint-warm",
                     daemon=True).start()
    return None


def lint_panel_html() -> str:
    """The run-page graftlint panel: per-rule counts, the R3/R4
    aggregates the SPMD rebuild tracks toward zero, and the baseline
    gate status — linked to the full /lint report. Renders a warming
    placeholder until the cached report exists."""
    try:
        rep = _lint_report_cached()
    except Exception:  # noqa: BLE001 — the panel must not 500 the page
        logger.exception("lint panel failed")
        return ""
    if rep is None:
        return ("<h2>graftlint</h2><p><a href='/lint'>report "
                "warming…</a> (computed in the background; "
                "refresh shortly)</p>")
    agg = rep.aggregates()
    rules = " ".join(f"{r}={n}" for r, n in agg["findings"].items()) \
        or "clean"
    gatetxt = ""
    if rep.ratchet is not None:
        new = len(rep.ratchet["new"])
        gatetxt = (f" · baseline: <b style='color:#b00'>{new} NEW"
                   "</b>" if new else " · baseline: ok")
    return ("<h2>graftlint</h2><p>"
            f"<a href='/lint'>{len(rep.findings)} finding(s)</a> "
            f"({_html.escape(rules)}) · non-donated "
            f"{agg['non_donated_bytes'] // 1024} KiB · replicated "
            f"{agg['replicated_bytes'] // 1024} KiB · unsharded axes "
            f"{agg['unsharded_axes']}{gatetxt}</p>")


def lint_html(refresh: bool = False) -> str:
    rep = _lint_report(refresh=refresh)
    agg = rep.aggregates()
    new_keys = ({f.key for f in rep.ratchet["new"]}
                if rep.ratchet is not None else set())
    rows = []
    for f in rep.findings:
        where = f"{f.file}:{f.line}" if f.file else ""
        flag = "<b style='color:#b00'>NEW</b>" if f.key in new_keys \
            else ("baselined" if rep.ratchet is not None else "")
        rows.append(
            f"<tr><td>{_html.escape(f.rule)}</td>"
            f"<td>{_html.escape(f.kernel)}</td>"
            f"<td>{_html.escape(f.site)}</td>"
            f"<td>{_html.escape(f.message)}"
            + (f"<br><i>fix: {_html.escape(f.hint)}</i>" if f.hint
               else "")
            + f"</td><td>{_html.escape(where)}</td>"
            f"<td>{flag}</td></tr>")
    stale = ""
    if rep.ratchet is not None and rep.ratchet["stale"]:
        stale = ("<p>stale baseline entries (fixed): "
                 + ", ".join(_html.escape(k)
                             for k in rep.ratchet["stale"])
                 + " — prune with <code>python -m jepsen_tpu lint "
                   "--baseline lint-baseline.json --update</code></p>")
    return ("<!DOCTYPE html><html><head><style>"
            "table { border-collapse: collapse } "
            "td, th { padding: 3px 10px; text-align: left; "
            "vertical-align: top; border-bottom: 1px solid #eee; "
            "font-size: 13px }</style></head><body>"
            "<h1>graftlint — device-kernel static analysis</h1>"
            f"<p>{len(rep.findings)} finding(s) across "
            f"{len(rep.traces)} kernel trace(s) in {rep.wall_s:.2f}s"
            " · R3 non-donated "
            f"{agg['non_donated_bytes'] // 1024} KiB · R4 replicated "
            f"{agg['replicated_bytes'] // 1024} KiB · R4 unsharded "
            f"axes {agg['unsharded_axes']} · "
            "<a href='/lint?refresh=1'>re-lint</a> · "
            "<a href='/lint?json=1'>json</a> · rule catalog: "
            "doc/static-analysis.md</p>"
            + stale
            + "<table><tr><th>rule</th><th>kernel</th><th>site</th>"
              "<th>finding</th><th>provenance</th><th>baseline</th>"
              "</tr>" + "".join(rows) + "</table>"
            "<p><a href='/'>home</a></p></body></html>")


def _nodes_html(d: Path) -> str:
    """The per-node observability lanes (jepsen_tpu.nodeprobe):
    resource strips + DB-log event markers + gap/breaker ticks under
    the run's nemesis fault windows (from its coverage record)."""
    from . import coverage as jcoverage
    from .reports import nodes as rnodes

    records = jstore.load_nodes(d)
    if not records:
        return ""
    faults = (jcoverage.load_record(d) or {}).get("faults")
    # the MERGED skew bound (probe + check-offsets) the verdict was
    # stamped with, from results.json — cheaper than re-reading the
    # history, and guaranteed consistent with what the verdict says
    bound = None
    try:
        res = jstore.load_results(d)
        if isinstance(res, dict):
            bound = res.get("clock-skew-bound")
    except (OSError, json.JSONDecodeError):
        pass
    try:
        return rnodes.lanes_html(records, faults, bound=bound)
    except Exception:  # noqa: BLE001 — lanes must not 500 the page
        logger.exception("rendering node lanes failed")
        return ""


def dir_html(rel: str, d: Path) -> str:
    entries = sorted(d.iterdir(),
                     key=lambda p: (not p.is_dir(), p.name))
    items = "".join(
        f"<li><a href='/files/{_html.escape(rel)}{_html.escape(e.name)}"
        f"{'/' if e.is_dir() else ''}'>{_html.escape(e.name)}"
        f"{'/' if e.is_dir() else ''}</a></li>" for e in entries)
    views = ""
    anomalies = ""
    profile = ""
    nodes = ""
    explorer = ""
    if (d / "test.json").exists():
        # a run directory: link its rendered views next to the raw files
        run_rel = _html.escape(rel.rstrip("/"))
        views = (f"<p>views: <a href='/telemetry/{run_rel}'>telemetry"
                 f"</a> · <a href='/live/{run_rel}'>live</a> · "
                 f"<a href='/trace/{run_rel}'>perfetto json</a></p>")
        anomalies = _anomaly_html(rel.rstrip("/"), d)
        nodes = _nodes_html(d)
        try:
            explorer = _explorer_html(d, rel.rstrip("/"))
        except Exception:  # noqa: BLE001 — panel must not 500
            logger.exception("rendering search explorer failed")
        profile = _profile_html(d, rel.rstrip("/")) + lint_panel_html()
    return (f"<!DOCTYPE html><html><head><style>"
            "table { border-collapse: collapse } "
            "td, th { padding: 3px 10px; text-align: left; "
            "border-bottom: 1px solid #eee; font-size: 13px }"
            "</style></head><body>"
            f"<h1>{_html.escape(rel)}</h1>"
            f"{views}{anomalies}{explorer}{nodes}{profile}"
            f"<ul>{items}</ul>"
            "</body></html>")


def live_html(rel: str) -> str:
    """The live dashboard: an EventSource over the SSE endpoint,
    rendering the newest sample point's vitals plus a rolling log.
    Works for finished runs too (replays the stored series, then
    gets the end event)."""
    sse = f"/live/{rel}?events=1" if rel else "/live/?events=1"
    title = _html.escape(rel or "current run")
    return ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
            f"<title>live — {title}</title><style>"
            "body { font-family: sans-serif; margin: 1.5em } "
            ".tiles { display: flex; gap: 1em; flex-wrap: wrap } "
            ".tile { border: 1px solid #ddd; border-radius: 6px; "
            "padding: .6em 1em; min-width: 7em } "
            ".tile b { display: block; font-size: 1.6em } "
            ".tile span { color: #888; font-size: .8em } "
            "#spans, #nemesis { color: #888 } "
            "table { border-collapse: collapse; margin-top: 1em } "
            "td, th { padding: 2px 10px; text-align: right; "
            "border-bottom: 1px solid #eee; font-size: 13px } "
            "#state { color: #888 }"
            "</style></head><body>"
            f"<h1>live — {title}</h1><p id='state'>connecting…</p>"
            "<div class='tiles'>"
            "<div class='tile'><b id='ops'>–</b><span>ops/s</span></div>"
            "<div class='tile'><b id='p50'>–</b><span>p50 ms</span></div>"
            "<div class='tile'><b id='p99'>–</b><span>p99 ms</span></div>"
            "<div class='tile'><b id='inflight'>–</b>"
            "<span>in flight</span></div>"
            "<div class='tile'><b id='stalls'>–</b>"
            "<span>stalls/s</span></div>"
            "<div class='tile'><b id='watchdog'>0</b>"
            "<span>watchdog</span></div>"
            "</div>"
            "<p>open spans: <span id='spans'>–</span><br>"
            "nemesis: <span id='nemesis'>–</span></p>"
            "<table id='log'><tr><th>t (s)</th><th>ops/s</th>"
            "<th>p50</th><th>p95</th><th>p99</th><th>in&nbsp;flight</th>"
            "<th>stalls/s</th></tr></table>"
            "<script>\n"
            f"var es = new EventSource({json.dumps(sse)});\n"
            "var n = 0;\n"
            "function set(id, v) { document.getElementById(id)"
            ".textContent = (v === null || v === undefined) ? '–' : v; }\n"
            "es.onopen = function() { set('state', 'streaming'); };\n"
            "es.addEventListener('end', function() { "
            "set('state', 'run complete'); es.close(); });\n"
            "es.onerror = function() { set('state', 'disconnected'); };\n"
            "es.onmessage = function(m) {\n"
            "  var p = JSON.parse(m.data);\n"
            "  var lat = p.latency_ms || {};\n"
            "  set('ops', p.ops_s); set('p50', lat.p50); "
            "set('p99', lat.p99);\n"
            "  set('inflight', Object.keys(p.inflight || {}).length);\n"
            "  set('stalls', p.stall_rate); "
            "set('watchdog', p.watchdog || 0);\n"
            "  set('spans', (p.open_spans || []).join(' › ') || '(none)');"
            "\n"
            "  set('nemesis', (p.nemesis || []).join(', ') || '(quiet)');"
            "\n"
            "  var tr = document.createElement('tr');\n"
            "  [ (p.t / 1e9).toFixed(1), p.ops_s, lat.p50, lat.p95, "
            "lat.p99,\n"
            "    Object.keys(p.inflight || {}).length, p.stall_rate ]\n"
            "    .forEach(function(v) { var td = "
            "document.createElement('td');\n"
            "      td.textContent = (v === null || v === undefined) "
            "? '–' : v; tr.appendChild(td); });\n"
            "  var log = document.getElementById('log');\n"
            "  log.insertBefore(tr, log.rows[1] || null);\n"
            "  if (log.rows.length > 31) "
            "log.deleteRow(log.rows.length - 1);\n"
            "  n++;\n"
            "};\n"
            "</script></body></html>")


CONTENT_TYPES = {".html": "text/html", ".json": "application/json",
                 ".log": "text/plain", ".txt": "text/plain",
                 ".png": "image/png", ".svg": "image/svg+xml",
                 ".jlog": "application/octet-stream"}


class StoreHandler(BaseHTTPRequestHandler):
    base: Path = Path("store")

    def log_message(self, fmt, *args):  # quiet
        logger.debug("web: " + fmt, *args)

    def _send(self, code: int, body: bytes,
              ctype: str = "text/html") -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _resolve(self, rel: str) -> Path | None:
        """Path-traversal guard (web.clj:382-388): the resolved path
        must stay under the store root."""
        p = (self.base / rel).resolve()
        root = self.base.resolve()
        if p == root or root in p.parents:
            return p
        return None

    def _live_dir(self, rel: str) -> Path | None:
        """The run directory a /live/ path names; an empty rel follows
        the store's `current` symlink (the run in progress), falling
        back to `latest`."""
        if rel:
            p = self._resolve(rel)
            return p if p is not None and p.is_dir() else None
        for link in ("current", "latest"):
            p = self.base / link
            if p.is_dir():
                # pin the real directory: the `current` symlink is
                # removed when the run finishes, mid-stream
                return p.resolve()
        return None

    def _sse_stream(self, d: Path) -> None:
        """Tails the run's timeseries.jsonl as Server-Sent Events: one
        `data:` message per sample point, `event: end` once the run
        has finished and the series is drained. The monitor flushes
        every point, so an in-progress run streams live even though
        this server is a different process."""
        from . import monitor as jmonitor

        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.end_headers()
        ts = d / jmonitor.TIMESERIES_FILE
        deadline = time.monotonic() + SSE_MAX_S
        last_beat = time.monotonic()
        f = None
        try:
            while time.monotonic() < deadline:
                if f is None and ts.exists():
                    f = open(ts)
                progressed = False
                if f is not None:
                    while True:
                        pos = f.tell()
                        line = f.readline()
                        if not line.endswith("\n"):
                            # torn tail (sampler mid-write): rewind,
                            # retry next poll
                            f.seek(pos)
                            break
                        line = line.strip()
                        if line:
                            self.wfile.write(
                                b"data: " + line.encode() + b"\n\n")
                            progressed = True
                if progressed:
                    self.wfile.flush()
                    last_beat = time.monotonic()
                # results.json marks the run finished; core.run stops
                # the monitor (final point flushed) before writing it,
                # so draining then ending cannot skip the last sample
                if not progressed and (d / "results.json").exists():
                    self.wfile.write(b"event: end\ndata: {}\n\n")
                    self.wfile.flush()
                    return
                if time.monotonic() - last_beat > SSE_HEARTBEAT_S:
                    self.wfile.write(b": ping\n\n")  # keep-alive
                    self.wfile.flush()
                    last_beat = time.monotonic()
                time.sleep(SSE_POLL_S)
        finally:
            if f is not None:
                f.close()

    def _fleet_sse(self) -> None:
        """Streams flight-recorder samples for the /fleet page's live
        charts (fleet_event_payload): one JSON `data:` message per
        poll of the fleet server's stats."""
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.end_headers()
        deadline = time.monotonic() + SSE_MAX_S
        while time.monotonic() < deadline:
            st, _info = _fleet_stats(self.base)
            payload = fleet_event_payload(st or {})
            self.wfile.write(b"data: "
                             + json.dumps(payload).encode() + b"\n\n")
            self.wfile.flush()
            time.sleep(FLEET_SSE_POLL_S)

    def do_GET(self):  # noqa: N802
        split = urlsplit(self.path)
        path = unquote(split.path)
        query = parse_qs(split.query)
        try:
            if path == "/":
                self._send(200, home_html(self.base).encode())
            elif path.startswith("/files/"):
                rel = path[len("/files/"):]
                p = self._resolve(rel)
                if p is None or not p.exists():
                    self._send(404, b"not found", "text/plain")
                elif p.is_dir():
                    if not path.endswith("/"):
                        rel += "/"
                    self._send(200, dir_html(rel, p).encode())
                else:
                    ctype = CONTENT_TYPES.get(p.suffix, "text/plain")
                    self._send(200, p.read_bytes(), ctype)
            elif path.startswith("/telemetry/"):
                rel = path[len("/telemetry/"):].rstrip("/")
                p = self._resolve(rel)
                if p is None or not p.is_dir():
                    self._send(404, b"not found", "text/plain")
                else:
                    from .reports import telemetry as rtel

                    events, metrics = jstore.load_telemetry(p)
                    if not events and metrics is None:
                        self._send(404, b"no telemetry recorded",
                                   "text/plain")
                    else:
                        self._send(200, rtel.telemetry_html(
                            rel, events, metrics).encode())
            elif path == "/live" or path.startswith("/live/"):
                rel = path[len("/live/"):].rstrip("/") \
                    if path.startswith("/live/") else ""
                d = self._live_dir(rel)
                if d is None:
                    self._send(404, b"no such run (and no run in "
                               b"progress)", "text/plain")
                elif query.get("events"):
                    self._sse_stream(d)
                else:
                    self._send(200, live_html(rel).encode())
            elif path.startswith("/trace/"):
                rel = path[len("/trace/"):].rstrip("/")
                p = self._resolve(rel)
                if p is None or not p.is_dir():
                    self._send(404, b"not found", "text/plain")
                else:
                    from .reports import trace as rtrace

                    ops = None
                    if query.get("ops"):
                        ops = [int(x) for x in query["ops"][0].split(",")
                               if x.strip().lstrip("-").isdigit()]
                    test = jstore.load(p)
                    events, _m = jstore.load_telemetry(p)
                    optrace = jstore.load_optrace(p)
                    noderecs = jstore.load_nodes(p)
                    doc = rtrace.chrome_trace(
                        test, test.get("history") or [], events,
                        optrace=optrace, ops=ops, noderecs=noderecs)
                    self._send(200, json.dumps(doc).encode(),
                               "application/json")
            elif path == "/lint" or path == "/lint/":
                # graftlint: the device-kernel static-analysis report
                # (jepsen_tpu.analysis; repo state, cached per process)
                refresh = bool(query.get("refresh"))
                if query.get("json"):
                    rep = _lint_report(refresh=refresh)
                    self._send(200,
                               json.dumps(rep.to_dict()).encode(),
                               "application/json")
                else:
                    self._send(200, lint_html(refresh).encode())
            elif path == "/fleet" or path == "/fleet/":
                # checking-as-a-service status (jepsen_tpu.fleet):
                # reads <base>/fleet/fleet.addr and asks the live
                # server for its per-tenant stats; ?events=1 is the
                # flight-recorder SSE feed for the live charts
                if query.get("events"):
                    self._fleet_sse()
                else:
                    self._send(200, fleet_html(self.base).encode())
            elif path == "/coverage" or path.startswith("/coverage/"):
                # the cross-run fault × workload × anomaly heatmap
                # (jepsen_tpu.coverage); /coverage/<fault>/<workload>
                # drills into one cell's witnessing runs
                cells = _atlas_cells(self.base)
                rest = [x for x in
                        path[len("/coverage"):].split("/") if x]
                if len(rest) == 2:
                    self._send(200, coverage_cell_html(
                        cells, rest[0], rest[1]).encode())
                else:
                    try:
                        from . import workloads

                        wls = list(workloads.REGISTRY)
                    except ImportError:
                        wls = None
                    self._send(200,
                               coverage_html(cells, wls).encode())
            elif path == "/metrics":
                # Prometheus text exposition of a run's metrics.json
                # (?run=<rel>; default: the current/latest run) — the
                # scrape endpoint the fleet service (ROADMAP item 2)
                # will aggregate
                rel = (query.get("run") or [""])[0].rstrip("/")
                d = self._live_dir(rel)
                if d is None:
                    self._send(404, b"no such run", "text/plain")
                else:
                    from . import telemetry as jtel
                    from .reports import profile as rprofile

                    metrics = jtel.read_metrics(d / jtel.METRICS_FILE)
                    if metrics is None:
                        self._send(404, b"no metrics recorded",
                                   "text/plain")
                    else:
                        body = rprofile.prometheus_text(
                            metrics, run=rel or d.name)
                        # node observability samples (latest per-node
                        # resource/skew gauges + log-event counters)
                        # ride on the same scrape
                        try:
                            from . import nodeprobe as jnodeprobe

                            nlines = jnodeprobe.prometheus_lines(
                                jstore.load_nodes(d))
                            if nlines:
                                body += "\n".join(nlines) + "\n"
                        except Exception:  # noqa: BLE001
                            logger.exception("node metrics failed")
                        # atlas-level coverage samples ride on the
                        # same scrape (jepsen_tpu.coverage)
                        try:
                            from . import coverage as jcoverage

                            cells = _atlas_cells(self.base)
                            if cells:
                                body += "\n".join(
                                    jcoverage.prometheus_lines(
                                        cells)) + "\n"
                        except Exception:  # noqa: BLE001
                            logger.exception(
                                "coverage metrics failed")
                        # fleet samples (per-tenant labels) ride on
                        # the same scrape when a server is running
                        try:
                            from .fleet.server import \
                                prometheus_from_stats

                            st, _info = _fleet_stats(self.base)
                            if st is not None:
                                body += prometheus_from_stats(st)
                        except Exception:  # noqa: BLE001
                            logger.exception("fleet metrics failed")
                        self._send(
                            200, body.encode(),
                            "text/plain; version=0.0.4; "
                            "charset=utf-8")
            elif path.startswith("/zip/"):
                rel = path[len("/zip/"):].rstrip("/")
                p = self._resolve(rel)
                if p is None or not p.is_dir():
                    self._send(404, b"not found", "text/plain")
                else:
                    buf = io.BytesIO()
                    with zipfile.ZipFile(buf, "w",
                                         zipfile.ZIP_DEFLATED) as z:
                        for f in sorted(p.rglob("*")):
                            if f.is_file():
                                z.write(f, f.relative_to(p.parent))
                    self._send(200, buf.getvalue(), "application/zip")
            else:
                self._send(404, b"not found", "text/plain")
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away (normal for abandoned SSE tails)
        except Exception:  # noqa: BLE001
            logger.exception("web error")
            self._send(500, b"internal error", "text/plain")


def serve(host: str = "0.0.0.0", port: int = 8080,
          base: Path | None = None) -> ThreadingHTTPServer:
    """Starts the store browser on a daemon thread; returns the server
    (web.clj:431-446)."""
    handler = type("Handler", (StoreHandler,),
                   {"base": Path(base) if base else jstore.BASE})
    server = ThreadingHTTPServer((host, port), handler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server
