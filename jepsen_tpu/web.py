"""Web UI over the store: browse tests, results, and artifacts.

Capability reference: jepsen/src/jepsen/web.clj — home page scanning
the store with cheap header reads (51-112), per-test file browser with
a path-traversal guard (288-388), zip download of a test directory
(340-381), app routes '/' and '/files/' (431-446).
"""

from __future__ import annotations

import html as _html
import io
import json
import logging
import threading
import zipfile
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from urllib.parse import unquote

from . import store as jstore

logger = logging.getLogger(__name__)


def fast_tests(base: Path | None = None) -> list:
    """Cheap per-test summaries for the home page (web.clj:51-112):
    reads only results.json, never the history."""
    out = []
    for td in jstore.tests(base=base):
        res = None
        try:
            res = jstore.load_results(td)
        except (OSError, json.JSONDecodeError):
            pass
        out.append({"name": td.parent.name, "time": td.name,
                    "dir": td,
                    "valid": (res or {}).get("valid?", "incomplete")})
    return out


def _valid_color(valid) -> str:
    return {True: "#6DB6FE", False: "#FEB5DA",
            "unknown": "#FFAA26"}.get(valid, "#eeeeee")


def home_html(base: Path | None = None) -> str:
    rows = []
    for t in fast_tests(base):
        rel = f"{t['name']}/{t['time']}"
        rows.append(
            f"<tr style='background:{_valid_color(t['valid'])}'>"
            f"<td>{_html.escape(t['name'])}</td>"
            f"<td><a href='/files/{_html.escape(rel)}/'>"
            f"{_html.escape(t['time'])}</a></td>"
            f"<td>{_html.escape(str(t['valid']))}</td>"
            f"<td><a href='/files/{_html.escape(rel)}/results.json'>"
            f"results</a></td>"
            f"<td><a href='/files/{_html.escape(rel)}/jepsen.log'>log"
            f"</a></td>"
            f"<td><a href='/telemetry/{_html.escape(rel)}'>telemetry"
            f"</a></td>"
            f"<td><a href='/zip/{_html.escape(rel)}'>zip</a></td>"
            f"</tr>")
    return ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
            "<title>Jepsen</title><style>"
            "body { font-family: sans-serif } "
            "table { border-collapse: collapse } "
            "td, th { padding: 4px 10px; text-align: left }"
            "</style></head><body><h1>Jepsen</h1><table>"
            "<tr><th>Test</th><th>Time</th><th>Valid?</th>"
            "<th colspan=4>Artifacts</th></tr>"
            + "".join(rows) + "</table></body></html>")


def dir_html(rel: str, d: Path) -> str:
    entries = sorted(d.iterdir(),
                     key=lambda p: (not p.is_dir(), p.name))
    items = "".join(
        f"<li><a href='/files/{_html.escape(rel)}{_html.escape(e.name)}"
        f"{'/' if e.is_dir() else ''}'>{_html.escape(e.name)}"
        f"{'/' if e.is_dir() else ''}</a></li>" for e in entries)
    return (f"<!DOCTYPE html><html><body><h1>{_html.escape(rel)}</h1>"
            f"<ul>{items}</ul></body></html>")


CONTENT_TYPES = {".html": "text/html", ".json": "application/json",
                 ".log": "text/plain", ".txt": "text/plain",
                 ".png": "image/png", ".svg": "image/svg+xml",
                 ".jlog": "application/octet-stream"}


class StoreHandler(BaseHTTPRequestHandler):
    base: Path = Path("store")

    def log_message(self, fmt, *args):  # quiet
        logger.debug("web: " + fmt, *args)

    def _send(self, code: int, body: bytes,
              ctype: str = "text/html") -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _resolve(self, rel: str) -> Path | None:
        """Path-traversal guard (web.clj:382-388): the resolved path
        must stay under the store root."""
        p = (self.base / rel).resolve()
        root = self.base.resolve()
        if p == root or root in p.parents:
            return p
        return None

    def do_GET(self):  # noqa: N802
        path = unquote(self.path.split("?", 1)[0])
        try:
            if path == "/":
                self._send(200, home_html(self.base).encode())
            elif path.startswith("/files/"):
                rel = path[len("/files/"):]
                p = self._resolve(rel)
                if p is None or not p.exists():
                    self._send(404, b"not found", "text/plain")
                elif p.is_dir():
                    if not path.endswith("/"):
                        rel += "/"
                    self._send(200, dir_html(rel, p).encode())
                else:
                    ctype = CONTENT_TYPES.get(p.suffix, "text/plain")
                    self._send(200, p.read_bytes(), ctype)
            elif path.startswith("/telemetry/"):
                rel = path[len("/telemetry/"):].rstrip("/")
                p = self._resolve(rel)
                if p is None or not p.is_dir():
                    self._send(404, b"not found", "text/plain")
                else:
                    from .reports import telemetry as rtel

                    events, metrics = jstore.load_telemetry(p)
                    if not events and metrics is None:
                        self._send(404, b"no telemetry recorded",
                                   "text/plain")
                    else:
                        self._send(200, rtel.telemetry_html(
                            rel, events, metrics).encode())
            elif path.startswith("/zip/"):
                rel = path[len("/zip/"):].rstrip("/")
                p = self._resolve(rel)
                if p is None or not p.is_dir():
                    self._send(404, b"not found", "text/plain")
                else:
                    buf = io.BytesIO()
                    with zipfile.ZipFile(buf, "w",
                                         zipfile.ZIP_DEFLATED) as z:
                        for f in sorted(p.rglob("*")):
                            if f.is_file():
                                z.write(f, f.relative_to(p.parent))
                    self._send(200, buf.getvalue(), "application/zip")
            else:
                self._send(404, b"not found", "text/plain")
        except BrokenPipeError:
            pass
        except Exception:  # noqa: BLE001
            logger.exception("web error")
            self._send(500, b"internal error", "text/plain")


def serve(host: str = "0.0.0.0", port: int = 8080,
          base: Path | None = None) -> ThreadingHTTPServer:
    """Starts the store browser on a daemon thread; returns the server
    (web.clj:431-446)."""
    handler = type("Handler", (StoreHandler,),
                   {"base": Path(base) if base else jstore.BASE})
    server = ThreadingHTTPServer((host, port), handler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server
