"""Lifting single-key workloads to independent key spaces.

Capability reference: jepsen/src/jepsen/independent.clj — linearizability
checking is exponential in history length, so histories are sharded by
key: sequential-generator (37-53), ConcurrentGenerator thread groups
(109-257), subhistories (271-326), and a checker that runs a sub-checker
per key (328-377).

The TPU twist: where the reference bounded-pmaps sub-checkers on the
JVM, a checker that supports batching (checker.linearizable) gets every
key's history in ONE device launch — per-key histories become the batch
dimension of the WGL kernel (the ensemble path, BASELINE config 5).

Ops carry (key, value) tuples as their value; `ktuple`/`key_/`value_`
mirror independent/tuple.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Sequence

from . import generator as gen
from . import history as h
from . import util
from .generator.context import make_thread_filter
from .history import History

NEMESIS = gen.NEMESIS if hasattr(gen, "NEMESIS") else "nemesis"


def ktuple(k, v) -> tuple:
    """A key-value pair riding an op's :value (independent/tuple)."""
    return (k, v)


def key_(pair):
    return pair[0] if isinstance(pair, (tuple, list)) and len(pair) == 2 \
        else None


def value_(pair):
    return pair[1] if isinstance(pair, (tuple, list)) and len(pair) == 2 \
        else pair


def _wrap_op(k, o):
    return o.copy(value=(k, o.value))


def _unwrap_event(k, event):
    v = event.value
    if isinstance(v, (tuple, list)) and len(v) == 2 and v[0] == k:
        return event.copy(value=v[1])
    return event


class SequentialGenerator(gen.Generator):
    """Works through keys one at a time; every thread works the current
    key until its generator is exhausted (independent.clj:37-53).

    `cur` uses a distinct _FRESH sentinel for "key not started": a
    generator's continuation can legitimately BE None (an exhausted
    one-element Seq flattens to its element's continuation), and
    treating that as "fresh" would restart the key's generator
    forever."""

    _FRESH = object()

    __slots__ = ("keys", "fgen", "i", "cur")

    def __init__(self, keys, fgen, i=0, cur=_FRESH):
        self.keys = tuple(keys)
        self.fgen = fgen
        self.i = i
        self.cur = cur

    def op(self, test, ctx):
        i, cur = self.i, self.cur
        while i < len(self.keys) or cur is not SequentialGenerator._FRESH:
            if cur is SequentialGenerator._FRESH:
                cur = self.fgen(self.keys[i])
            res = gen.op(cur, test, ctx)
            if res is None:
                i, cur = i + 1, SequentialGenerator._FRESH
                if i >= len(self.keys):
                    return None
                continue
            o, g = res
            if o is gen.PENDING:
                return gen.PENDING, SequentialGenerator(
                    self.keys, self.fgen, i, g)
            return (_wrap_op(self.keys[i], o),
                    SequentialGenerator(self.keys, self.fgen, i, g))
        return None

    def update(self, test, ctx, event):
        cur = self.cur
        if cur is SequentialGenerator._FRESH or cur is None:
            return self
        return SequentialGenerator(
            self.keys, self.fgen, self.i,
            gen.update(cur, test, ctx, _unwrap_event(
                self.keys[self.i] if self.i < len(self.keys) else None,
                event)))


def sequential_generator(keys, fgen) -> SequentialGenerator:
    return SequentialGenerator(keys, fgen)


class ConcurrentGenerator(gen.Generator):
    """Splits client threads into fixed groups of n; each group works
    its own key concurrently, taking fresh keys from the shared sequence
    as sub-generators exhaust (independent.clj:109-257)."""

    __slots__ = ("n", "keys", "fgen", "groups", "filters", "state",
                 "next_key")

    def __init__(self, n, keys, fgen, groups=None, filters=None,
                 state=None, next_key=0):
        self.n = n
        self.keys = tuple(keys)
        self.fgen = fgen
        self.groups = groups
        self.filters = filters
        self.state = state      # per group: (key, gen) | None (done)
        self.next_key = next_key

    def _init(self, ctx):
        if self.groups is not None:
            return self
        threads = sorted(t for t in ctx.all_thread_names()
                         if t != gen.NEMESIS)
        assert len(threads) % self.n == 0, (
            f"concurrency ({len(threads)}) must be divisible by group "
            f"size ({self.n})")
        groups = [frozenset(threads[i:i + self.n])
                  for i in range(0, len(threads), self.n)]
        filters = [make_thread_filter(lambda t, s=s: t in s)
                   for s in groups]
        state: list = []
        nk = 0
        for _g in groups:
            if nk < len(self.keys):
                state.append((self.keys[nk], self.fgen(self.keys[nk])))
                nk += 1
            else:
                state.append(None)
        return ConcurrentGenerator(self.n, self.keys, self.fgen, groups,
                                   filters, state, nk)

    def op(self, test, ctx):
        self_ = self._init(ctx)
        soonest = None
        state = list(self_.state)
        nk = self_.next_key
        for i, st in enumerate(state):
            # refill exhausted groups with fresh keys
            while st is not None and st[1] is None:
                if nk < len(self_.keys):
                    st = (self_.keys[nk], self_.fgen(self_.keys[nk]))
                    nk += 1
                else:
                    st = None
            state[i] = st
            if st is None:
                continue
            k, g = st
            tctx = self_.filters[i](ctx)
            res = gen.op(g, test, tctx)
            if res is None:
                # exhausted now: try again with a fresh key next round
                state[i] = (k, None)
                if nk < len(self_.keys):
                    state[i] = (self_.keys[nk],
                                self_.fgen(self_.keys[nk]))
                    nk += 1
                    k, g = state[i]
                    res = gen.op(g, test, tctx)
                else:
                    state[i] = None
                    continue
                if res is None:
                    continue
            o, g2 = res
            if o is gen.PENDING:
                state[i] = (k, g2)
                continue
            soonest = gen.soonest_op_map(
                soonest, {"op": o, "gen": g2, "i": i, "key": k,
                          "weight": self_.n})
        nxt = ConcurrentGenerator(self_.n, self_.keys, self_.fgen,
                                  self_.groups, self_.filters, state, nk)
        if soonest is not None:
            state2 = list(state)
            state2[soonest["i"]] = (soonest["key"], soonest["gen"])
            return (_wrap_op(soonest["key"], soonest["op"]),
                    ConcurrentGenerator(self_.n, self_.keys, self_.fgen,
                                        self_.groups, self_.filters,
                                        state2, nk))
        if any(st is not None for st in state):
            return gen.PENDING, nxt
        return None

    def update(self, test, ctx, event):
        self_ = self._init(ctx)
        thread = ctx.process_to_thread_name(event.process)
        for i, threads in enumerate(self_.groups):
            st = self_.state[i]
            if thread in threads and st is not None and st[1] is not None:
                k, g = st
                tctx = self_.filters[i](ctx)
                state = list(self_.state)
                state[i] = (k, gen.update(g, test, tctx,
                                          _unwrap_event(k, event)))
                return ConcurrentGenerator(
                    self_.n, self_.keys, self_.fgen, self_.groups,
                    self_.filters, state, self_.next_key)
        return self_


def concurrent_generator(n, keys, fgen) -> ConcurrentGenerator:
    return ConcurrentGenerator(n, keys, fgen)


# ---------------------------------------------------------------------------
# History splitting + checker
# ---------------------------------------------------------------------------

def subhistories(hist: History) -> dict:
    """Splits a history of (key, value) ops into per-key histories with
    unwrapped values (independent.clj:271-326)."""
    out: dict = {}
    for o in hist:
        v = o.value
        if isinstance(v, (tuple, list)) and len(v) == 2:
            k, val = v[0], v[1]
            out.setdefault(k, []).append(o.copy(value=val))
    return {k: History(ops, assign_indices=False)
            for k, ops in out.items()}


class IndependentChecker:
    """Applies a sub-checker to each key's history. If the sub-checker
    supports check_batch (the TPU linearizable checker does), every key
    is checked in one device launch."""

    def __init__(self, inner):
        self.inner = inner

    def check(self, test, hist, opts=None):
        from . import checker as chk

        opts = opts or {}
        subs = subhistories(hist)
        keys = sorted(subs.keys(), key=str)
        results = None
        if hasattr(self.inner, "check_batch"):
            try:
                results = self.inner.check_batch(
                    test, [subs[k] for k in keys], opts)
            except Exception:  # noqa: BLE001 - retry with isolation
                results = None
        if results is None:
            results = util.bounded_pmap(
                lambda k: chk.check_safe(self.inner, test, subs[k], opts),
                keys, limit=8)
        by_key = dict(zip(keys, results))
        # per-key verdict certificates reference the ORIGINAL history's
        # op indices (subhistories keep them), but their values are
        # wrapped (key, v) tuples there and their digest covers only
        # the subhistory — stamp each certificate with its key (so the
        # validator filters + unwraps during replay) and re-anchor the
        # digest to the whole history the validator will be handed
        # (jepsen_tpu.tpu.certify)
        full_digest = None
        for k, r in by_key.items():
            cert = (r or {}).get("certificate") \
                if isinstance(r, dict) else None
            if isinstance(cert, dict) and "absent" not in cert:
                from .tpu import certify as jcertify

                try:
                    import json as _json

                    _json.dumps(k)
                except (TypeError, ValueError):
                    r["certificate"] = {"v": cert.get("v", 1),
                                        "absent": "independent key "
                                        "is not JSON-serializable"}
                    continue
                if full_digest is None:
                    full_digest = jcertify.history_digest(hist)
                cert["key"] = jcertify._jv(k)
                cert["history"] = full_digest
        failures = [k for k, r in by_key.items()
                    if (r or {}).get("valid?") is False]
        valid = chk.merge_valid((r or {}).get("valid?")
                                for r in by_key.values())
        return {"valid?": valid,
                "results": by_key,
                "failures": failures}


def checker(inner) -> IndependentChecker:
    return IndependentChecker(inner)
