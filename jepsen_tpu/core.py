"""Test lifecycle orchestration.

A test is a plain dict. `run(test)` opens control sessions, sets up
OS/DB, spawns clients and nemesis, drives the generator through the
interpreter, tears everything down, checks the history, and returns the
test with :history and :results.

Capability reference: jepsen/src/jepsen/core.clj (run! 322-412,
prepare-test 302-320, with-resources 69-90, with-os 92-99, with-db
164-173, client+nemesis setup/teardown 175-206, run-case! 208-213,
analyze! 215-228, snarf-logs! 101-162, synchronize 43-56).
"""

from __future__ import annotations

import datetime
import errno
import logging
import socket
import threading
from pathlib import Path
from typing import Any

from . import client as jclient
from . import control
from . import coverage as jcoverage
from . import db as jdb
from . import interpreter
from . import monitor as jmonitor
from . import nemesis as jnemesis
from . import telemetry
from . import tracing
from . import util
from . import watchdog as jwatchdog
from .history import History

logger = logging.getLogger(__name__)

NO_BARRIER = "::no-barrier"


def synchronize(test: dict, timeout_s: float = 60.0) -> None:
    """Blocks until all nodes arrive at the same point (core.clj:43-56)."""
    barrier = test.get("barrier")
    if barrier == NO_BARRIER or barrier is None:
        return
    barrier.wait(timeout=timeout_s)


def primary(test: dict):
    return test["nodes"][0]


def prepare_test(test: dict) -> dict:
    """Fills in :start-time, :concurrency, :barrier (core.clj:302-320)."""
    test = dict(test)
    if not test.get("start_time"):
        test["start_time"] = datetime.datetime.now()
    if not test.get("concurrency"):
        test["concurrency"] = len(test.get("nodes") or [])
    if not test.get("barrier"):
        n = len(test.get("nodes") or [])
        test["barrier"] = threading.Barrier(n) if n > 0 else NO_BARRIER
    return test


def _setup_os(test: dict) -> None:
    os_ = test.get("os")
    if os_ is not None:
        control.on_nodes(test, lambda t, n: os_.setup(t, n))


def _teardown_os(test: dict) -> None:
    os_ = test.get("os")
    if os_ is not None:
        _teardown_tolerantly(test, "os",
                             lambda t, n: os_.teardown(t, n))


def _transport_failure(e: BaseException) -> bool:
    """Couldn't REACH the node: SSH transport errors, refused/reset
    connections, DNS failures, and the network-errno family of raw
    OSErrors (EHOSTUNREACH etc., which Python does NOT map onto
    ConnectionError). Local misconfiguration — FileNotFoundError for a
    missing binary, TypeError from a client bug — is never transport."""
    from .control.core import TransportError

    if isinstance(e, (TransportError, ConnectionError, TimeoutError,
                      socket.gaierror)):
        return True
    return (isinstance(e, OSError) and not isinstance(e, socket.herror)
            and e.errno in (errno.EHOSTUNREACH, errno.ENETUNREACH,
                            errno.ENETDOWN, errno.EHOSTDOWN,
                            errno.ETIMEDOUT))


def _teardown_tolerantly(test: dict, what: str, node_fn) -> None:
    """Runs a per-node teardown phase on all nodes; with quarantine
    active, a dead node's transport failure degrades (logged + counted)
    instead of aborting the run between history capture and analysis —
    the history is already safe on disk and is worth analyzing. Every
    node's teardown is attempted (a bare on_nodes call would surface
    only the FIRST node's failure, letting a dead node mask a genuine
    teardown bug on a live one); non-transport failures still raise,
    carrying all of them."""
    errs: dict = {}

    def one(t, n):
        try:
            node_fn(t, n)
        except Exception as e:  # noqa: BLE001 — classified below
            errs[n] = e  # distinct keys per node: no lock needed

    control.on_nodes(test, one)
    if not errs:
        return
    if (test.get("health") is None
            or not all(_transport_failure(x) for x in errs.values())):
        failures = [errs[n] for n in sorted(errs, key=str)]
        if len(failures) == 1:
            raise failures[0]
        raise util.RealPmapError(failures)
    telemetry.count("core.degraded-teardowns")
    logger.warning("%s teardown failed on unreachable node(s) %s; "
                   "continuing :degraded", what,
                   sorted(map(str, errs)))


def _db_cycle(test: dict) -> None:
    """Tears down then sets up the DB on all nodes, with primary setup
    (db.clj cycle!)."""
    db = test.get("db")
    if db is None:
        return

    def once():
        control.on_nodes(test, lambda t, n: db.teardown(t, n))
        if db.supports_primaries:
            db.setup_primary(test, primary(test))
        control.on_nodes(test, lambda t, n: db.setup(t, n))

    util.with_retry(once, retries=2, backoff=1.0)


def _teardown_db(test: dict) -> None:
    db = test.get("db")
    if db is not None and not test.get("leave_db_running?"):
        _teardown_tolerantly(test, "db",
                             lambda t, n: db.teardown(t, n))


def snarf_logs(test: dict) -> None:
    """Downloads DB log files into the store directory
    (core.clj:101-128)."""
    db = test.get("db")
    if db is None:
        return
    try:
        from . import store as jstore
    except ImportError:
        return
    if not test.get("name") or not test.get("start_time"):
        return

    def snarf(t, node):
        files = jdb.log_files_map(db, t, node)
        for remote, local in files.items():
            try:
                dest = jstore.path(t, str(node), local.lstrip("/"))
                dest.parent.mkdir(parents=True, exist_ok=True)
                control.download([remote], dest)
            except Exception as e:  # noqa: BLE001
                logger.info("couldn't download %s: %s", remote, e)

    try:
        control.on_nodes(test, snarf)
    except Exception:  # noqa: BLE001
        logger.exception("Error snarfing logs")


# Bound on the daemon nemesis-teardown join: a nemesis hung in
# teardown must not stall the run forever, but a silently leaked
# partition is worse — the timeout is surfaced via telemetry + log,
# and the final heal below still runs.
NEMESIS_TEARDOWN_TIMEOUT_S = 60.0


def final_heal(test: dict) -> None:
    """Last-resort cleanup after a case: heal the network and (when the
    test opted in via restore_clocks?) reset node clocks — even if the
    nemesis or its teardown thread died. The reference brackets its
    whole run in teardown forms (core.clj:322-387); without this, a
    partition opened by a crashed nemesis outlives the test and poisons
    the next one. Best-effort: failures are logged, never raised."""
    if not test.get("sessions"):
        return
    # a quarantined node can't be healed and must not abort healing
    # the nodes that ARE reachable
    hr = test.get("health")
    if hr is not None and hr.quarantined():
        test = dict(test)
        dead = set(hr.quarantined())
        test["nodes"] = [n for n in (test.get("nodes") or [])
                         if n not in dead]
    net = test.get("net")
    if net is not None:
        try:
            with telemetry.span("final-heal"):
                net.heal(test)
        except Exception:  # noqa: BLE001 — heal must not sink teardown
            telemetry.count("core.final-heal-failures")
            logger.exception("final net heal failed")
    if test.get("restore_clocks?"):
        from .nemesis import time as ntime

        try:
            with telemetry.span("final-clock-restore"):
                control.on_nodes(test, lambda t, n: ntime._meh_reset())
        except Exception:  # noqa: BLE001
            telemetry.count("core.final-heal-failures")
            logger.exception("final clock restore failed")


def run_case(test: dict) -> dict:
    """Sets up clients + nemesis, runs the generator via the interpreter,
    tears them down (core.clj:175-213). A final heal (net + clocks) is
    registered around the whole case so it fires even when the nemesis
    thread died mid-fault."""
    client = test["client"]
    nem = jnemesis.validate(test.get("nemesis") or jnemesis.noop)

    nem_box: dict = {}

    def setup_nemesis():
        try:
            nem_box["nem"] = nem.setup(test)
        except BaseException as e:  # noqa: BLE001 - re-raised on the caller
            nem_box["error"] = e

    nem_thread = threading.Thread(target=setup_nemesis, daemon=True)
    nem_thread.start()

    def open_one(node):
        c = None
        try:
            c = jclient.validate(client).open(test, node)
            c.setup(test)
            return c
        except Exception as e:  # noqa: BLE001 — classified below
            # couldn't REACH the node (_transport_failure: ssh
            # transport, connect/timeout, DNS, network errnos) —
            # degradable under quarantine; a client bug or local
            # misconfiguration (TypeError, FileNotFoundError for a
            # missing client binary) still raises and fails the run
            if test.get("health") is None or not _transport_failure(e):
                raise
            if c is not None:
                # open() succeeded, setup() died: close the half-open
                # client instead of leaking its connection for the
                # rest of the (continuing) run
                try:
                    c.close(test)
                except Exception:  # noqa: BLE001 — best-effort
                    pass
            # the node's worker will retry opens per-op (ClientWorker
            # fails ops "no-client" until then); the run continues
            telemetry.count("core.degraded-client-opens")
            logger.warning("client open/setup failed on %s; continuing "
                           ":degraded (quarantine active)", node)
            return None

    clients = [c for c in util.real_pmap(open_one,
                                         test.get("nodes") or [])
               if c is not None]
    nem_thread.join()
    if "error" in nem_box:
        raise nem_box["error"]
    nemesis_up = nem_box["nem"]
    test = dict(test)
    test["nemesis"] = nemesis_up
    try:
        return interpreter.run(test)
    finally:
        def teardown_nem():
            try:
                nemesis_up.teardown(test)
            except Exception:  # noqa: BLE001 — teardown is best-effort;
                # the final heal below still clears partitions
                telemetry.count("core.nemesis-teardown-failures")
                logger.exception("nemesis teardown failed")

        nt = threading.Thread(target=teardown_nem, daemon=True)
        nt.start()

        def close_one(c):
            try:
                c.teardown(test)
            finally:
                c.close(test)

        try:
            util.real_pmap(close_one, clients)
        finally:
            nt.join(NEMESIS_TEARDOWN_TIMEOUT_S)
            if nt.is_alive():
                # the daemon thread is abandoned; whatever faults it
                # failed to undo are surfaced (and the final heal
                # below still clears partitions)
                telemetry.count("core.nemesis-teardown-timeouts")
                logger.warning(
                    "nemesis teardown still running after %.0fs; "
                    "abandoning it (possible leaked faults — final "
                    "heal will clear network partitions)",
                    NEMESIS_TEARDOWN_TIMEOUT_S)
            final_heal(test)


def analyze(test: dict, store_ctx=None, extra_opts: dict | None = None
            ) -> dict:
    """Runs the checker over the history (core.clj:215-228). With a
    store, composed checkers stream each sub-result to a partial-
    results log as they finish, so a crash mid-analysis leaves the
    completed results readable (store/format.clj PartialMap).
    extra_opts merge into the checker opts (the resume path passes
    recovered partial results through here)."""
    from . import checker as jchecker

    logger.info("Analyzing...")
    checker = test.get("checker")
    if checker is None:
        checker = jchecker.unbridled_optimism()
    test = dict(test)
    opts = dict(extra_opts or {})
    partial = None
    if store_ctx is not None:
        try:
            from .store import format as sformat
            partial = sformat.PartialResultsWriter(
                store_ctx.path(test, "results.partial.jlog"))
            opts["partial_results"] = partial
        except Exception:  # noqa: BLE001 — partials are best-effort
            logger.exception("opening partial-results log failed")
    trace_dir = test.get("profile_dir")
    if trace_dir is None and store_ctx is not None and test.get(
            "profile?"):
        trace_dir = store_ctx.path(test, "xprof")
    if trace_dir is None and store_ctx is not None and test.get(
            "xla-trace?"):
        # the --xla-trace CLI flag: an XLA profiler trace of the
        # analysis phase (every kernel launch) lands in the store dir
        trace_dir = store_ctx.path(test, "xla-trace")
    # a hung non-composed checker gets the same wall-clock bound the
    # Compose applies per sub-checker; composed checkers are bounded
    # individually inside (one outer bound would cap the whole set)
    timeout_s = None
    if not isinstance(checker, jchecker.Compose):
        timeout_s = jchecker.checker_timeout_s(test, opts)
    try:
        with telemetry.span("analyze"):
            with util.profile_trace(trace_dir):
                test["results"] = jchecker.check_safe(
                    checker, test, test["history"], opts,
                    timeout_s=timeout_s)
    finally:
        if partial is not None:
            partial.close()
    # per-checker timings + phase/kernel counters ride in the results
    # (and therefore results.json) next to the verdict they explain
    if isinstance(test.get("results"), dict):
        # verdict certificates: every wgl/elle result carrying a proof
        # is independently re-validated against the raw history and
        # stamped `certified` / `certificate-error` — live here, and
        # offline too (analyze --resume re-enters this path), so a
        # device-kernel regression fails by proof, not by luck
        # (jepsen_tpu.tpu.certify, doc/observability.md)
        try:
            from .tpu import certify as jcertify

            counts = jcertify.stamp_results(test["results"],
                                            test.get("history") or [])
            if any(counts.values()):
                logger.info(
                    "certificates: %d validated, %d failed, %d absent",
                    counts["certified"], counts["errors"],
                    counts["absent"])
        except Exception:  # noqa: BLE001 — stamping is best-effort
            logger.exception("certificate validation failed")
        test["results"]["telemetry"] = telemetry.get().summary()
        # the online watchdog's violations ride alongside too —
        # informational only, never folded into the checkers' valid?
        wd = test.get("watchdog")
        if wd is not None and hasattr(wd, "results"):
            test["results"]["watchdog"] = wd.results()
            if test.get("aborted"):
                test["results"]["watchdog"]["aborted"] = test["aborted"]
        # quarantined-node runs finish with a :degraded marker instead
        # of aborting: the verdict stands, but readers see which nodes
        # the control plane gave up on (control/health.py)
        hr = test.get("health")
        if hr is not None and hr.ever_quarantined():
            test["results"]["degraded"] = {
                "quarantined-nodes": sorted(
                    map(str, hr.ever_quarantined())),
                "still-quarantined": sorted(
                    map(str, hr.quarantined()))}
        # the fleet's verdict (with its certificate) rides next to the
        # local one — informational: a tenant compares, it never
        # replaces local checking (jepsen_tpu.fleet, doc/fleet.md)
        streamer = test.get("_fleet_streamer")
        if streamer is not None:
            try:
                test["results"]["fleet"] = streamer.result_summary()
            except Exception:  # noqa: BLE001 — best-effort
                logger.exception("collecting fleet verdict failed")
        # realtime-order verdicts (wgl linearizability, elle strict
        # variants) carry the clock skew actually measured during the
        # run: the node probe's per-tick offsets merged with the
        # history's check-offsets observations (jepsen_tpu.nodeprobe).
        # Works offline too — `analyze` re-reads nodes.jsonl.
        try:
            from . import nodeprobe as jnodeprobe

            nprobe = test.get("nodeprobe")
            recs = (nprobe.records() if nprobe is not None
                    else jnodeprobe.load_records(test.get("store_dir")))
            bound = jnodeprobe.clock_skew_bound(recs,
                                                test.get("history"))
            if bound is not None:
                n = jnodeprobe.stamp_results(test["results"], bound)
                test["results"]["clock-skew-bound"] = bound
                logger.info("clock-skew-bound %.3fs stamped on %d "
                            "realtime verdict(s)", bound, n)
        except Exception:  # noqa: BLE001 — stamping is best-effort
            logger.exception("stamping clock-skew-bound failed")
    logger.info("Analysis complete")
    return test


def log_results(test: dict) -> dict:
    results = test.get("results") or {}
    valid = results.get("valid?")
    if valid is True:
        logger.info("Everything looks good! (results valid)")
    elif valid == "unknown":
        logger.info("Errors during analysis, but no anomalies found.")
    else:
        logger.info("Analysis invalid!")
    return test


def run(test: dict) -> dict:
    """Full lifecycle (core.clj:322-412)."""
    # multi-host analysis: jax.distributed must initialize before the
    # first JAX computation, so it happens at lifecycle entry
    try:
        from .tpu import dist
        dist.ensure_initialized()
    except ImportError:
        pass
    test = prepare_test(test)

    store_ctx = None
    if test.get("name"):
        try:
            from . import store as jstore
            store_ctx = jstore
            test = jstore.start_test(test)
        except ImportError:
            store_ctx = None

    if test.get("fleet"):
        # checking-as-a-service (jepsen_tpu.fleet): mirror the op log
        # to the fleet mid-run; its verdict+certificate ride in the
        # results as results['fleet'] NEXT to the authoritative local
        # checkers. Best-effort — but never silent: a fleet that was
        # requested and couldn't attach still yields an honest
        # results['fleet'] = {'unavailable': reason}.
        try:
            from .fleet import client as jfleet_client
            if test.get("history_writer") is None:
                test["_fleet_streamer"] = jfleet_client.NoStream(
                    "no history writer (unnamed test: no store)")
            else:
                writer, streamer = jfleet_client.attach(test)
                test["history_writer"] = writer
                test["_fleet_streamer"] = streamer
        except Exception as e:  # noqa: BLE001 — never sink a run
            logger.exception("attaching fleet streamer failed")
            try:
                from .fleet import client as jfleet_client
                test["_fleet_streamer"] = jfleet_client.NoStream(
                    f"attach failed: {e!r}"[:200])
            except Exception:  # noqa: BLE001
                pass

    try:
        # analyze runs INSIDE the relative-time scope so its telemetry
        # spans share the run's clock origin (and line up with op
        # times); nothing in analysis reads the ambient origin itself.
        with util.with_relative_time():
            telemetry.reset()
            # fault-activation coverage is scoped per run like the
            # telemetry it rides next to (jepsen_tpu.coverage)
            jcoverage.reset()
            try:
                # per-launch device-profile records are scoped per run
                # like the telemetry they mirror into
                from .tpu import profiler as jprofiler
                jprofiler.reset()
            except ImportError:
                pass
            # per-op causal tracing is opt-in (test["trace?"]); when a
            # store exists the recorder streams optrace.jsonl into it
            # as spans complete (crash-tolerant like telemetry.jsonl)
            tracer = tracing.get()
            tracer.reset(enabled=bool(test.get("trace?")))
            if tracer.enabled and test.get("store_dir"):
                tracer.open(Path(test["store_dir"]) / tracing.TRACE_FILE)
            # the live monitor + online watchdog span the whole run:
            # the sampler sees setup, the case, AND analysis (device
            # occupancy gauges appear mid-analyze), streaming points
            # into timeseries.jsonl that web.py's /live/ page tails
            mon = jmonitor.Monitor(test)
            test["monitor"] = mon
            wd = jwatchdog.from_test(test)
            if wd is not None:
                test["watchdog"] = wd
            mon.start(Path(test["store_dir"]) / jmonitor.TIMESERIES_FILE
                      if test.get("store_dir") else None)
            try:
                with telemetry.span("run", test=test.get("name")):
                    if test.get("quarantine?"):
                        # per-node circuit breakers: a persistently
                        # dead node is quarantined (its ops crash fast
                        # to :info) and the run continues :degraded
                        # instead of aborting (control/health.py)
                        from .control import health as jhealth
                        test["health"] = jhealth.HealthRegistry.from_test(
                            test)
                    test = control.open_sessions(test)
                    # the node observability plane: a per-node
                    # resource/clock-skew/DB-log sampler over its own
                    # control sessions, appending nodes.jsonl
                    # (jepsen_tpu.nodeprobe; opt-in via
                    # test["nodeprobe?"], on by default in the demo CLI)
                    nprobe = None
                    if test.get("nodeprobe?") and test.get("store_dir"):
                        try:
                            from . import nodeprobe as jnodeprobe
                            nprobe = jnodeprobe.NodeProbe(test)
                            test["nodeprobe"] = nprobe
                            nprobe.start(Path(test["store_dir"])
                                         / jnodeprobe.NODES_FILE)
                        except Exception:  # noqa: BLE001 — never
                            # sink the run for observability
                            logger.exception("starting node probe "
                                             "failed")
                            nprobe = None
                    try:
                        with telemetry.span("os-setup"):
                            _setup_os(test)
                        try:
                            with telemetry.span("db-cycle"):
                                _db_cycle(test)
                            try:
                                with telemetry.span("case"):
                                    test = run_case(test)
                                if store_ctx:
                                    store_ctx.save_history(test)
                                with telemetry.span("snarf-logs"):
                                    snarf_logs(test)
                            finally:
                                with telemetry.span("teardown-db"):
                                    _teardown_db(test)
                        finally:
                            with telemetry.span("teardown-os"):
                                _teardown_os(test)
                    finally:
                        # the probe's final offsets/events land before
                        # analysis so the skew bound sees the full run
                        if nprobe is not None:
                            try:
                                nprobe.stop()
                            except Exception:  # noqa: BLE001
                                logger.exception("stopping node probe "
                                                 "failed")
                        control.close_sessions(test)

                # checkers read optrace.jsonl (timeline hover, trace
                # excerpts): push any buffered records out first
                tracer.flush()
                # one guaranteed case-phase sample: the perf checker's
                # monitor graph reads timeseries.jsonl during analyze,
                # and a short run may not have crossed the sampler's
                # first interval yet
                mon.flush_point()
                test = analyze(test, store_ctx)
                # final monitor point BEFORE results.json: /live/
                # tailers treat results.json as the end-of-run marker
                # and must not miss the last sample
                mon.stop()
                if store_ctx:
                    store_ctx.save_results(test)
                # the run's coverage record (fault × workload ×
                # anomaly cells, doc/observability.md) + its atlas
                # line; best-effort — coverage must never sink a run
                if store_ctx and test.get("store_dir"):
                    try:
                        rec = jcoverage.write_record(test)
                        if rec is not None:
                            jcoverage.append_run(
                                store_ctx.base_dir(test), rec)
                    except Exception:  # noqa: BLE001
                        logger.exception("writing coverage record "
                                         "failed")
            finally:
                try:
                    mon.stop()
                except Exception:  # noqa: BLE001 — best-effort
                    logger.exception("stopping monitor failed")
                # even a crashed run leaves its trace behind
                if store_ctx and test.get("store_dir"):
                    try:
                        telemetry.save(test["store_dir"])
                    except Exception:  # noqa: BLE001 — best-effort
                        logger.exception("saving telemetry failed")
                # the op-trace stream is already on disk; close it so
                # the tail is flushed even when the run crashed
                try:
                    tracer.close()
                except Exception:  # noqa: BLE001 — best-effort
                    logger.exception("closing optrace failed")
    finally:
        # a crashed lifecycle must not leak the per-test log handler
        if store_ctx:
            store_ctx.stop(test)
    return log_results(test)
