"""Test result storage: store/<name>/<timestamp>/ directories.

Capability reference: jepsen/src/jepsen/store.clj — per-test directories
with `latest`/`current` symlinks (40-76, 320-358), three-phase saves so
partial results survive crashes (save-0!/1!/2!, 426-466), per-test
jepsen.log (468-512), load (108-134) and delete! GC (514-531).

Layout:
  store/<name>/<YYYYMMDDTHHMMSS.ffff>/
    test.json      save-0: the test map, minus the history/results
    spec.json      save-0: reconstructible test spec (test["spec"]) —
                   `python -m jepsen_tpu analyze <dir>` rebuilds the
                   checker stack from it after a control-process crash
                   (doc/robustness.md)
    history.jlog   incremental CRC-framed op log (store.format)
    results.json   save-2: checker results
    jepsen.log     per-test log output
    telemetry.jsonl  span trace (jepsen_tpu.telemetry, doc/observability.md)
    metrics.json   aggregated span/counter/gauge metrics
    timeseries.jsonl  live-monitor sample points (jepsen_tpu.monitor),
                   appended while the run executes (web.py /live/ tails it)
    optrace.jsonl  per-op causal trace: client/remote child spans +
                   events (jepsen_tpu.tracing, when test["trace?"])
    trace.json     Chrome-trace/Perfetto export (reports/trace.py, on demand)
    coverage.json  per-run fault × workload × anomaly coverage record
                   (jepsen_tpu.coverage, doc/observability.md)
    nodes.jsonl    node observability plane: per-node resource samples,
                   clock offsets, tagged DB-log events, honest gap
                   markers (jepsen_tpu.nodeprobe, when test["nodeprobe?"])
    <node>/...     downloaded node logs (core.snarf_logs)
  store/<name>/latest  -> most recent run   store/latest -> same
  store/current        -> run in progress
  store/coverage_atlas.jsonl  cross-run coverage journal (one line per
                   analyzed run, newest-per-run wins; jepsen_tpu.coverage)
"""

from __future__ import annotations

import datetime
import json
import logging
import os
import shutil
from pathlib import Path
from typing import Any, Iterator

from . import format as fmt
from ..history import History

logger = logging.getLogger(__name__)

BASE = Path("store")

_SKIP_KEYS = {"history", "results", "barrier", "db", "client", "nemesis",
              "checker", "generator", "os", "remote", "sessions",
              "history_writer", "store_dir", "_log_handler",
              "monitor", "watchdog", "monitor_probes", "health",
              "nodeprobe", "_fleet_streamer"}


def base_dir(test: dict | None = None) -> Path:
    if test and test.get("store_base"):
        return Path(test["store_base"])
    return BASE


def dir_name(test: dict) -> str:
    t = test.get("start_time") or datetime.datetime.now()
    if isinstance(t, str):
        return t
    return t.strftime("%Y%m%dT%H%M%S.%f")[:-2]


def test_dir(test: dict) -> Path:
    return base_dir(test) / str(test.get("name", "noname")) / dir_name(test)


def path(test: dict, *parts) -> Path:
    """A path inside the test's store directory (creating parents is the
    caller's business)."""
    d = test.get("store_dir") or test_dir(test)
    return Path(d).joinpath(*[str(p) for p in parts])


def _symlink(link: Path, target: Path) -> None:
    try:
        if link.is_symlink() or link.exists():
            link.unlink()
        link.symlink_to(target.resolve())
    except OSError:  # e.g. filesystems without symlink support
        pass


def save_test_map(test: dict) -> None:
    d = Path(test["store_dir"])
    view = {k: fmt.jsonable(v) for k, v in test.items()
            if k not in _SKIP_KEYS}
    with open(d / "test.json", "w") as f:
        json.dump(view, f, indent=1, default=repr)


def save_spec(test: dict) -> None:
    """Writes the reconstructible test spec (test["spec"]) as
    spec.json, so a crashed run's analysis can rebuild its checker
    stack without the original process (`analyze` subcommand)."""
    spec = test.get("spec")
    if not spec:
        return
    d = Path(test["store_dir"])
    with open(d / "spec.json", "w") as f:
        json.dump(fmt.jsonable(spec), f, indent=1, default=repr)


def load_spec(d) -> dict | None:
    """The reconstructible test spec a run saved at start, or None for
    runs that predate (or never carried) one."""
    p = Path(d) / "spec.json"
    if not p.exists():
        return None
    try:
        with open(p) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def start_test(test: dict) -> dict:
    """save-0: creates the store dir, symlinks, log file, initial test
    map + spec, and attaches an incremental history writer."""
    test = dict(test)
    d = test_dir(test)
    d.mkdir(parents=True, exist_ok=True)
    test["store_dir"] = str(d)
    _symlink(d.parent / "latest", d)
    _symlink(base_dir(test) / "latest", d)
    _symlink(base_dir(test) / "current", d)
    save_test_map(test)
    save_spec(test)
    # liveness marker: the web UI must not advertise a RUNNING test as
    # '[recoverable]' just because a long checker phase went quiet —
    # as long as this pid is alive, the run is live (web.py)
    try:
        (d / "run.pid").write_text(str(os.getpid()))
    except OSError:
        pass
    test["history_writer"] = fmt.HistoryWriter(d / "history.jlog")
    _start_logging(test)
    return test


def save_history(test: dict) -> dict:
    """save-1: the op log is already on disk (written incrementally by
    the interpreter); refresh the test map."""
    save_test_map(test)
    return test


def stop(test: dict) -> None:
    """Releases per-test resources (log handler, writer); safe to call
    repeatedly. core.run calls this in a finally block so a crashed
    lifecycle doesn't leak the root-logger handler."""
    _stop_logging(test)
    w = test.get("history_writer")
    if w is not None:
        w.close()


def save_results_only(test: dict) -> None:
    """results.json alone — offline re-analysis (`analyze` over a
    stored run) must not retire the store's `current` symlink (it
    belongs to whichever run is LIVE) or overwrite the run's original
    test.json with the rebuilt map."""
    d = Path(test["store_dir"])
    with open(d / "results.json", "w") as f:
        json.dump(fmt.jsonable(test.get("results")), f, indent=1,
                  default=repr)


def save_results(test: dict) -> dict:
    """save-2: writes checker results."""
    save_results_only(test)
    save_test_map(test)
    cur = base_dir(test) / "current"
    if cur.is_symlink():
        cur.unlink()
    _stop_logging(test)
    return test


def _start_logging(test: dict) -> None:
    handler = logging.FileHandler(path(test, "jepsen.log"))
    handler.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s [%(name)s] %(message)s"))
    logging.getLogger().addHandler(handler)
    test["_log_handler"] = handler


def _stop_logging(test: dict) -> None:
    handler = test.pop("_log_handler", None)
    if handler is not None:
        logging.getLogger().removeHandler(handler)
        handler.close()


# ---------------------------------------------------------------------------
# Loading / browsing
# ---------------------------------------------------------------------------

def load_results(d) -> dict | None:
    """Final results; when only the crash-surviving partial log exists
    (the checker died mid-analysis), its completed entries come back
    with valid? 'unknown' (store/format.clj PartialMap)."""
    p = Path(d) / "results.json"
    if p.exists():
        with open(p) as f:
            return json.load(f)
    partial = Path(d) / "results.partial.jlog"
    if partial.exists():
        from . import format as fmt

        got = fmt.read_partial_results(partial)
        if got:
            got["valid?"] = "unknown"
            got["partial?"] = True
            return got
    return None


def load_telemetry(d) -> tuple[list, dict | None]:
    """(span events, metrics) from a stored test dir's telemetry
    artifacts (telemetry.jsonl / metrics.json); ([], None) when the
    run predates the telemetry layer."""
    from .. import telemetry as tel

    d = Path(d)
    events = list(tel.read_events(d / tel.TRACE_FILE))
    metrics = tel.read_metrics(d / tel.METRICS_FILE)
    return events, metrics


def load_optrace(d) -> list[dict]:
    """Per-op trace records from a stored test dir's optrace.jsonl
    (jepsen_tpu.tracing); [] when the run didn't opt into tracing."""
    from .. import tracing as jtracing

    return list(jtracing.read_records(Path(d) / jtracing.TRACE_FILE))


def load_nodes(d) -> list[dict]:
    """Node-plane records (samples, gaps, log events, breaker
    transitions) from a stored test dir's nodes.jsonl
    (jepsen_tpu.nodeprobe); [] when the run predates (or disabled)
    the probe."""
    from .. import nodeprobe as jnodeprobe

    return jnodeprobe.load_records(d)


def load_timeseries(d) -> list[dict]:
    """Live-monitor sample points from a stored test dir's
    timeseries.jsonl; [] when the run predates (or disabled) the
    monitor."""
    from .. import monitor as jmonitor

    return list(jmonitor.read_points(
        Path(d) / jmonitor.TIMESERIES_FILE))


def load(name_or_dir, timestamp: str = "latest",
         base: Path | None = None) -> dict:
    """Loads a stored test: test map + history + results
    (store.clj:108-134)."""
    d = Path(name_or_dir)
    if not d.exists():
        d = (base or BASE) / str(name_or_dir) / timestamp
    d = d.resolve()
    with open(d / "test.json") as f:
        test = json.load(f)
    hpath = d / "history.jlog"
    if hpath.exists():
        test["history"] = fmt.read_history(hpath)
    res = load_results(d)
    if res is not None:
        test["results"] = res
    test["store_dir"] = str(d)
    return test


def tests(name: str | None = None, base: Path | None = None
          ) -> Iterator[Path]:
    """Yields all stored test dirs, newest first."""
    b = base or BASE
    if not b.exists():
        return
    names = [b / name] if name else sorted(b.iterdir())
    for nd in names:
        if not nd.is_dir() or nd.name in ("latest", "current"):
            continue
        for td in sorted(nd.iterdir(), reverse=True):
            if td.is_dir() and not td.is_symlink():
                yield td


def delete(name: str | None = None, base: Path | None = None) -> int:
    """Deletes stored tests (store.clj:514-531). Returns count."""
    n = 0
    for td in list(tests(name, base)):
        shutil.rmtree(td, ignore_errors=True)
        n += 1
    b = base or BASE
    for link in ([b / "latest", b / "current"]
                 + ([b / name / "latest"] if name else [])):
        if link.is_symlink() and not link.resolve().exists():
            link.unlink()
    return n
