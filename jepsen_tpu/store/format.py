"""On-disk history log: append-only, CRC-framed, chunk-sealed,
crash-recoverable.

Capability reference: jepsen/src/jepsen/store/format.clj — the reference
writes histories as CRC32-checked typed blocks inside a single container
file, sealing FressianStream chunks into a BigVector so a crash loses at
most the unsealed tail (format.clj:36-200, 182-200; the interpreter
appends ops while the test runs, interpreter.clj:251-253).

This implementation keeps the same guarantees with a simpler layout that
a C++ codec can also read/write:

  history.jlog:
    header: 8 bytes magic b"JTPUHIS1"
    record: [u32 payload_len][u32 crc32(payload)][payload bytes]
    payload: one JSON-encoded op dict (utf-8)

Records are flushed per-append (cheap at test op rates; the reference's
rates are ~20k ops/s and a buffered write+flush keeps up). On read, a
torn/corrupt tail record is dropped rather than failing the whole load —
exactly the reference's crash-recovery behavior.
"""

from __future__ import annotations

import bisect
import json
import struct
import threading
import zlib
from pathlib import Path
from typing import Any, Iterator

from ..history import History, Op, op as make_op

MAGIC = b"JTPUHIS1"
IDX_MAGIC = b"JTPUIDX1"
_HDR = struct.Struct("<II")
_IDX_ENTRY = struct.Struct("<qq")  # (op_count_so_far, byte_end)
_CRC = struct.Struct("<I")


def index_path(path) -> Path:
    return Path(str(path) + ".idx")


def _scan_path(path):
    """Yields (payload, end_offset) for intact records, via the native
    codec when available (one mmap-free bulk scan in C) else the
    Python walker."""
    path = Path(path)
    try:
        from .. import native

        if native.jlog() is not None:
            buf = path.read_bytes()
            if buf[:len(MAGIC)] != MAGIC:
                raise ValueError(f"{path}: bad magic")
            offs, _end = native.scan(buf, len(MAGIC))
            for a, b in offs:
                yield buf[a:b], b
            return
    except (ImportError, RuntimeError):
        pass
    with open(path, "rb") as f:
        if f.read(len(MAGIC)) != MAGIC:
            raise ValueError(f"{path}: bad magic")
        yield from _scan_records(f)


def _default(o):
    if isinstance(o, Op):
        return o.to_dict()
    if isinstance(o, (set, frozenset)):
        return sorted(o, key=repr)
    if isinstance(o, bytes):
        return o.decode("utf-8", "replace")
    return repr(o)


def encode_op(o: Op) -> bytes:
    return json.dumps(o.to_dict(), default=_default,
                      separators=(",", ":")).encode()


def decode_op(payload: bytes) -> Op:
    return make_op(**json.loads(payload))


class HistoryWriter:
    """Incremental history log writer with the interpreter's
    append/close/read_back interface. Every `chunk_size` appends, an
    entry [ops_so_far, byte_end] is sealed into a CRC'd sidecar index
    (<log>.idx), the analog of the reference's periodically-sealed
    BigVector chunks (store/format.clj:182-200): a crash loses at most
    the unsealed tail, and readers can count ops and seek chunks
    without decoding the whole log."""

    def __init__(self, path: Path, chunk_size: int = 4096):
        self.path = Path(path)
        self.chunk_size = chunk_size
        self.path.parent.mkdir(parents=True, exist_ok=True)
        # rebuild index state consistent with the (possibly truncated)
        # log: start the index fresh rather than trusting a stale one
        idx_path = index_path(self.path)
        if idx_path.exists():
            idx_path.unlink()
        self._idx = open(idx_path, "ab")
        self._idx.write(IDX_MAGIC)
        self._count = 0
        seals: list[int] = []
        end = 0
        if self.path.exists() and self.path.stat().st_size > 0:
            # Reopening after a crash: ONE scan yields both the valid
            # prefix (truncation point — appends after a torn tail
            # would be silently dropped by the recovering reader) and
            # the chunk seal offsets.
            try:
                with open(self.path, "rb") as f:
                    bad_magic = f.read(len(MAGIC)) != MAGIC
            except OSError:
                bad_magic = True
            end = 0 if bad_magic else len(MAGIC)
            if not bad_magic:
                for _payload, end in _scan_path(self.path):
                    self._count += 1
                    if self._count % self.chunk_size == 0:
                        seals.append(end)
            if end < self.path.stat().st_size:
                with open(self.path, "r+b") as f:
                    f.truncate(end)
        self._f = open(self.path, "ab")
        if self._f.tell() == 0:
            self._f.write(MAGIC)
            self._f.flush()
        for i, e in enumerate(seals):
            self._seal((i + 1) * self.chunk_size, e, flush=False)
        self._idx.flush()

    def _seal(self, count: int, byte_end: int, flush: bool = True
              ) -> None:
        entry = _IDX_ENTRY.pack(count, byte_end)
        self._idx.write(entry)
        self._idx.write(_CRC.pack(zlib.crc32(entry)))
        if flush:
            self._idx.flush()

    def append(self, o: Op) -> None:
        payload = encode_op(o)
        self._f.write(_HDR.pack(len(payload), zlib.crc32(payload)))
        self._f.write(payload)
        self._f.flush()
        self._count += 1
        if self._count % self.chunk_size == 0:
            self._seal(self._count, self._f.tell())

    def close(self) -> None:
        if not self._f.closed:
            self._f.flush()
            self._f.close()
        if not self._idx.closed:
            self._idx.flush()
            self._idx.close()

    def read_back(self) -> list[Op]:
        self.close()
        return list(read_ops(self.path))


def _scan_records(f) -> Iterator[tuple[bytes, int]]:
    """Walks intact CRC-framed records from just after the magic,
    yielding (payload, end_offset) and stopping at a torn/corrupt tail.
    The single framing walker behind both reads and reopen-truncation,
    so the writer can never truncate what the reader would accept."""
    end = len(MAGIC)
    while True:
        hdr = f.read(_HDR.size)
        if len(hdr) < _HDR.size:
            return  # clean EOF or torn header
        n, crc = _HDR.unpack(hdr)
        payload = f.read(n)
        if len(payload) < n or zlib.crc32(payload) != crc:
            return  # torn/corrupt tail: drop and recover
        end += _HDR.size + n
        yield payload, end


def _valid_prefix_end(path) -> int:
    """Byte offset just past the last intact record (0 if even the
    magic is bad, so the writer restarts the file)."""
    try:
        with open(path, "rb") as f:
            if f.read(len(MAGIC)) != MAGIC:
                return 0
    except OSError:
        return 0
    end = len(MAGIC)
    for _payload, end in _scan_path(path):
        pass
    return end


def read_ops(path) -> Iterator[Op]:
    """Reads ops, tolerating a torn tail (crash recovery)."""
    for payload, _end in _scan_path(path):
        yield decode_op(payload)


def read_history(path) -> History:
    return History(list(read_ops(path)), assign_indices=False)


def _read_index(path) -> list[tuple[int, int]]:
    """Sealed (op_count, byte_end) entries; torn/corrupt entries are
    dropped from the tail (same recovery rule as the log)."""
    p = index_path(path)
    out: list[tuple[int, int]] = []
    try:
        buf = p.read_bytes()
    except OSError:
        return out
    if buf[:len(IDX_MAGIC)] != IDX_MAGIC:
        return out
    pos = len(IDX_MAGIC)
    step = _IDX_ENTRY.size + _CRC.size
    while pos + step <= len(buf):
        entry = buf[pos:pos + _IDX_ENTRY.size]
        (crc,) = _CRC.unpack(
            buf[pos + _IDX_ENTRY.size:pos + step])
        if zlib.crc32(entry) != crc:
            break
        out.append(_IDX_ENTRY.unpack(entry))
        pos += step
    return out


class LazyHistory:
    """Lazy chunked history view over a log + its sidecar index
    (store/format.clj BigVector, 143-173: O(1) count via sealed chunk
    metadata, chunks decoded on demand, the unsealed tail scanned
    once). Supports len/iteration/indexing without ever decoding more
    than the chunks touched."""

    def __init__(self, path):
        self.path = Path(path)
        size = self.path.stat().st_size
        with open(self.path, "rb") as f:
            if f.read(len(MAGIC)) != MAGIC:
                raise ValueError(f"{path}: bad magic")
        # Sealed index entries are CRC'd and written only after their
        # records hit disk, so they need no re-validation: only the
        # unsealed tail past the last seal gets CRC-scanned. That keeps
        # open cost O(tail), not O(file).
        self._chunks = [(0, len(MAGIC))] + [
            (n, e) for n, e in _read_index(self.path) if e <= size]
        last_n, last_end = self._chunks[-1]
        self._tail_offsets: list[tuple[int, int]] = []
        with open(self.path, "rb") as f:
            f.seek(last_end)
            data = f.read(size - last_end)
        pos = 0
        while pos + _HDR.size <= len(data):
            n, crc = _HDR.unpack(data[pos:pos + _HDR.size])
            payload = data[pos + _HDR.size:pos + _HDR.size + n]
            if len(payload) < n or zlib.crc32(payload) != crc:
                break  # torn/corrupt tail
            a = last_end + pos + _HDR.size
            self._tail_offsets.append((a, a + n))
            pos += _HDR.size + n
        self._len = last_n + len(self._tail_offsets)
        self._counts = [n for n, _e in self._chunks]
        self._cache: dict[int, list] = {}

    def __len__(self) -> int:
        return self._len

    def _chunk_ops(self, ci: int) -> list:
        ops = self._cache.get(ci)
        if ops is None:
            start = self._chunks[ci][1]
            end = (self._chunks[ci + 1][1]
                   if ci + 1 < len(self._chunks) else None)
            with open(self.path, "rb") as f:
                f.seek(start)
                data = f.read((end - start) if end is not None
                              else self._tail_offsets[-1][1] - start
                              if self._tail_offsets else 0)
            ops = []
            pos = 0
            while pos + _HDR.size <= len(data):
                n, _crc = _HDR.unpack(data[pos:pos + _HDR.size])
                ops.append(decode_op(
                    data[pos + _HDR.size:pos + _HDR.size + n]))
                pos += _HDR.size + n
            if len(self._cache) > 4:  # keep a few hot chunks
                self._cache.pop(next(iter(self._cache)))
            self._cache[ci] = ops
        return ops

    def __getitem__(self, i: int):
        if i < 0:
            i += self._len
        if not 0 <= i < self._len:
            raise IndexError(i)
        # find the chunk whose [count_start, count_end) contains i
        ci = bisect.bisect_right(self._counts, i) - 1
        ops = self._chunk_ops(ci)
        return ops[i - self._counts[ci]]

    def __iter__(self):
        for ci in range(len(self._chunks)):
            yield from self._chunk_ops(ci)


def read_history_lazy(path) -> LazyHistory:
    return LazyHistory(path)


def write_history(path, ops, chunk_size: int = 4096) -> Path:
    """Bulk history export: frames whole chunks at a time (through the
    C codec when available) and seals the sidecar index per chunk —
    the batch analog of HistoryWriter for already-complete histories
    (re-exports, converters, test fixtures)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    try:
        from .. import native

        framer = native.frame if native.jlog() is not None else None
    except ImportError:
        framer = None
    if framer is None:
        def framer(payloads):
            return b"".join(
                _HDR.pack(len(p), zlib.crc32(p)) + p for p in payloads)
    idx_p = index_path(path)
    with open(path, "wb") as f, open(idx_p, "wb") as idx:
        f.write(MAGIC)
        idx.write(IDX_MAGIC)
        count = 0
        batch: list[bytes] = []

        def flush_batch():
            nonlocal count
            if not batch:
                return
            f.write(framer(batch))
            count += len(batch)
            batch.clear()
            if count % chunk_size == 0:
                entry = _IDX_ENTRY.pack(count, f.tell())
                idx.write(entry)
                idx.write(_CRC.pack(zlib.crc32(entry)))

        for o in ops:
            batch.append(encode_op(o))
            if len(batch) >= chunk_size - (count % chunk_size):
                flush_batch()
        flush_batch()
    return path


# ---------------------------------------------------------------------------
# Partial results: each checker's result lands on disk the moment it
# completes, so a crash mid-analysis leaves everything finished so far
# readable (store/format.clj PartialMap, 143-200; save-2! phases)
# ---------------------------------------------------------------------------

class PartialResultsWriter:
    """Append-only CRC-framed (key, result) log."""

    def __init__(self, path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._f = open(self.path, "wb")
        self._f.write(MAGIC)
        self._f.flush()
        self._lock = threading.Lock()

    def put(self, key, result) -> None:
        payload = json.dumps({"key": key, "result": jsonable(result)},
                             default=_default,
                             separators=(",", ":")).encode()
        with self._lock:
            self._f.write(_HDR.pack(len(payload), zlib.crc32(payload)))
            self._f.write(payload)
            self._f.flush()

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()


def read_partial_results(path) -> dict:
    """Whatever results survived, keyed by checker name."""
    out: dict = {}
    try:
        for payload, _end in _scan_path(path):
            d = json.loads(payload)
            out[d["key"]] = d["result"]
    except (OSError, ValueError):
        pass
    return out


# ---------------------------------------------------------------------------
# JSON round-trip for test maps / results
# ---------------------------------------------------------------------------

def jsonable(v: Any, depth: int = 0) -> Any:
    """Best-effort JSON view of a test/results value; non-data values
    (clients, generators, ...) degrade to their repr, mirroring the
    reference's :nonserializable-keys escape hatch (store.clj:92-106)."""
    if depth > 12:
        return repr(v)
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (list, tuple)):
        return [jsonable(x, depth + 1) for x in v]
    if isinstance(v, (set, frozenset)):
        return sorted((jsonable(x, depth + 1) for x in v), key=repr)
    if isinstance(v, dict):
        return {str(k): jsonable(x, depth + 1) for k, x in v.items()}
    if isinstance(v, Op):
        return jsonable(v.to_dict(), depth + 1)
    if hasattr(v, "isoformat"):
        return v.isoformat()
    return repr(v)
