"""On-disk history log: append-only, CRC-framed, chunk-sealed,
crash-recoverable.

Capability reference: jepsen/src/jepsen/store/format.clj — the reference
writes histories as CRC32-checked typed blocks inside a single container
file, sealing FressianStream chunks into a BigVector so a crash loses at
most the unsealed tail (format.clj:36-200, 182-200; the interpreter
appends ops while the test runs, interpreter.clj:251-253).

This implementation keeps the same guarantees with a simpler layout that
a C++ codec can also read/write:

  history.jlog:
    header: 8 bytes magic b"JTPUHIS1"
    record: [u32 payload_len][u32 crc32(payload)][payload bytes]
    payload: one JSON-encoded op dict (utf-8)

Records are flushed per-append (cheap at test op rates; the reference's
rates are ~20k ops/s and a buffered write+flush keeps up). On read, a
torn/corrupt tail record is dropped rather than failing the whole load —
exactly the reference's crash-recovery behavior.
"""

from __future__ import annotations

import json
import struct
import zlib
from pathlib import Path
from typing import Any, Iterator

from ..history import History, Op, op as make_op

MAGIC = b"JTPUHIS1"
_HDR = struct.Struct("<II")


def _default(o):
    if isinstance(o, Op):
        return o.to_dict()
    if isinstance(o, (set, frozenset)):
        return sorted(o, key=repr)
    if isinstance(o, bytes):
        return o.decode("utf-8", "replace")
    return repr(o)


def encode_op(o: Op) -> bytes:
    return json.dumps(o.to_dict(), default=_default,
                      separators=(",", ":")).encode()


def decode_op(payload: bytes) -> Op:
    return make_op(**json.loads(payload))


class HistoryWriter:
    """Incremental history log writer with the interpreter's
    append/close/read_back interface."""

    def __init__(self, path: Path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        if self.path.exists() and self.path.stat().st_size > 0:
            # Reopening after a crash: cut the file back to its last
            # intact record, or appends would land after a torn tail
            # and be silently dropped by the recovering reader.
            end = _valid_prefix_end(self.path)
            if end < self.path.stat().st_size:
                with open(self.path, "r+b") as f:
                    f.truncate(end)
        self._f = open(self.path, "ab")
        if self._f.tell() == 0:
            self._f.write(MAGIC)
            self._f.flush()
        self._count = 0

    def append(self, o: Op) -> None:
        payload = encode_op(o)
        self._f.write(_HDR.pack(len(payload), zlib.crc32(payload)))
        self._f.write(payload)
        self._f.flush()
        self._count += 1

    def close(self) -> None:
        if not self._f.closed:
            self._f.flush()
            self._f.close()

    def read_back(self) -> list[Op]:
        self.close()
        return list(read_ops(self.path))


def _scan_records(f) -> Iterator[tuple[bytes, int]]:
    """Walks intact CRC-framed records from just after the magic,
    yielding (payload, end_offset) and stopping at a torn/corrupt tail.
    The single framing walker behind both reads and reopen-truncation,
    so the writer can never truncate what the reader would accept."""
    end = len(MAGIC)
    while True:
        hdr = f.read(_HDR.size)
        if len(hdr) < _HDR.size:
            return  # clean EOF or torn header
        n, crc = _HDR.unpack(hdr)
        payload = f.read(n)
        if len(payload) < n or zlib.crc32(payload) != crc:
            return  # torn/corrupt tail: drop and recover
        end += _HDR.size + n
        yield payload, end


def _valid_prefix_end(path) -> int:
    """Byte offset just past the last intact record (0 if even the
    magic is bad, so the writer restarts the file)."""
    with open(path, "rb") as f:
        if f.read(len(MAGIC)) != MAGIC:
            return 0
        end = len(MAGIC)
        for _payload, end in _scan_records(f):
            pass
        return end


def read_ops(path) -> Iterator[Op]:
    """Reads ops, tolerating a torn tail (crash recovery)."""
    path = Path(path)
    with open(path, "rb") as f:
        if f.read(len(MAGIC)) != MAGIC:
            raise ValueError(f"{path}: bad magic")
        for payload, _end in _scan_records(f):
            yield decode_op(payload)


def read_history(path) -> History:
    return History(list(read_ops(path)), assign_indices=False)


# ---------------------------------------------------------------------------
# JSON round-trip for test maps / results
# ---------------------------------------------------------------------------

def jsonable(v: Any, depth: int = 0) -> Any:
    """Best-effort JSON view of a test/results value; non-data values
    (clients, generators, ...) degrade to their repr, mirroring the
    reference's :nonserializable-keys escape hatch (store.clj:92-106)."""
    if depth > 12:
        return repr(v)
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (list, tuple)):
        return [jsonable(x, depth + 1) for x in v]
    if isinstance(v, (set, frozenset)):
        return sorted((jsonable(x, depth + 1) for x in v), key=repr)
    if isinstance(v, dict):
        return {str(k): jsonable(x, depth + 1) for k, x in v.items()}
    if isinstance(v, Op):
        return jsonable(v.to_dict(), depth + 1)
    if hasattr(v, "isoformat"):
        return v.isoformat()
    return repr(v)
