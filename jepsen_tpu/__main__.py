"""Built-in runner: `python -m jepsen_tpu <test|test-all|serve> ...`.

Drives the bundled workloads (jepsen_tpu.workloads.REGISTRY) against
the in-memory databases in jepsen_tpu.testing when --no-ssh is given —
the clusterless analog of each suite's core.clj -main (e.g.
tidb/src/tidb/core.clj:47-60) — or against real nodes over SSH.
"""

from __future__ import annotations

from . import checker as chk
from . import cli, testing, workloads
from . import generator as gen

# workload name -> in-memory client factory (testing.py fixtures)
CLIENTS = {
    "register": lambda: testing.KVClient(testing.KVState()),
    "bank": lambda: testing.BankClient(
        testing.BankState(list(range(8)))),
    "set": lambda: testing.SetClient(),
    "set-full": lambda: testing.SetClient(),
    "queue": lambda: testing.QueueClient(),
    "counter": lambda: testing.CounterClient(),
    "unique-ids": lambda: testing.UniqueIdsClient(),
    "long-fork": lambda: testing.TxnClient(),
    "append": lambda: testing.TxnClient(),
    "wr": lambda: testing.TxnClient(),
}


def make_test(opts: dict) -> dict:
    name = opts.get("workload", "register")
    if name not in workloads.REGISTRY:
        raise SystemExit(f"unknown workload {name!r}; "
                         + cli.one_of(workloads.REGISTRY))
    w = workloads.REGISTRY[name](
        {"ops": opts.get("ops", 500),
         "ops_per_key": opts.get("ops", 500) // 8 or 1,
         # thread groups must divide concurrency (independent.clj)
         "group_size": opts["concurrency"]})
    test = testing.noop_test()
    test.update(
        name=f"{name}-demo",
        nodes=opts["nodes"],
        concurrency=opts["concurrency"],
        ssh=opts["ssh"],
        time_limit=opts.get("time_limit"),
        client=CLIENTS[name](),
        checker=chk.compose({"workload": w["checker"],
                             "stats": chk.stats(),
                             "perf": chk.perf(),
                             "timeline": chk.timeline()}),
        generator=gen.clients(
            gen.time_limit(opts.get("time_limit", 60),
                           gen.stagger(1.0 / opts.get("rate", 100),
                                       w["generator"]))))
    for k, v in w.items():
        if k not in ("generator", "checker"):
            test[k] = v
    return test


def make_all_tests(opts: dict):
    names = (opts.get("workload") or "").split(",") if \
        opts.get("workload") else list(CLIENTS)
    for name in names:
        o = dict(opts)
        o["workload"] = name
        yield make_test(o)


def _workload_opt(p):
    p.add_argument("--workload", default="register",
                   help="Workload name. " + cli.one_of(CLIENTS))
    p.add_argument("--ops", type=int, default=500,
                   help="Rough op budget for the workload generator.")
    p.add_argument("--rate", type=float, default=100,
                   help="Target ops/sec across all workers.")
    return p


def main(argv=None) -> None:
    commands = {}
    commands.update(cli.single_test_cmd(make_test,
                                        parser_fn=_workload_opt))
    commands.update(cli.test_all_cmd(make_all_tests,
                                     parser_fn=_workload_opt))
    commands.update(cli.serve_cmd())
    cli.run_cli(commands, argv)


if __name__ == "__main__":
    main()
