"""Built-in runner: `python -m jepsen_tpu <test|test-all|serve> ...`.

Drives the bundled workloads (jepsen_tpu.workloads.REGISTRY) against
the in-memory databases in jepsen_tpu.testing when --no-ssh is given —
the clusterless analog of each suite's core.clj -main (e.g.
tidb/src/tidb/core.clj:47-60) — or against real nodes over SSH.
"""

from __future__ import annotations

from . import checker as chk
from . import cli, nodeprobe, testing, workloads
from . import generator as gen
from . import nemesis as jnemesis

# the synthetic DB log every clusterless demo node "writes" — the node
# probe tails it and tags seeded election/OOM lines, so demo runs
# exercise the full node-plane path (jepsen_tpu.nodeprobe)
DEMO_LOG = "/var/log/db.log"

# --nemesis packages for clusterless demo runs: the faults fire
# against the dummy control plane (commands logged, nothing disturbed,
# activations recorded), so every run honestly exercises its
# fault × workload × anomaly coverage cells — including the explicit
# "fault fired, anomaly checked, none found" negatives the atlas
# needs (jepsen_tpu.coverage; doc/observability.md).
NEMESES = {
    "none": None,
    "partition": jnemesis.partition_random_halves,
    "partition-node": jnemesis.partition_random_node,
    "partition-ring": jnemesis.partition_majorities_ring,
    "hammer": lambda: jnemesis.hammer_time("demo-daemon"),
}


def _make_demo_responder():
    """A demo responder: answers the partitioner's discovery commands
    (getent node-IP resolution, ip-link device discovery — so faults
    fire against the dummy control plane instead of crashing the
    nemesis process) and the node probe's compound /proc + log-tail
    command with seeded synthetic node state. Each test built by
    make_test gets its OWN instance, so a second run in the same
    process re-seeds tick counters and the synthetic log instead of
    re-tailing the previous run's content at stale timestamps."""
    synth = nodeprobe.synthetic_responder()

    def respond(node, action):
        cmd = action.cmd
        if cmd.startswith("getent ahostsv4"):
            host = cmd.split()[-1]
            digits = "".join(ch for ch in str(host) if ch.isdigit())
            n = int(digits) % 250 + 1 if digits else \
                sum(str(host).encode()) % 250 + 1
            return f"10.0.0.{n}   STREAM {host}"
        if cmd == "ip -o link show":
            return ("1: lo: <LOOPBACK,UP> mtu 65536\n"
                    "2: eth0: <BROADCAST,MULTICAST,UP> mtu 1500")
        return synth(node, action)

    return respond


# module-level instance for direct importers (tests that just need
# the discovery answers); make_test builds a fresh one per test
_demo_responder = _make_demo_responder()

# workload name -> in-memory client factory (testing.py fixtures)
CLIENTS = {
    "register": lambda: testing.KVClient(testing.KVState()),
    "bank": lambda: testing.BankClient(
        testing.BankState(list(range(8)))),
    "set": lambda: testing.SetClient(),
    "set-full": lambda: testing.SetClient(),
    "queue": lambda: testing.QueueClient(),
    "counter": lambda: testing.CounterClient(),
    "dirty-read": lambda: testing.DirtyReadClient(),
    "unique-ids": lambda: testing.UniqueIdsClient(),
    "long-fork": lambda: testing.TxnClient(),
    "monotonic": lambda: testing.MonotonicClient(),
    "sequential": lambda: testing.SequentialClient(),
    "append": lambda: testing.TxnClient(),
    "wr": lambda: testing.TxnClient(),
    "kafka": lambda: testing.KafkaClient(),
    "causal": lambda: testing.CausalClient(),
    "causal-reverse": lambda: testing.PerKeySetClient(),
    "adya-g2": lambda: testing.G2Client(),
    "lock": lambda: testing.LockClient(fences=False),
    "owner-lock": lambda: testing.LockClient(fences=False),
    "fenced-lock": lambda: testing.LockClient(),
    "reentrant-lock": lambda: testing.LockClient(reentrant_limit=2),
    "semaphore": lambda: testing.LockClient(
        testing.LockState(permits=2), semaphore=True),
    "upsert": lambda: testing.UpsertClient(),
    "run-coverage": lambda: testing.SchedulerClient(),
    "pages": lambda: testing.PagesClient(),
    "multimonotonic": lambda: testing.MultiRegClient(),
    "lost-updates": lambda: testing.VersionedSetClient(),
    "version-divergence": lambda: testing.VersionRegClient(),
}


def _workload_opts(name: str, opts: dict) -> dict:
    """Per-workload option scoping: only the knobs each workload
    actually reads, so a CLI default can't silently reshape unrelated
    workloads (e.g. long-fork's read-group size or adya's key count)."""
    ops = opts.get("ops", 500)
    wopts = {"ops": ops}
    if name == "register":
        # all threads share one key group; keys rotate sequentially
        wopts.update({"group-size": opts["concurrency"],
                      "ops_per_key": ops // 8 or 1})
    elif name == "causal-reverse":
        wopts.update({"per-key-limit": ops // 4 or 1})
    elif name == "dirty-read":
        wopts.update({"concurrency": opts["concurrency"]})
    elif name == "sequential":
        # reserve() would otherwise hand every thread to the writers,
        # leaving zero readers (valid? unknown)
        wopts.update({"writers": workloads.sequential.default_writers(
            opts["concurrency"])})
    elif name == "multimonotonic":
        # half the threads write (one key each), half read
        wopts.update({"writers": max(1, opts["concurrency"] // 2)})
    elif name == "run-coverage":
        wopts.update({"jobs": min(ops, 50)})
    elif name in ("upsert", "pages", "lost-updates",
                  "version-divergence"):
        # independent-key groups must divide the thread count; budget
        # ops per key so every key reaches its final-read phase inside
        # the time limit (an unread key is an honest 'unknown').
        # pages' atomic insert size is its own knob (elements_per_add),
        # NOT group_size — thread count must never resize the groups.
        wopts.update({"group_size": opts["concurrency"],
                      "ops_per_key": max(ops // 8, 1)})
    return wopts


# workloads whose concurrent generator uses fixed thread pairs
_PAIRED = {"adya-g2", "causal-reverse"}


def make_test(opts: dict) -> dict:
    name = opts.get("workload", "register")
    if name not in workloads.REGISTRY:
        raise SystemExit(f"unknown workload {name!r}; "
                         + cli.one_of(workloads.REGISTRY))
    w = workloads.REGISTRY[name](_workload_opts(name, opts))
    if name in _PAIRED and opts["concurrency"] % 2:
        # pair-based generators need an even thread count; park the
        # last thread instead of failing the divisibility assert
        usable = set(range(opts["concurrency"] - 1))
        w = dict(w)
        w["generator"] = gen.on_threads(usable, w["generator"])
    test = testing.noop_test()
    test.update(
        name=f"{name}-demo",
        nodes=opts["nodes"],
        concurrency=opts["concurrency"],
        ssh=opts["ssh"],
        time_limit=opts.get("time_limit"),
        client=CLIENTS[name](),
        checker=chk.compose({"workload": w["checker"],
                             "stats": chk.stats(),
                             "perf": chk.perf(),
                             "timeline": chk.timeline()}),
        generator=_generator(opts, w))
    if opts.get("trace"):
        # per-op causal tracing (optrace.jsonl + anomaly provenance)
        test["trace?"] = True
    if opts.get("quarantine"):
        # per-node circuit breakers: a dead node degrades the run
        # instead of aborting it (doc/robustness.md)
        test["quarantine?"] = True
    if opts.get("xla_trace"):
        # capture an XLA profiler trace (xplane protobufs, viewable in
        # xprof/TensorBoard) of the analysis phase into the run's
        # store dir (doc/observability.md)
        test["xla-trace?"] = True
    if not opts.get("no_nodeprobe"):
        # the node observability plane is on by default: per-node
        # resource/clock/log sampling into nodes.jsonl, clusterless
        # demo nodes answering with seeded synthetic /proc data
        test["nodeprobe?"] = True
        if opts.get("nodeprobe_interval"):
            test["nodeprobe_interval_s"] = float(
                opts["nodeprobe_interval"])
        if (opts.get("ssh") or {}).get("dummy"):
            test["node_log_files"] = [DEMO_LOG]
    nem_name = opts.get("nemesis") or "none"
    if nem_name not in NEMESES:
        raise SystemExit(f"unknown nemesis {nem_name!r}; "
                         + cli.one_of(NEMESES))
    if nem_name != "none":
        test["nemesis"] = NEMESES[nem_name]()
    if (opts.get("ssh") or {}).get("dummy") and not test.get("remote"):
        # the demo responder answers BOTH the partitioner's discovery
        # commands and the node probe's compound /proc probe; a fresh
        # instance per test keeps synthetic node state run-scoped
        from .control.dummy import DummyRemote

        test["remote"] = DummyRemote(_make_demo_responder())
    for k, v in w.items():
        if k not in ("generator", "checker", "final_generator"):
            test[k] = v
    # crash-safety knobs (doc/robustness.md): the reconstructible spec
    # lets `analyze <run-dir>` rebuild this exact checker stack after a
    # control-process crash; persistent wgl segment checkpoints make
    # that re-analysis resume instead of re-search.
    test["spec"] = {"workload": name, "opts": _spec_opts(opts)}
    test.setdefault("checkpoint?", True)
    return test


def _spec_opts(opts: dict) -> dict:
    """The JSON-representable subset of the option map — everything
    make_test needs to rebuild the same test (store.save_spec writes
    it as spec.json)."""
    def plain(v):
        if isinstance(v, (str, int, float, bool, type(None))):
            return True
        if isinstance(v, (list, tuple)):
            return all(plain(x) for x in v)
        if isinstance(v, dict):
            return all(isinstance(k, str) and plain(x)
                       for k, x in v.items())
        return False

    return {k: v for k, v in opts.items() if plain(v)}


def _generator(opts: dict, w: dict):
    client_gen = gen.stagger(1.0 / opts.get("rate", 100),
                             w["generator"])
    nem_name = opts.get("nemesis") or "none"
    if nem_name != "none":
        # the canonical sleep/start/sleep/stop cycle on the nemesis
        # thread, bounded by the same time limit as the clients
        main = gen.time_limit(
            opts.get("time_limit", 60),
            gen.clients(client_gen,
                        jnemesis.start_stop_cycle(
                            opts.get("nemesis_interval", 5.0))))
    else:
        main = gen.clients(
            gen.time_limit(opts.get("time_limit", 60), client_gen))
    final = w.get("final_generator")
    if final is None:
        return main
    # a workload's final phase (e.g. monotonic's reads) runs after the
    # time limit, like the suites' heal-then-read shape
    return gen.phases(main, gen.clients(final))


def make_all_tests(opts: dict):
    names = (opts.get("workload") or "").split(",") if \
        opts.get("workload") else list(CLIENTS)
    for name in names:
        o = dict(opts)
        o["workload"] = name
        yield make_test(o)


def _workload_opt(p):
    p.add_argument("--workload", default="register",
                   help="Workload name. " + cli.one_of(CLIENTS))
    p.add_argument("--ops", type=int, default=500,
                   help="Rough op budget for the workload generator.")
    p.add_argument("--rate", type=float, default=100,
                   help="Target ops/sec across all workers.")
    p.add_argument("--trace", action="store_true",
                   help="Record the per-op causal trace "
                        "(optrace.jsonl; see doc/observability.md).")
    p.add_argument("--quarantine", action="store_true",
                   help="Quarantine persistently unreachable nodes "
                        "and continue the run :degraded instead of "
                        "aborting (doc/robustness.md).")
    p.add_argument("--xla-trace", action="store_true",
                   help="Drop an XLA profiler trace of the analysis "
                        "phase into the run's store dir "
                        "(<run>/xla-trace, xprof/TensorBoard format).")
    p.add_argument("--no-nodeprobe", action="store_true",
                   help="Disable the node observability plane "
                        "(per-node resource/clock/log sampling into "
                        "nodes.jsonl; see doc/observability.md).")
    p.add_argument("--nodeprobe-interval", type=float, default=None,
                   metavar="SECS",
                   help="Node probe tick interval (default 1s).")
    p.add_argument("--nemesis", default="none",
                   help="Fault package to run against the workload "
                        "(coverage atlas column). " + cli.one_of(
                            NEMESES))
    p.add_argument("--nemesis-interval", type=float, default=5.0,
                   help="Seconds between nemesis start/stop phases.")
    return p


def main(argv=None) -> None:
    commands = {}
    commands.update(cli.single_test_cmd(make_test,
                                        parser_fn=_workload_opt))
    commands.update(cli.test_all_cmd(make_all_tests,
                                     parser_fn=_workload_opt))
    commands.update(cli.serve_cmd())
    commands.update(cli.telemetry_cmd())
    commands.update(cli.profile_cmd())
    commands.update(cli.nodes_cmd())
    commands.update(cli.trace_cmd())
    commands.update(cli.certify_cmd())
    commands.update(cli.analyze_cmd(make_test))
    commands.update(cli.coverage_cmd(list(workloads.REGISTRY)))
    commands.update(cli.lint_cmd())
    commands.update(cli.fleet_cmd())
    cli.run_cli(commands, argv)


if __name__ == "__main__":
    main()
