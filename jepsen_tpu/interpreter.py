"""The execution engine: interprets operations from a pure generator,
spawning one worker thread per logical thread, applying ops through
clients/nemeses, and journaling invocations + completions to a history.

Capability reference: jepsen/src/jepsen/generator/interpreter.clj (Worker
protocol 22-34, ClientWorker 36-70, spawn-worker 102-167, run! 184-337).
The hot-loop structure is preserved: poll completions first (they're
latency-sensitive), then ask the generator, dispatch with a 1-slot
inbound queue per worker, crash-to-:info conversion, process
reincarnation on :info, and incremental history writes.
"""

from __future__ import annotations

import logging
import queue
import threading
import traceback
from typing import Any

from . import client as jclient
from . import generator as gen
from . import telemetry
from . import tracing
from . import util
from .generator.context import NEMESIS
from .history import History, Op

logger = logging.getLogger(__name__)

# When the generator is :pending, the max interval before re-checking (µs)
# (interpreter.clj:169-173).
MAX_PENDING_INTERVAL_US = 1000


def goes_in_history(op: Op) -> bool:
    """:sleep and :log ops are not journaled (interpreter.clj:175-182)."""
    return op.type not in ("sleep", "log")


class Worker:
    """Stateful per-thread op executor; all calls on one thread
    (interpreter.clj:22-34)."""

    def open(self, test, wid) -> "Worker":
        return self

    def invoke(self, test, op: Op) -> Op:
        raise NotImplementedError

    def close(self, test) -> None:
        pass


class ClientWorker(Worker):
    """Wraps a client, reopening it whenever the process changes and the
    client isn't reusable (interpreter.clj:36-70)."""

    def __init__(self, node):
        self.node = node
        self.process = None
        self.client = None

    def invoke(self, test, op):
        while True:
            if (self.process != op.process
                    and not jclient.is_reusable(self.client, test)):
                self.close(test)
                try:
                    c = jclient.validate(test["client"])
                    if jclient.should_trace(test):
                        # the traced_client wrapper (dgraph trace.clj
                        # analog): each client call becomes a child
                        # span of the op's trace context
                        c = jclient.Traced(c)
                    self.client = c.open(test, self.node)
                    self.process = op.process
                except Exception as e:  # noqa: BLE001
                    logger.warning("Error opening client: %s", e)
                    self.client = None
                    return op.copy(type="fail",
                                   error=["no-client", str(e)])
                continue
            return self.client.invoke(test, op)

    def close(self, test):
        if self.client is not None:
            self.client.close(test)
            self.client = None


class NemesisWorker(Worker):
    def invoke(self, test, op):
        return test["nemesis"].invoke(test, op)


class ClientNemesisWorker(Worker):
    """Spawns client workers for integer ids, a nemesis worker otherwise
    (interpreter.clj:81-95)."""

    def open(self, test, wid):
        if isinstance(wid, int):
            nodes = list(test.get("nodes") or [None])
            return ClientWorker(nodes[wid % len(nodes)])
        return NemesisWorker()


def spawn_worker(test, out: queue.Queue, worker: Worker, wid):
    """One thread + 1-slot inbound queue per worker
    (interpreter.clj:102-167). Returns {'id','thread','in'}."""
    inq: queue.Queue = queue.Queue(maxsize=1)

    def run():
        import time as _t

        w = worker.open(test, wid)
        # per-op stats accumulate locally and flush once at exit: the
        # hot loop must not contend on the recorder's lock across all
        # worker threads (the throughput floor test polices this path)
        tel = telemetry.get()
        tracer = tracing.get()
        epoch0 = tel.epoch
        invoke_ns = 0
        type_counts: dict = {}
        crashes = 0
        try:
            while True:
                op = inq.get()
                t0 = None
                try:
                    if op.type == "exit":
                        return
                    if op.type == "sleep":
                        _t.sleep(op.value)
                        out.put(op)
                    elif op.type == "log":
                        logger.info("%s", op.value)
                        out.put(op)
                    else:
                        t0 = _t.monotonic_ns()
                        if tracer.enabled:
                            # mint the op's trace context: trace id =
                            # the invocation's op index, so client/
                            # remote child spans join the history
                            with tracer.op_span(op) as trec:
                                op2 = w.invoke(test, op)
                                if trec is not None:
                                    trec["status"] = op2.type
                        else:
                            op2 = w.invoke(test, op)
                        invoke_ns += _t.monotonic_ns() - t0
                        t0 = None
                        type_counts[op2.type] = type_counts.get(
                            op2.type, 0) + 1
                        out.put(op2)
                except Exception as e:  # noqa: BLE001 - crash becomes :info
                    if t0 is not None:
                        # crashed invokes still spent client time (a
                        # 30s timeout-then-raise is exactly the kind
                        # of wait this counter exists to expose)
                        invoke_ns += _t.monotonic_ns() - t0
                    logger.warning("Process %s crashed: %s", op.process, e)
                    crashes += 1
                    out.put(op.copy(
                        type="info",
                        exception=traceback.format_exc(),
                        error=f"indeterminate: {e}"))
        finally:
            # abnormal interpreter exits signal workers but don't join
            # them, so this finally may fire after a LATER run reset
            # the recorder — the epoch check keeps a straggler's tallies
            # out of that run's metrics (the crashed run's artifacts
            # simply miss this worker's counts, which is best-effort)
            if tel.epoch == epoch0:
                if invoke_ns:
                    tel.count("interpreter.invoke_ns", invoke_ns)
                for ty, n in type_counts.items():
                    tel.count(f"interpreter.ops.{ty}", n)
                if crashes:
                    tel.count("interpreter.worker-crashes", crashes)
            try:
                w.close(test)
            except Exception:  # noqa: BLE001
                logger.exception("Error closing worker %s", wid)

    t = threading.Thread(target=run, name=f"jepsen-worker-{wid}", daemon=True)
    t.start()
    return {"id": wid, "thread": t, "in": inq}


class MemoryHistoryWriter:
    """In-memory history sink (the disk-backed writer lives in
    jepsen_tpu.store.format)."""

    def __init__(self):
        self.ops: list[Op] = []

    def append(self, op: Op) -> None:
        self.ops.append(op)

    def close(self) -> None:
        pass

    def read_back(self) -> History:
        return History(self.ops, assign_indices=False)


def run(test: dict) -> dict:
    """Runs (:generator test) against (:client test)/(:nemesis test),
    returning the test with a completed :history (interpreter.clj:184-337).
    """
    writer = test.get("history_writer") or MemoryHistoryWriter()
    ctx = gen.context(test)
    worker_ids = ctx.all_thread_names()
    completions: queue.Queue = queue.Queue(maxsize=len(worker_ids))
    workers = [spawn_worker(test, completions, ClientNemesisWorker(), wid)
               for wid in worker_ids]
    invocations = {w["id"]: w["in"] for w in workers}
    g = gen.validate(gen.friendly_exceptions(test.get("generator")))
    test = dict(test)
    test.pop("generator", None)

    # live-observability hooks (both optional): the monitor samples
    # rates/in-flight/latencies, the watchdog checks safety online.
    # Their calls are a few dict updates each — the throughput-floor
    # test runs with both enabled to police this path.
    mon = test.get("monitor")
    wd = test.get("watchdog")
    if wd is not None and not hasattr(wd, "observe"):
        wd = None  # an unbuilt spec (core.run builds the object)

    op_index = 0
    outstanding = 0
    poll_timeout_us = 0
    # local tallies, flushed once below — no recorder locking in the
    # hot loop (same rule as the worker threads)
    dispatched = 0
    stalls = 0

    def finish():
        """Drains workers, closes the writer, reads the history back."""
        for q in invocations.values():
            q.put(Op(type="exit"))
        for w in workers:
            w["thread"].join()
        writer.close()
        test["history"] = writer.read_back()
        return test

    try:
        while True:
            op2 = None
            if poll_timeout_us > 0:
                try:
                    op2 = completions.get(timeout=poll_timeout_us / 1e6)
                except queue.Empty:
                    op2 = None
            else:
                try:
                    op2 = completions.get_nowait()
                except queue.Empty:
                    op2 = None

            if op2 is not None:
                # Completion path (interpreter.clj:228-256).
                thread = ctx.process_to_thread_name(op2.process)
                now = util.relative_time_nanos()
                op2 = op2.copy(index=op_index, time=now)
                ctx = ctx.free_thread(now, thread)
                g = gen.update(g, test, ctx, op2)
                if thread != NEMESIS and (op2.type == "info"
                                          or op2.get("end_process?")):
                    ctx = ctx.with_next_process(thread)
                if goes_in_history(op2):
                    writer.append(op2)
                    op_index += 1
                    if mon is not None:
                        mon.on_complete(op2, thread, now)
                    if wd is not None:
                        wd.observe(op2)
                        if wd.tripped and wd.early_abort:
                            # safety already lost: stop generating,
                            # keep what we have (core.analyze still
                            # runs the full checkers over it)
                            logger.warning(
                                "watchdog tripped; aborting run early")
                            test["aborted"] = "watchdog"
                            return finish()
                outstanding -= 1
                poll_timeout_us = 0
                continue

            # Ask the generator (interpreter.clj:258-318).
            now = util.relative_time_nanos()
            ctx = ctx.with_time(now)
            res = gen.op(g, test, ctx)
            if res is None:
                if outstanding > 0:
                    poll_timeout_us = MAX_PENDING_INTERVAL_US
                    continue
                # Done: drain workers, close writer, read history back.
                return finish()

            op_, g2 = res
            if op_ is gen.PENDING:
                # Keep the pre-call generator state, like the reference
                # (interpreter.clj:290-291).
                stalls += 1
                if mon is not None:
                    mon.on_stall()
                poll_timeout_us = MAX_PENDING_INTERVAL_US
                continue

            if now < op_.time:
                # Not due yet: leave g unconsumed and re-ask once the op
                # is due or a completion changes circumstances
                # (interpreter.clj:294-300).
                poll_timeout_us = max(1, (op_.time - now) // 1000)
                continue

            # Dispatch (interpreter.clj:302-318).
            thread = ctx.process_to_thread_name(op_.process)
            op_ = op_.copy(index=op_index)
            if goes_in_history(op_):
                writer.append(op_)
                op_index += 1
                if mon is not None:
                    mon.on_dispatch(op_, thread, now)
                if wd is not None:
                    wd.observe(op_)
            invocations[thread].put(op_)
            dispatched += 1
            ctx = ctx.busy_thread(op_.time, thread)
            g = gen.update(g2, test, ctx, op_)
            outstanding += 1
            poll_timeout_us = 0
    except BaseException:
        logger.info("Shutting down workers after abnormal exit")
        for w in workers:
            if w["thread"].is_alive():
                try:
                    w["in"].put_nowait(Op(type="exit"))
                except queue.Full:
                    pass
        raise
    finally:
        tel = telemetry.get()
        if dispatched:
            tel.count("interpreter.dispatched", dispatched)
        if stalls:
            tel.count("interpreter.generator-stalls", stalls)
