"""Command-line runner harness.

Capability reference: jepsen/src/jepsen/cli.clj — test-opt-spec
standard flags (64-206: --node/--nodes/--nodes-file/--username/
--password/--concurrency "2n" syntax/--test-count/--time-limit/
--no-ssh/--leave-db-running), test-opt-fn option normalization
(230-255), run! subcommand dispatcher with exit codes (258-335),
serve-cmd (336-354), single-test-cmd (355-442), test-all run/summary/
exit (443-530).

Exit codes mirror the reference: 0 pass, 1 invalid, 2 unknown,
254 usage error, 255 crash.
"""

from __future__ import annotations

import argparse
import logging
import sys
import time
from typing import Callable

from . import util

logger = logging.getLogger(__name__)

DEFAULT_NODES = ["n1", "n2", "n3", "n4", "n5"]


def one_of(coll) -> str:
    names = sorted(coll.keys() if isinstance(coll, dict) else coll)
    return "Must be one of " + ", ".join(str(n) for n in names)


def _concurrency(s: str) -> str:
    import re

    if not re.fullmatch(r"\d+n?", s):
        raise argparse.ArgumentTypeError(
            "Must be an integer, optionally followed by n.")
    return s


def add_test_opts(p: argparse.ArgumentParser) -> argparse.ArgumentParser:
    """The standard test flags (cli.clj test-opt-spec, 64-206)."""
    p.add_argument("-n", "--node", action="append", dest="node",
                   metavar="HOSTNAME", default=None,
                   help="Node to run the test on; repeatable.")
    p.add_argument("--nodes", metavar="NODE_LIST",
                   help="Comma-separated list of node hostnames.")
    p.add_argument("--nodes-file", metavar="FILENAME",
                   help="File of node hostnames, one per line.")
    p.add_argument("--username", default="root",
                   help="Username for logins")
    p.add_argument("--password", default="root",
                   help="Password for sudo access")
    p.add_argument("--strict-host-key-checking", action="store_true",
                   help="Whether to check host keys")
    p.add_argument("--no-ssh", action="store_true",
                   help="Don't establish SSH connections (dummy remote).")
    p.add_argument("--ssh-private-key", metavar="FILE",
                   help="Path to an SSH identity file")
    p.add_argument("--concurrency", default="1n", type=_concurrency,
                   help="Worker count; an integer, optionally followed "
                        "by n to multiply by the node count (e.g. 3n).")
    p.add_argument("--leave-db-running", action="store_true",
                   help="Leave the database running after the test.")
    p.add_argument("--test-count", type=int, default=1,
                   help="How many times to repeat the test.")
    p.add_argument("--time-limit", type=int, default=60,
                   help="Test duration excluding setup/teardown, secs.")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="Persistent XLA compilation cache directory "
                        "(or 0 to disable). Defaults to "
                        "store/.xla-cache; also settable via "
                        "JEPSEN_TPU_COMPILE_CACHE (doc/spmd.md).")
    return p


def test_opt_fn(options: argparse.Namespace) -> dict:
    """Normalizes parsed options into a test-options dict
    (cli.clj test-opt-fn: parse-nodes, parse-concurrency,
    rename-ssh-options)."""
    o = vars(options).copy()
    if o.get("nodes_file"):
        with open(o["nodes_file"]) as f:
            nodes = [ln.strip() for ln in f if ln.strip()]
    elif o.get("nodes"):
        nodes = [n.strip() for n in o["nodes"].split(",") if n.strip()]
    elif o.get("node"):
        nodes = list(o["node"])
    else:
        nodes = list(DEFAULT_NODES)
    o["nodes"] = nodes
    o["concurrency"] = util.coll_scaled(o.get("concurrency", "1n"),
                                        len(nodes))
    o["ssh"] = {
        "username": o.pop("username", "root"),
        "password": o.pop("password", "root"),
        "strict_host_key_checking": o.pop("strict_host_key_checking",
                                          False),
        "private_key_path": o.pop("ssh_private_key", None),
        "dummy": o.pop("no_ssh", False),
    }
    o["leave_db_running?"] = o.pop("leave_db_running", False)
    cache = o.pop("compile_cache", None)
    if cache is not None:
        # the kernel jit factories read the env knob lazily
        # (jepsen_tpu.tpu.spmd.enable_compile_cache), so setting it
        # here covers every checker launch in this process
        import os

        os.environ["JEPSEN_TPU_COMPILE_CACHE"] = cache
    o.pop("node", None)
    o.pop("nodes_file", None)
    return o


def run_test_n_times(test_fn: Callable[[dict], dict],
                     opts: dict) -> int:
    """single-test-cmd's run loop (cli.clj:389-399): runs test-count
    tests, returning the worst exit code."""
    from . import core

    worst = 0
    for _ in range(opts.get("test_count", 1)):
        test = core.run(test_fn(opts))
        valid = (test.get("results") or {}).get("valid?")
        if valid is False:
            return 1
        if valid == "unknown":
            worst = max(worst, 2)
    return worst


def test_all_run_tests(tests) -> dict:
    """Runs tests, grouping store paths by outcome
    (cli.clj:443-461). Outcomes: True, False, 'unknown', 'crashed'."""
    from . import core
    from . import store as jstore

    out: dict = {}
    for t in tests:
        t = core.prepare_test(t)
        where = str(jstore.test_dir(t))
        try:
            t = core.run(t)
            key = (t.get("results") or {}).get("valid?")
        except Exception:  # noqa: BLE001
            logger.exception("Test crashed")
            key = "crashed"
        out.setdefault(key, []).append(where)
    return out


def test_all_print_summary(results: dict) -> dict:
    """Prints grouped outcomes (cli.clj:463-492)."""
    sections = [(True, "Successful tests"),
                ("unknown", "Indeterminate tests"),
                ("crashed", "Crashed tests"),
                (False, "Failed tests")]
    for key, title in sections:
        if results.get(key):
            print(f"\n# {title}\n")
            for p in results[key]:
                print(p)
    print()
    print(len(results.get(True, [])), "successes")
    print(len(results.get("unknown", [])), "unknown")
    print(len(results.get("crashed", [])), "crashed")
    print(len(results.get(False, [])), "failures")
    return results


def test_all_exit_code(results: dict) -> int:
    """255 if crashed, 2 if unknown, 1 if invalid, 0 otherwise
    (cli.clj:494-502)."""
    if results.get("crashed"):
        return 255
    if results.get("unknown"):
        return 2
    if results.get(False):
        return 1
    return 0


def serve(host: str = "0.0.0.0", port: int = 8080, block: bool = True):
    """Runs the store web UI (cli.clj serve-cmd, web.clj)."""
    from . import web

    server = web.serve(host, port)
    logger.info("Listening on http://%s:%s/", host, port)
    if block:
        try:
            while True:
                time.sleep(1)
        except KeyboardInterrupt:
            server.shutdown()
    return server


class CliError(SystemExit):
    pass


def run_cli(subcommands: dict, argv=None) -> None:
    """Dispatches `argv` to {name: {parser_fn?, run}} subcommands
    (cli.clj run!, 258-335). run receives the parsed Namespace."""
    argv = list(sys.argv[1:] if argv is None else argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s [%(name)s] %(message)s")
    command = argv[0] if argv else None
    if command not in subcommands:
        print("Usage: python -m jepsen_tpu COMMAND [OPTIONS ...]")
        print("Commands:", ", ".join(sorted(subcommands)))
        raise SystemExit(254)
    spec = subcommands[command]
    parser = argparse.ArgumentParser(prog=command)
    if spec.get("parser_fn"):
        spec["parser_fn"](parser)
    try:
        options = parser.parse_args(argv[1:])
    except SystemExit as e:
        raise SystemExit(254 if e.code not in (0, None) else 0)
    try:
        code = spec["run"](options)
    except SystemExit:
        raise
    except Exception:  # noqa: BLE001
        logger.exception("Oh jeez, I'm sorry, Jepsen broke. Here's why:")
        raise SystemExit(255)
    raise SystemExit(code or 0)


def single_test_cmd(test_fn, parser_fn=None, opt_fn=None) -> dict:
    """A 'test' subcommand for a suite (cli.clj:355-442). test_fn:
    options-dict -> test map."""
    def run(options):
        opts = test_opt_fn(options)
        if opt_fn:
            opts = opt_fn(opts)
        return run_test_n_times(test_fn, opts)

    def build(p):
        add_test_opts(p)
        if parser_fn:
            parser_fn(p)
        return p

    return {"test": {"parser_fn": build, "run": run}}


def test_all_cmd(tests_fn, parser_fn=None, opt_fn=None) -> dict:
    """A 'test-all' subcommand sweeping a test matrix
    (cli.clj:504-530). tests_fn: options-dict -> iterable of tests."""
    def run(options):
        opts = test_opt_fn(options)
        if opt_fn:
            opts = opt_fn(opts)
        results = test_all_run_tests(tests_fn(opts))
        test_all_print_summary(results)
        return test_all_exit_code(results)

    def build(p):
        add_test_opts(p)
        if parser_fn:
            parser_fn(p)
        return p

    return {"test-all": {"parser_fn": build, "run": run}}


def telemetry_cmd() -> dict:
    """A 'telemetry' subcommand: prints the span-tree + metrics
    summary for a stored run (its telemetry.jsonl / metrics.json
    artifacts; see doc/observability.md). --min-ms / --top prune the
    span tree (ancestors of kept spans survive) so per-launch kernel
    records don't drown the phase view."""
    def build(p):
        _store_run_opts(p)
        p.add_argument("--min-ms", type=float, default=None,
                       metavar="MS",
                       help="Hide spans shorter than this many "
                            "milliseconds.")
        p.add_argument("--top", type=int, default=None, metavar="N",
                       help="Show only the N longest spans (plus "
                            "their ancestors).")
        return p

    def run(options):
        from . import store as jstore
        from .reports import telemetry as rtel

        d = _resolve_stored_run(options)
        if d is None:
            print(f"no such stored test: {options.test}")
            return 254
        events, metrics = jstore.load_telemetry(d)
        if not events and metrics is None:
            print(f"no telemetry recorded under {d} "
                  "(run predates the telemetry layer?)")
            return 1
        print(f"# {d.resolve()}\n")
        print(rtel.telemetry_text(events, metrics,
                                  min_ms=options.min_ms,
                                  top=options.top))
        return 0

    return {"telemetry": {"parser_fn": build, "run": run}}


def profile_cmd() -> dict:
    """A 'profile' subcommand: the per-kernel device-performance table
    for a stored run — launches, compile-cache hit rate, FLOPs, bytes
    accessed, peak device memory, and the wall/device phase split —
    from the run's metrics.json + telemetry.jsonl launch records
    (jepsen_tpu.tpu.profiler; doc/observability.md)."""
    def build(p):
        return _store_run_opts(p)

    def run(options):
        from . import store as jstore
        from .reports import profile as rprofile

        d = _resolve_stored_run(options)
        if d is None:
            print(f"no such stored test: {options.test}")
            return 254
        events, metrics = jstore.load_telemetry(d)
        if not events and metrics is None:
            print(f"no telemetry recorded under {d} "
                  "(run predates the profiler?)")
            return 1
        print(f"# {d.resolve()}\n")
        print(rprofile.profile_text(events, metrics))
        return 0

    return {"profile": {"parser_fn": build, "run": run}}


def _resolve_stored_run(options):
    """Shared run-dir resolution for artifact subcommands (telemetry,
    trace): a literal directory, a test name under the store base, or
    'latest'."""
    from pathlib import Path

    from . import store as jstore

    base = Path(options.store) if options.store else jstore.BASE
    d = Path(options.test)
    if not d.is_dir():
        d = base / options.test / options.timestamp
    if options.test == "latest" and not d.is_dir():
        d = base / "latest"
    return d if d.is_dir() else None


def _store_run_opts(p: argparse.ArgumentParser) -> argparse.ArgumentParser:
    p.add_argument("test", nargs="?", default="latest",
                   help="A store directory, or a test name "
                        "(resolved under the store base).")
    p.add_argument("--timestamp", default="latest",
                   help="Which run of the named test.")
    p.add_argument("--store", default=None,
                   help="Store base directory (default ./store).")
    return p


def nodes_cmd() -> dict:
    """A 'nodes' subcommand: the node observability plane's per-node
    summary for a stored run — sample/gap counts, resource extremes,
    tagged DB-log events, breaker badges, and the merged clock-skew
    bound (nodes.jsonl, jepsen_tpu.nodeprobe; doc/observability.md)."""
    def build(p):
        return _store_run_opts(p)

    def run(options):
        from . import nodeprobe as jnodeprobe
        from . import store as jstore
        from .reports import nodes as rnodes

        d = _resolve_stored_run(options)
        if d is None:
            print(f"no such stored test: {options.test}")
            return 254
        records = jstore.load_nodes(d)
        if not records:
            print(f"no node-plane records under {d} "
                  "(run predates — or disabled — the node probe)")
            return 1
        jnodeprobe.validate_records(records)
        test = None
        try:
            test = jstore.load(d)
        except (OSError, ValueError):
            pass
        print(f"# {d.resolve()}\n")
        print(rnodes.nodes_text(records,
                                (test or {}).get("history")))
        return 0

    return {"nodes": {"parser_fn": build, "run": run}}


def certify_cmd() -> dict:
    """A 'certify' subcommand: independently re-validates a stored
    run's verdict certificates against its recovered history (valid →
    replayable linearization / serialization order; invalid →
    confirmed witness or justified cycle; jepsen_tpu.tpu.certify,
    doc/observability.md). --print pretty-prints each certificate.
    Exit: 0 = every present certificate validates, 1 = at least one
    proof failed, 2 = the run carries no certificates."""
    def build(p):
        _store_run_opts(p)
        p.add_argument("--print", action="store_true", dest="print_",
                       help="Pretty-print each certificate instead "
                            "of just validating it.")
        return p

    def run(options):
        import json as _json

        from . import store as jstore
        from .store import format as fmt
        from .tpu import certify as jcertify

        d = _resolve_stored_run(options)
        if d is None:
            print(f"no such stored test: {options.test}")
            return 254
        try:
            results = jstore.load_results(d)
        except (OSError, ValueError):
            results = None
        if not isinstance(results, dict):
            print(f"no results.json under {d} (crashed run? try "
                  "`analyze --resume` first)")
            return 2
        hist = fmt.read_history(d / "history.jlog")
        digest = jcertify.history_digest(hist)
        print(f"# {d.resolve()}\n")
        rows = []
        errors = 0
        for path, res in jcertify.iter_certificates(results):
            cert = res["certificate"]
            if isinstance(cert.get("absent"), str) \
                    and cert["absent"]:
                status = f"absent ({cert['absent']})"
            else:
                # a malformed/unknown-version certificate is itself a
                # diagnosis, not a crash: validate() schema-checks
                # first, so it lands in the error column and exit 1
                try:
                    jcertify.validate(hist, cert, digest=digest)
                    status = "certified"
                except jcertify.CertificateError as e:
                    status = f"ERROR: {e}"
                    errors += 1
            kind = cert.get("kind", "-")
            verdict = cert.get("verdict", "-")
            rows.append((path, kind, verdict, status))
            if options.print_:
                print(f"## {path}")
                print(_json.dumps(cert, indent=1, default=repr))
                print()
        if not rows:
            print("(no certificates — the run predates verdict "
                  "certification, or every checker skipped it)")
            return 2
        w = max(len(p) for p, *_r in rows)
        for path, kind, verdict, status in rows:
            print(f"{path.ljust(w)}  {kind:<5} {verdict:<8} {status}")
        print(f"\n{len(rows)} certificate(s), {errors} error(s)")
        return 1 if errors else 0

    return {"certify": {"parser_fn": build, "run": run}}


def trace_cmd() -> dict:
    """A 'trace' subcommand: exports a stored run as Chrome-trace JSON
    (trace.json) openable in ui.perfetto.dev — telemetry spans, op
    lifetimes (one track per process), and nemesis windows on one
    timeline (reports/trace.py, doc/observability.md)."""
    def build(p):
        _store_run_opts(p)
        p.add_argument("-o", "--out", default=None,
                       help="Output path (default: <run>/trace.json).")
        p.add_argument("--ops", default=None,
                       help="Comma-separated op indices: restrict the "
                            "client tracks to these ops (anomaly "
                            "provenance drill-down).")
        return p

    def run(options):
        from .reports import trace as rtrace

        d = _resolve_stored_run(options)
        if d is None:
            print(f"no such stored test: {options.test}")
            return 254
        ops = ([int(x) for x in options.ops.split(",") if x.strip()]
               if options.ops else None)
        out = rtrace.write_trace(d, options.out, ops=ops)
        print(f"wrote {out}")
        print("open it at https://ui.perfetto.dev "
              "(or chrome://tracing)")
        return 0

    return {"trace": {"parser_fn": build, "run": run}}


def analyze_cmd(test_fn=None) -> dict:
    """An 'analyze' subcommand: recovers a stored run's history (valid
    CRC prefix; torn tail dropped) and (re)runs its checkers, writing
    results.json — the recovery path after a control-process crash.
    --resume reuses completed checkers from the partial-results log and
    the wgl segment checkpoints (doc/robustness.md). test_fn rebuilds
    the checker stack from the run's spec.json (suites pass their own
    builder; the default is the bundled-workload builder)."""
    def build(p):
        _store_run_opts(p)
        p.add_argument("--resume", action="store_true",
                       help="Reuse completed checker results and wgl "
                            "segment checkpoints from the crashed "
                            "analysis instead of starting over.")
        p.add_argument("--checker-timeout", type=float, default=None,
                       metavar="SECS",
                       help="Per-checker wall-clock bound; a hung "
                            "checker degrades to valid? unknown.")
        return p

    def run(options):
        from . import resume as jresume

        d = _resolve_stored_run(options)
        if d is None:
            print(f"no such stored test: {options.test}")
            return 254
        test = jresume.analyze_run(
            d, resume=options.resume, test_fn=test_fn,
            checker_timeout_s=options.checker_timeout)
        valid = (test.get("results") or {}).get("valid?")
        print(f"results written to {d / 'results.json'}")
        if valid is True:
            return 0
        if valid is False:
            return 1
        return 2

    return {"analyze": {"parser_fn": build, "run": run}}


def coverage_cmd(all_workloads=None) -> dict:
    """A 'coverage' subcommand: the cross-run fault × workload ×
    anomaly matrix, witnessed-cell detail, gap report, and (--suggest)
    ranked gap-filling configs — the campaign runner's input hook
    (jepsen_tpu.coverage, doc/observability.md). Scans the store for
    per-run coverage.json records, folds any missing ones into
    store/coverage_atlas.jsonl, then aggregates."""
    def build(p):
        p.add_argument("--store", default=None,
                       help="Store base directory (default ./store).")
        p.add_argument("--suggest", type=int, nargs="?", const=5,
                       default=0, metavar="N",
                       help="Also print the top N gap-filling "
                            "(workload, nemesis) configs (default 5).")
        p.add_argument("--no-sync", action="store_true",
                       help="Skip folding stored coverage.json "
                            "records into the atlas first.")
        return p

    def run(options):
        from pathlib import Path

        from . import coverage as jcoverage
        from . import store as jstore

        base = Path(options.store) if options.store else jstore.BASE
        if not options.no_sync:
            n = jcoverage.sync_store(base)
            if n:
                print(f"(folded {n} run record(s) into the atlas)")
        entries = jcoverage.read_atlas(base / jcoverage.ATLAS_FILE)
        jcoverage.validate_atlas(entries)
        cells = jcoverage.aggregate(entries)
        wls = all_workloads
        if wls is None:
            from . import workloads

            wls = list(workloads.REGISTRY)
        print(jcoverage.coverage_text(cells, wls,
                                      n_suggest=options.suggest))
        return 0

    return {"coverage": {"parser_fn": build, "run": run}}


def lint_cmd() -> dict:
    """A 'lint' subcommand: graftlint — static analysis of the
    compiled device kernels (host-sync, dtype-widening, donation
    misses, sharding-readiness, recompile risk, carry bloat) plus the
    threaded modules' lock-discipline lint, gated by the committed
    baseline ratchet (jepsen_tpu.analysis; doc/static-analysis.md).
    Abstract tracing only: CPU-safe, no execution — tier-1 runs
    `lint --baseline lint-baseline.json`. Exit: 0 clean or fully
    baselined, 1 NEW findings, 2 a kernel failed to trace."""
    def build(p):
        # the driver owns the full flag set; mirror it here so
        # `lint --help` works through the standard dispatcher
        p.add_argument("--baseline", default=None, metavar="FILE")
        p.add_argument("--update", action="store_true")
        p.add_argument("--json", action="store_true", dest="json_")
        p.add_argument("--runtime-buckets", action="store_true")
        p.add_argument("--full", action="store_true")
        p.add_argument("--rules", default=None, metavar="R1,R2,...")
        return p

    def run(options):
        from .analysis import driver

        argv = []
        if options.baseline:
            argv += ["--baseline", options.baseline]
        if options.update:
            argv.append("--update")
        if options.json_:
            argv.append("--json")
        if options.runtime_buckets:
            argv.append("--runtime-buckets")
        if options.full:
            argv.append("--full")
        if options.rules:
            argv += ["--rules", options.rules]
        return driver.main(argv)

    return {"lint": {"parser_fn": build, "run": run}}


def _fleet_top_lines(stats: dict) -> list[str]:
    """Renders `fleet top`'s frame from a stats() reply: the flight
    recorder's SLO quantiles, per-tenant latency tracks, per-class
    occupancy, and the scheduler decision log. Pure text-from-dict so
    tests exercise it without a terminal."""
    fr = stats.get("flightrec") or {}
    lines = []
    sched = stats.get("scheduler") or {}
    lines.append(
        f"streams {stats.get('streams', 0)}  "
        f"chunks {stats.get('chunks', 0)}  "
        f"verdicts {stats.get('verdicts', 0)}  "
        f"launches {sched.get('launches', 0)}")
    if not fr.get("enabled"):
        lines.append("flight recorder disabled")
        return lines

    def q(d, key):
        v = (d or {}).get(key)
        return "     -" if v is None else f"{v:10.2f}"

    v, a = fr.get("verdict_ms") or {}, fr.get("ack_ms") or {}
    lines.append(f"verdict ms  p50 {q(v, 'p50')}  p95 {q(v, 'p95')}"
                 f"  p99 {q(v, 'p99')}   (n={v.get('n', 0)})")
    lines.append(f"ack ms      p50 {q(a, 'p50')}  p95 {q(a, 'p95')}"
                 f"  p99 {q(a, 'p99')}   (n={a.get('n', 0)})")
    tenants = fr.get("tenants") or {}
    if tenants:
        lines.append(f"{'tenant':<16} {'verdict p50':>12} "
                     f"{'verdict p99':>12} {'ack p99':>10} "
                     f"{'items':>7}")
        fair = fr.get("fairness") or {}
        for t in sorted(tenants):
            td = tenants[t]
            lines.append(
                f"{t:<16} {q(td.get('verdict_ms'), 'p50'):>12} "
                f"{q(td.get('verdict_ms'), 'p99'):>12} "
                f"{q(td.get('ack_ms'), 'p99'):>10} "
                f"{(fair.get(t) or {}).get('items', 0):>7}")
    for cls, c in sorted((fr.get("classes") or {}).items()):
        lines.append(
            f"{cls:<7} launches {c.get('launches', 0):>5}  "
            f"rows/launch {c.get('rows_per_launch', 0.0):>8.2f}  "
            f"occupancy {c.get('occupancy', 0.0):>6.1%}")
    dec = fr.get("decisions") or {}
    lines.append("decisions  " + "  ".join(
        f"{r}={dec.get(r, 0)}" for r in
        ("full", "timeout", "drain", "breaker", "quarantine")))
    idle = fr.get("idle") or {}
    lines.append(f"device idle  {idle.get('gaps', 0)} gaps, "
                 f"{idle.get('total_ms', 0.0):.1f} ms total")
    return lines


def fleet_cmd() -> dict:
    """A 'fleet' subcommand: the checking-as-a-service data plane
    (jepsen_tpu.fleet; doc/fleet.md).

      fleet serve            run the always-on multi-tenant server
      fleet submit <run>     stream a stored run's history.jlog to the
                             fleet and print its verdict
      fleet status           the server's per-tenant stats
      fleet top              live SLO/utilization view (flight rec.)
      fleet explain <run>    a verdict's latency decomposition
      fleet trace            write the Perfetto fleet-session view
      fleet ckpt <path>      inspect a checkpoint record (or a
                             <tenant>/<run> under <base>/ckpt)
    """
    def build(p):
        p.add_argument("action", choices=["serve", "submit",
                                          "status", "top", "explain",
                                          "trace", "ckpt"])
        p.add_argument("run_dir", nargs="?", default=None,
                       help="submit: a stored run dir (or a "
                            "history.jlog) to stream. explain: the "
                            "run name whose verdict to decompose. "
                            "ckpt: a .ckpt path or tenant/run.")
        p.add_argument("--base", default="store/fleet",
                       help="Fleet state dir (WALs, verdicts, "
                            "fleet.addr).")
        p.add_argument("--addr", default=None,
                       help="host:port (default: read "
                            "<base>/fleet.addr).")
        p.add_argument("-b", "--host", default="127.0.0.1")
        p.add_argument("-p", "--port", type=int, default=0)
        p.add_argument("--tenant", default="cli")
        p.add_argument("--model", default="cas-register",
                       help="Model spec for submit (see "
                            "fleet.known_models()).")
        p.add_argument("--initial", default=None,
                       help="Initial value for register-family "
                            "models (JSON scalar; e.g. 0 for a DB "
                            "that seeds the register).")
        p.add_argument("--weight", type=float, default=1.0,
                       help="Weighted-fair-queue share for submit.")
        p.add_argument("--chunk-ops", type=int, default=256)
        p.add_argument("--max-tenants", type=int, default=8)
        p.add_argument("--max-streams", type=int, default=16)
        p.add_argument("--interval", type=float, default=2.0,
                       help="top: seconds between refreshes.")
        p.add_argument("--iterations", type=int, default=0,
                       help="top: stop after N frames (0 = forever).")
        p.add_argument("--out", default=None,
                       help="trace: output path (default "
                            "<base>/fleet-trace.json).")
        return p

    def _addr(options):
        if options.addr:
            return options.addr
        from pathlib import Path
        try:
            line = (Path(options.base)
                    / "fleet.addr").read_text().splitlines()[0]
            return line.strip()
        except (OSError, IndexError):
            raise CliError(
                f"no fleet.addr under {options.base!r} — pass --addr "
                "or start one with `fleet serve`")

    def run(options):
        import json as _json

        from .fleet import client as fclient
        from .fleet import server as fserver

        if options.action == "serve":
            quotas = fserver.Quotas(
                max_tenants=options.max_tenants,
                max_total_streams=options.max_streams)
            srv = fserver.FleetServer(options.base, host=options.host,
                                      port=options.port,
                                      quotas=quotas).start()
            host, port = srv.addr
            print(f"fleet server on {host}:{port} "
                  f"(base {options.base})")
            try:
                import time as _time
                while True:
                    _time.sleep(3600)
            except KeyboardInterrupt:
                srv.stop()
            return 0
        if options.action == "status":
            c = fclient.FleetClient(_addr(options), options.tenant,
                                    "status", observe=True)
            print(_json.dumps(c.status(), indent=2, sort_keys=True))
            c.close()
            return 0
        if options.action == "top":
            import time as _time
            i = 0
            while True:
                c = fclient.FleetClient(_addr(options),
                                        options.tenant, "status",
                                        observe=True)
                try:
                    stats = c.status()
                finally:
                    c.close()
                print("\n".join(_fleet_top_lines(stats)))
                i += 1
                if options.iterations and i >= options.iterations:
                    return 0
                print()
                _time.sleep(options.interval)
        if options.action == "explain":
            if not options.run_dir:
                raise CliError("fleet explain needs a run name")
            from .fleet import flightrec as frec

            c = fclient.FleetClient(_addr(options), options.tenant,
                                    options.run_dir)
            try:
                env = c.claim()
            finally:
                c.close()
            lat = env.get("latency") if isinstance(env, dict) \
                else None
            if not isinstance(lat, dict):
                print("no latency block (flight recorder disabled?)")
                return 2
            frec.validate_latency(lat)
            for k in frec.LATENCY_KEYS:
                print(f"  {k:>15}  {lat.get(k, 0.0):9.3f} ms")
            print(f"  {'total':>15}  "
                  f"{lat.get('total_ms', 0.0):9.3f} ms")
            if lat.get("replay"):
                print("  (replayed after restart: ingest/WAL slices "
                      "predate the crash and read zero)")
            k, v = frec.dominant_slice(lat)
            print(f"dominant slice: {k} ({v:.3f} ms)")
            return 0
        if options.action == "ckpt":
            if not options.run_dir:
                raise CliError(
                    "fleet ckpt needs a .ckpt path or tenant/run")
            from pathlib import Path

            from .tpu import ckpt as tckpt

            p = Path(options.run_dir)
            if p.suffix != ".ckpt" and not p.exists():
                # tenant/run shorthand under the fleet base
                parts = options.run_dir.split("/")
                if len(parts) == 2:
                    p = tckpt.fleet_path(options.base, *parts)
            if not p.exists():
                raise CliError(f"no checkpoint at {p}")
            rec = tckpt.read(p)
            if rec is None:
                # honest about why the reader refused it — a torn or
                # schema-invalid record is discarded, never trusted
                print(f"{p}: torn or invalid checkpoint "
                      "(discarded on read — a resume from this file "
                      "falls back to a full re-check)")
                return 2
            kind = rec["kind"]
            print(f"{p}")
            print(f"  kind    {kind}")
            print(f"  n_ops   {rec['n_ops']}")
            print(f"  digest  {rec['digest'][:16]}…")
            if kind == "stream-wgl":
                print(f"  model   {rec['model']}")
                print(f"  checked {rec['checked']}  "
                      f"mask {rec['mask']:#x}")
            elif kind == "wgl-extend":
                print(f"  stride  {rec['stride']}  "
                      f"cuts {len(rec['cuts'])}  "
                      f"states {len(rec['states'])}  "
                      f"masks {len(rec['masks'])}")
            elif kind == "elle":
                print(f"  family  {rec['family']}")
                fro = rec.get("frontier") or {}
                print(f"  closed  {rec['n_closed']} txns  "
                      f"keys {len(rec.get('versions') or {})}  "
                      f"edges {len(fro.get('edges') or [])}  "
                      f"state {fro.get('state')!r}")
            return 0
        if options.action == "trace":
            from pathlib import Path

            from .fleet import flightrec as frec
            from .reports import trace as rtrace

            snap = Path(options.base) / frec.SNAPSHOT_FILE
            try:
                d = _json.loads(snap.read_text())
            except (OSError, ValueError):
                raise CliError(
                    f"no flight-recorder snapshot at {snap}")
            doc = rtrace.fleet_chrome_trace(d.get("records") or [])
            out = Path(options.out) if options.out \
                else Path(options.base) / "fleet-trace.json"
            with open(out, "w") as f:
                _json.dump(doc, f)
            print(f"wrote {out} ({len(doc['traceEvents'])} events)")
            return 0
        # submit: stream a stored history
        if not options.run_dir:
            raise CliError("fleet submit needs a run dir or .jlog")
        from pathlib import Path

        from .store import format as sformat

        p = Path(options.run_dir)
        log = p if p.suffix == ".jlog" else p / "history.jlog"
        if not log.exists():
            raise CliError(f"no history log at {log}")
        run_name = (p.parent.name if p.suffix == ".jlog" else p.name
                    ).replace(" ", "-") or "run"
        initial = options.initial
        if initial is not None:
            try:
                initial = _json.loads(initial)
            except ValueError:
                pass  # a bare string initial is legal
        c = fclient.FleetClient(_addr(options), options.tenant,
                                run_name, model=options.model,
                                initial=initial,
                                weight=options.weight)
        ops: list = []
        n = 0
        for o in sformat.read_ops(log):
            ops.append(o)
            if len(ops) >= options.chunk_ops:
                c.send_chunk(ops)
                n += len(ops)
                ops = []
        if ops:
            c.send_chunk(ops)
            n += len(ops)
        verdict = c.finish()
        c.close()
        print(_json.dumps(verdict, indent=2, sort_keys=True))
        res = (verdict.get("result") or {}).get("valid?")
        return 0 if res is True else 1 if res is False else 2

    return {"fleet": {"parser_fn": build, "run": run}}


def serve_cmd() -> dict:
    """A 'serve' subcommand for the web UI (cli.clj:336-354)."""
    def build(p):
        p.add_argument("-b", "--host", default="0.0.0.0",
                       help="Hostname to bind to")
        p.add_argument("-p", "--port", type=int, default=8080,
                       help="Port number to bind to")
        return p

    def run(options):
        serve(options.host, options.port)
        return 0

    return {"serve": {"parser_fn": build, "run": run}}
