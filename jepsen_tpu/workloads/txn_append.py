"""List-append transactional workload (elle list-append).

Capability reference: jepsen/src/jepsen/tests/cycle/append.clj.
"""

from __future__ import annotations

from .. import generator as gen
from ..checker import cycle


def workload(opts: dict | None = None) -> dict:
    o = dict(opts or {})
    g = cycle.append_gen(
        key_count=o.get("key-count", 3),
        min_txn_length=o.get("min-txn-length", 1),
        max_txn_length=o.get("max-txn-length", 4),
        max_writes_per_key=o.get("max-writes-per-key", 32),
        seed=o.get("seed"))
    out = {"generator": (lambda: next(g)),
           "checker": cycle.append_checker(o)}
    if o.get("ops"):
        out["generator"] = gen.limit(o["ops"], out["generator"])
    return out
