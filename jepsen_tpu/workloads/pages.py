"""Pagination-isolation workload: groups of elements are inserted in
one transaction; concurrent full reads (which the database serves as
paginated scans) must see each group atomically — every read must be
expressible as a union of complete groups.

Capability reference: faunadb/src/jepsen/faunadb/pages.clj — client
(45-61: add inserts a whole group in one query, read pages through the
index), read-errs (67-92: peel one element, its whole group must be
present, recurse on the rest), checker (94-143: candidate adds =
invoked - failed, elements must be globally unique, duplicate items in
a read are their own error), workload (145-169: independent keys,
groups of 4 drawn without replacement, 4:1 add:read mix).

Client contract (per key, via independent tuples):
  {"f": "add", "value": (k, [e1..eG])} -> ok iff the whole group was
      inserted atomically
  {"f": "read", "value": (k, None)} -> ok with value (k, [elements...])
      in scan order (duplicates preserved — they are evidence).
"""

from __future__ import annotations

from .. import util

from .. import checker as chk
from .. import generator as gen
from .. import independent


def read_errs(idx: dict, read: set) -> list:
    """pages.clj read-errs: the read set must be a union of complete
    groups. Peel any element, check its full group is present, cross
    the group off, recurse."""
    errs = []
    read = set(read)
    while read:
        e = next(iter(read))
        group = idx.get(e, frozenset((e,)))
        missing = group - read
        if missing:
            errs.append({"expected": sorted(group),
                         "found": sorted(group & read)})
        read -= group
    return errs


def check_pages(hist) -> dict:
    """pages.clj checker (94-143)."""
    invoked, failed = set(), set()
    ok_reads = []
    for op in hist:
        if op.f == "add":
            group = tuple(op.value or ())
            if op.type == "invoke":
                invoked.add(group)
            elif op.type == "fail":
                failed.add(group)
        elif op.f == "read" and op.type == "ok":
            ok_reads.append(op)
    # adds that may have taken effect
    candidates = invoked - failed
    idx: dict = {}
    for group in candidates:
        gset = frozenset(group)
        for e in group:
            assert e not in idx, f"elements must be unique: {e}"
            idx[e] = gset
    errors = []
    for op in ok_reads:
        v = list(op.value or ())
        if len(v) != len(set(v)):
            errors.append({"op-index": op.index,
                           "errors": ["duplicate-items"]})
            continue
        errs = read_errs(idx, set(v))
        if errs:
            errors.append({"op-index": op.index, "errors": errs})
    worst = max(errors, key=lambda e: len(e["errors"]), default=None)
    return {
        "valid?": not errors,
        "ok-read-count": len(ok_reads),
        "error-count": len(errors),
        "first-error": errors[0] if errors else None,
        "worst-error": worst,
    }


def checker() -> chk.Checker:
    return chk.checker(lambda test, hist, opts: check_pages(hist))


def key_gen(k, opts: dict):
    """Groups drawn without replacement from a shuffled range, 4:1
    add:read, limited (pages.clj workload). `elements_per_add` sizes
    the atomic insert groups — deliberately NOT `group_size`, which
    names the independent thread-group like every other workload."""
    o = opts
    group_size = o.get("elements_per_add", 4)
    n = o.get("elements", 10_000)
    rng = util.seeded_rng(o.get("seed"), k)
    pool = list(range(-n, n))
    rng.shuffle(pool)
    groups = [pool[i:i + group_size]
              for i in range(0, len(pool) - group_size + 1, group_size)]
    adds = iter(groups)

    def add():
        g = next(adds, None)
        if g is None:
            return None  # pool exhausted ends the generator
        return {"f": "add", "value": g}

    def read():
        return {"f": "read", "value": None}

    return gen.limit(o.get("ops_per_key", 256),
                     gen.stagger(o.get("stagger", 0.001),
                                 gen.mix([add, add, add, add, read])))


def workload(opts: dict | None = None) -> dict:
    o = dict(opts or {})
    keys = o.get("keys", list(range(o.get("key_count", 8))))
    n_group = o.get("group-size", o.get("group_size", 4))
    return {
        "generator": independent.concurrent_generator(
            n_group, keys, lambda k: key_gen(k, o)),
        "checker": independent.checker(checker()),
    }
