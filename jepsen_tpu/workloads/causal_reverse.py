"""Strict-serializability anomaly probe: T1 < T2 but T2 is visible
without T1.

Capability reference: jepsen/src/jepsen/tests/causal_reverse.clj —
concurrent blind writes per key plus transactional reads; `graph`
replays the history collecting, for each write w, the set of writes
acknowledged before w's invocation (22-48); `errors` flags reads that
see w but miss an acknowledged predecessor (50-78); checker (80-89)
and the independent-keyed workload (91-120).
"""

from __future__ import annotations

from .. import checker as chk
from .. import independent
from ..checker import _Fn


def graph(hist) -> dict:
    """value -> frozenset of writes acknowledged before its invocation
    (first-order write precedence, causal_reverse.clj:22-48)."""
    completed: set = set()
    expected: dict = {}
    for op in hist:
        if op.f != "write":
            continue
        if op.type == "invoke":
            expected[op.value] = frozenset(completed)
        elif op.type == "ok":
            completed.add(op.value)
    return expected


def errors(hist, expected: dict) -> list:
    """Reads that observe a write but miss one of its acknowledged
    predecessors (causal_reverse.clj:50-78)."""
    errs = []
    for op in hist:
        if op.f != "read" or op.type != "ok":
            continue
        seen = set(op.value or [])
        our_expected: set = set()
        for v in seen:
            our_expected |= expected.get(v, frozenset())
        missing = our_expected - seen
        if missing:
            errs.append({"op": op, "missing": sorted(missing, key=str),
                         "expected-count": len(our_expected)})
    return errs


def checker() -> chk.Checker:
    def run(test, hist, opts):
        expected = graph(hist)
        errs = errors(hist, expected)
        return {"valid?": not errs, "errors": errs[:8],
                "error-count": len(errs)}

    return _Fn(run)


def workload(opts: dict | None = None) -> dict:
    """Concurrent writes + reads per key (causal_reverse.clj:91-120)."""
    from .. import generator as gen

    o = dict(opts or {})
    keys = o.get("keys", list(range(o.get("key-count", 4))))
    per_key = o.get("per-key-limit", 100)

    def key_gen(k):
        writes = ({"f": "write", "value": x} for x in range(10 ** 6))
        return gen.limit(per_key, gen.stagger(
            0.01, gen.mix([gen.repeat({"f": "read", "value": None}),
                           writes])))

    return {
        "generator": independent.concurrent_generator(
            o.get("group-size", 2), keys, key_gen),
        "checker": chk.compose(
            {"sequential": independent.checker(checker()),
             "stats": chk.stats()}),
    }
