"""Dirty-read workload: writers keep a value in flight on each node
while readers race to observe it; after healing, every client takes a
refreshed "strong read" of the full committed set. A value any read
observed that NO strong read contains was a dirty read (it came from
a write that never committed); an acknowledged write missing from the
strong reads was lost.

Capability reference:
elasticsearch/src/jepsen/elasticsearch/dirty_read.clj — rw-gen
(writers advertise their in-flight write per node, readers probe it,
161-189), final refresh + per-client strong reads (203-223), checker
(106-156: dirty = reads - union(strong), lost = writes - union,
nodes-agree = union == intersection).

Client contract: "write" v indexes v (ok when acknowledged); "read" v
is ok iff v is currently visible, fail otherwise; "refresh" forces
visibility convergence; "strong-read" completes with the full set of
visible values.
"""

from __future__ import annotations

from .. import checker as chk
from .. import generator as gen


def check_dirty_read(hist) -> dict:
    """dirty_read.clj checker (106-156)."""
    writes, reads, strong = set(), set(), []
    for op in hist:
        if op.type != "ok":
            continue
        if op.f == "write":
            writes.add(op.value)
        elif op.f == "read":
            reads.add(op.value)
        elif op.f == "strong-read":
            strong.append(set(op.value or ()))
    if not strong:
        return {"valid?": "unknown",
                "error": "no strong reads completed"}
    on_all = set.intersection(*strong)
    on_some = set.union(*strong)
    dirty = reads - on_some
    lost = writes - on_some
    some_lost = writes - on_all
    nodes_agree = on_all == on_some
    return {
        "valid?": nodes_agree and not dirty and not lost,
        "nodes-agree?": nodes_agree,
        "read-count": len(reads),
        "on-all-count": len(on_all),
        "on-some-count": len(on_some),
        "not-on-all": sorted(on_some - on_all)[:16],
        "dirty-count": len(dirty),
        "dirty": sorted(dirty)[:16],
        "lost-count": len(lost),
        "lost": sorted(lost)[:16],
        "some-lost-count": len(some_lost),
        "strong-read-count": len(strong),
    }


class _Writes(gen.Generator):
    """Functional monotonic write values (see sequential._Writes for
    why emission must not mutate shared state: reserve probes and
    discards sub-generators)."""

    __slots__ = ("k",)

    def __init__(self, k: int = 0):
        self.k = k

    def op(self, test, ctx):
        o = gen.fill_in_op({"f": "write", "value": self.k}, ctx)
        if o is gen.PENDING:
            return gen.PENDING, self
        return o, _Writes(self.k + 1)

    def update(self, test, ctx, event):
        return self


def workload(opts: dict | None = None) -> dict:
    """1/3 of the threads write, the rest read whatever write is in
    flight on a random node (dirty_read.clj rw-gen); refresh + strong
    reads arrive as final_generator, to run after healing."""
    o = dict(opts or {})
    in_flight: dict[int, int] = {}  # node index -> latest write value

    class _Reads(gen.Generator):
        """Round-robins the probed node FUNCTIONALLY (emission returns
        a successor generator): reserve probes-and-discards, so a
        shared rng here would advance on discarded probes and void
        seeded reproducibility."""

        __slots__ = ("i",)

        def __init__(self, i: int = 0):
            self.i = i

        def op(self, test, ctx):
            if not in_flight:
                return gen.PENDING, self
            keys = sorted(in_flight)
            v = in_flight[keys[self.i % len(keys)]]
            op_ = gen.fill_in_op({"f": "read", "value": v}, ctx)
            if op_ is gen.PENDING:
                return gen.PENDING, self
            return op_, _Reads(self.i + 1)

        def update(self, test, ctx, event):
            return self

    def hook(this, test, ctx, event):
        if getattr(event, "type", None) == "invoke" \
                and getattr(event, "f", None) == "write":
            n = len(test.get("nodes", ())) or 1
            # the client is bound to the WORKER (thread), not the
            # process: crashed processes get fresh ids, so process %
            # nodes would misfile in-flight writes after a crash
            thread = ctx.process_to_thread_name(event.process)
            tid = int(thread) if isinstance(thread, int) \
                else int(event.process)
            in_flight[tid % n] = event.value
        inner = gen.update(this.gen, test, ctx, event)
        return gen.OnUpdate(this.f, inner)

    writers = o.get("writers")
    if writers is None:
        writers = max(1, o.get("concurrency", 6) // 3)
    g = gen.on_update(hook, gen.reserve(writers, _Writes(), _Reads()))
    if o.get("ops"):
        g = gen.limit(o["ops"], g)
    return {
        "generator": g,
        # heal first, then refresh everywhere, then one strong read
        # per client (dirty_read.clj final phases)
        "final_generator": gen.phases(
            gen.each_thread(gen.once(
                lambda: {"f": "refresh", "value": None})),
            gen.each_thread(gen.once(
                lambda: {"f": "strong-read", "value": None}))),
        "checker": chk.checker(
            lambda test, hist, _o: check_dirty_read(hist)),
    }
