"""Workload bundles: generator + checker pairs for the classic jepsen
test families. Each module exposes `workload(opts) -> {"generator": ...,
"checker": ..., ...}` mirroring how suites map workload names to
{:generator :checker :client} bundles (e.g. tidb/src/tidb/core.clj:32-45,
jepsen/src/jepsen/tests/bank.clj:178-191).
"""

from . import adya  # noqa: F401
from . import bank  # noqa: F401
from . import causal  # noqa: F401
from . import causal_reverse  # noqa: F401
from . import counter  # noqa: F401
from . import dirty_read  # noqa: F401
from . import kafka  # noqa: F401
from . import lock  # noqa: F401
from . import long_fork  # noqa: F401
from . import lost_updates  # noqa: F401
from . import monotonic  # noqa: F401
from . import multimonotonic  # noqa: F401
from . import pages  # noqa: F401
from . import queue  # noqa: F401
from . import register  # noqa: F401
from . import scheduler  # noqa: F401
from . import sequential  # noqa: F401
from . import sets  # noqa: F401
from . import txn_append  # noqa: F401
from . import txn_wr  # noqa: F401
from . import unique_ids  # noqa: F401
from . import upsert  # noqa: F401
from . import version_divergence  # noqa: F401

REGISTRY = {
    "adya-g2": adya.workload,
    "bank": bank.workload,
    "causal": causal.workload,
    "causal-reverse": causal_reverse.workload,
    "counter": counter.workload,
    "dirty-read": dirty_read.workload,
    "fenced-lock": lock.fenced_lock_workload,
    "kafka": kafka.workload,
    "lock": lock.lock_workload,
    "long-fork": long_fork.workload,
    "lost-updates": lost_updates.workload,
    "monotonic": monotonic.workload,
    "multimonotonic": multimonotonic.workload,
    "owner-lock": lock.owner_lock_workload,
    "pages": pages.workload,
    "queue": queue.workload,
    "reentrant-lock": lock.reentrant_lock_workload,
    "register": register.workload,
    "run-coverage": scheduler.workload,
    "semaphore": lock.semaphore_workload,
    "sequential": sequential.workload,
    "set": sets.workload,
    "set-full": sets.full_workload,
    "append": txn_append.workload,
    "upsert": upsert.workload,
    "version-divergence": version_divergence.workload,
    "wr": txn_wr.workload,
    "unique-ids": unique_ids.workload,
}
