"""Multi-register monotonicity workload: increment-only registers,
each written by a single dedicated worker (blind writes — no OCC read
locks), read in random subsets with a database timestamp. Two
checkers: timestamp-order (replay reads in ts order, values must never
run backwards) and read-skew (the per-key value orders must be
mutually compatible — no cycles).

Capability reference: faunadb/src/jepsen/faunadb/multimonotonic.clj —
client (76-107: write = blind upserts of {k: v}, read = subset query
returning {ts, registers}), nonmonotonic-states (180-241: fold reads
in ts order tracking max-seen per key; any key running backwards is an
error with both observations), ts-order-checker (253-270),
read-skew-checker (272-316: the reference documents the SCC
formulation but left its body a stub returning valid? true — here it
is actually implemented, via the elle engine's host SCC), generator
(318-340: per-thread keys from process ids, reads over random
non-empty subsets of active keys).

Client contract:
  {"f": "write", "value": {k: v}} -> ok (blind upsert of each k to v)
  {"f": "read", "value": [k...]} -> ok with value
      {"ts": <comparable>, "registers": {k: v, ...}}  (absent keys
      omitted)
"""

from __future__ import annotations

from .. import checker as chk
from .. import generator as gen


def _observation(op, k):
    v = op.value
    return {"read-ts": v.get("ts"),
            "value": v["registers"].get(k),
            "op-index": op.index}


def nonmonotonic_states(reads: list) -> list:
    """multimonotonic.clj nonmonotonic-states (180-241): fold reads
    (already ordered) keeping the highest observation per key; flag
    any read whose value for a key is lower than the inferred floor."""
    inferred: dict = {}
    errors = []
    for op in reads:
        state = op.value.get("registers", {})
        bad = {}
        for k, v in state.items():
            prev = inferred.get(k)
            if prev is not None and v < prev["value"]:
                bad[k] = [prev, _observation(op, k)]
        if bad:
            errors.append({
                "inferred": {k: inferred[k]["value"]
                             for k in state if k in inferred},
                "observed": dict(state),
                "op-index": op.index,
                "errors": bad,
            })
        for k, v in state.items():
            prev = inferred.get(k)
            if prev is None or v > prev["value"]:
                inferred[k] = _observation(op, k)
    return errors


def _ok_ts_reads(hist) -> list:
    reads = [o for o in hist
             if o.type == "ok" and o.f == "read"
             and isinstance(o.value, dict)
             and o.value.get("ts") is not None]
    reads.sort(key=lambda o: o.value["ts"])
    return reads


def check_ts_order(hist) -> dict:
    """ts-order-checker (253-270): in timestamp order, increment-only
    registers must never run backwards."""
    errs = nonmonotonic_states(_ok_ts_reads(hist))
    return {"valid?": not errs, "errors": errs[:8],
            "error-count": len(errs)}


def check_read_skew(hist) -> dict:
    """read-skew-checker (272-316), actually implemented: each key's
    increment-only order gives edges between read-states (state with
    k=v points at the next-higher observed v); a cycle in the union
    graph is a read skew — two reads that each saw the other's
    'past'."""
    from ..tpu.elle import _find_cycle, _sccs

    reads = [o for o in hist
             if o.type == "ok" and o.f == "read"
             and isinstance(o.value, dict)]
    by_key: dict = {}  # k -> {v: [read index]}
    for i, op in enumerate(reads):
        for k, v in op.value.get("registers", {}).items():
            by_key.setdefault(k, {}).setdefault(v, []).append(i)
    edges = []
    for k, versions in by_key.items():
        ordered = sorted(versions)
        for a, b in zip(ordered, ordered[1:]):
            for i in versions[a]:
                for j in versions[b]:
                    if i != j:
                        edges.append((i, j, k))
    cycles = []
    for scc in _sccs(len(reads), edges):
        if len(scc) > 1:
            cyc = _find_cycle(scc, edges)
            cycles.append([{"op-index": reads[i].index,
                            "key": key,
                            "registers": reads[i].value["registers"]}
                           for i, _, key in cyc])
    return {"valid?": not cycles, "cycles": cycles[:4],
            "cycle-count": len(cycles)}


def ts_order_checker() -> chk.Checker:
    return chk.checker(lambda test, hist, opts: check_ts_order(hist))


def read_skew_checker() -> chk.Checker:
    return chk.checker(lambda test, hist, opts: check_read_skew(hist))


class _WriteGen(gen.Generator):
    """Each thread owns the key named after its thread index and
    blind-writes 0,1,2,...: single-writer increment-only registers
    with no shared state (multimonotonic.clj generator, 318-340).
    Functional: the per-thread counters ride in the successor. Keys
    are strings so histories survive the JSON store round trip."""

    def __init__(self, counts=()):
        self.counts = tuple(counts)  # (key, next_v) pairs

    def op(self, test, ctx):
        m = gen.fill_in_op({"f": "write", "value": None}, ctx)
        if m is gen.PENDING:
            return gen.PENDING, self
        # the key belongs to whichever thread the op landed on
        k = str(ctx.process_to_thread_name(m.process))
        d = dict(self.counts)
        v = d.get(k, 0)
        d[k] = v + 1
        return m.copy(value={k: v}), _WriteGen(tuple(sorted(d.items())))

    def update(self, test, ctx, event):
        return self


def workload(opts: dict | None = None) -> dict:
    o = dict(opts or {})
    n = o.get("ops", 400)

    def read():
        return {"f": "read", "value": None}

    # reads carry value None; the CLIENT chooses a random non-empty
    # subset of keys it has seen (reference: random-nonempty-subset of
    # active keys) — keeping the generator pure.
    half = max(o.get("writers", 2), 1)
    g = gen.reserve(half, _WriteGen(), gen.repeat(read))
    return {
        "generator": gen.limit(n, gen.stagger(
            o.get("stagger", 0.001), g)),
        "checker": chk.compose({
            "ts-order": ts_order_checker(),
            "read-skew": read_skew_checker(),
        }),
    }
