"""Set workloads: unique adds followed by reads.

Capability reference: jepsen/src/jepsen/checker.clj set (257-317) and
set-full (320-612); generator shape from doc/tutorial/08 (adds of
monotonically increasing elements, final read).
"""

from __future__ import annotations

import itertools

from .. import checker as chk
from .. import generator as gen


def adds():
    """add ops with unique ascending elements."""
    counter = itertools.count()
    return lambda: {"f": "add", "value": next(counter)}


def reads():
    return lambda: {"f": "read", "value": None}


def workload(opts: dict | None = None) -> dict:
    """Adds throughout; one final read checked by the basic set checker."""
    o = dict(opts or {})
    n = o.get("ops", 200)
    return {
        "generator": gen.phases(gen.limit(n, adds()),
                                gen.once(reads())),
        "checker": chk.set_checker(),
    }


def full_workload(opts: dict | None = None) -> dict:
    """Continuous adds + reads checked by the rigorous per-element
    lifecycle analysis (set-full)."""
    o = dict(opts or {})
    n = o.get("ops", 300)
    a = adds()
    rd = reads()
    return {
        "generator": gen.limit(
            n, gen.mix([a, rd])),
        "checker": chk.set_full({"linearizable?":
                                 o.get("linearizable?", False)}),
    }
