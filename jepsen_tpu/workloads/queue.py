"""Queue workload: enqueues/dequeues with a final drain, checked for
conservation (what goes in must come out).

Capability reference: jepsen/src/jepsen/checker.clj total-queue
(648-708) + queue (235-255); drain expansion (614-646).
"""

from __future__ import annotations

import itertools

from .. import checker as chk
from .. import generator as gen
from ..checker import models


def workload(opts: dict | None = None) -> dict:
    o = dict(opts or {})
    n = o.get("ops", 200)
    counter = itertools.count()

    def enq():
        return {"f": "enqueue", "value": next(counter)}

    def deq():
        return {"f": "dequeue", "value": None}

    return {
        "generator": gen.phases(
            gen.limit(n, gen.mix([enq, deq])),
            gen.each_thread(gen.once(lambda: {"f": "drain",
                                              "value": None}))),
        "checker": chk.compose({
            "total-queue": chk.total_queue(),
            "stats": chk.stats()}),
    }


def fifo_workload(opts: dict | None = None) -> dict:
    o = dict(opts or {})
    n = o.get("ops", 200)
    counter = itertools.count()
    return {
        "generator": gen.limit(n, gen.mix(
            [lambda: {"f": "enqueue", "value": next(counter)},
             lambda: {"f": "dequeue", "value": None}])),
        "checker": chk.queue(models.unordered_queue()),
    }
