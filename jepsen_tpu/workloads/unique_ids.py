"""Unique-ID workload: every acknowledged generate must return a
globally unique id.

Capability reference: jepsen/src/jepsen/checker.clj unique-ids
(710-747).
"""

from __future__ import annotations

from .. import checker as chk
from .. import generator as gen


def workload(opts: dict | None = None) -> dict:
    o = dict(opts or {})
    n = o.get("ops", 300)
    return {
        "generator": gen.limit(n, lambda: {"f": "generate",
                                           "value": None}),
        "checker": chk.unique_ids(),
    }
