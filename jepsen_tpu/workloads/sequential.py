"""Sequential-consistency workload: a writer inserts a key's subkeys
in client order across separate transactions; a reader then reads the
subkeys in REVERSE order. Seeing a later subkey but missing an
earlier one violates sequential consistency.

Capability reference: cockroachdb/src/jepsen/cockroach/sequential.clj
— subkeys per key (46-49), writer inserts each subkey in its own txn
in order / reader queries them reversed (70-95), writes generator with
a recently-written buffer the readers sample (107-133), checker
flagging any read with a nil AFTER a non-nil (trailing-nil?, 136-162).

Client contract: "write" with value k inserts every subkey of k in
order; "read" with value k completes with (k, observations) where
observations lists each subkey (reversed order) or None if missing.
"""

from __future__ import annotations

import collections
import random

from .. import checker as chk
from .. import generator as gen


def default_writers(concurrency: int) -> int:
    """Half the threads write, but never ALL of them (a reader pool
    must exist for the checker to have coverage); at concurrency 1
    the single thread writes and the checker reports unknown."""
    return min(max(1, concurrency // 2), max(concurrency - 1, 1))


def subkeys(key_count: int, k) -> list:
    """The subkeys of k, in write order (sequential.clj:46-49)."""
    return [f"{k}_{i}" for i in range(key_count)]


def _trailing_none(obs) -> bool:
    """A None after a non-None: a later write visible while an earlier
    one is missing (sequential.clj trailing-nil?)."""
    started = False
    for x in obs:
        if x is not None:
            started = True
        elif started:
            return True
    return False


def check_sequential(hist) -> dict:
    """sequential.clj checker (140-162). Read observations arrive
    reversed, so trailing Nones are the violations. Zero reads can't
    vacuously pass — that's no coverage, not correctness."""
    # (k, observations) pairs arrive as tuples in-memory but as LISTS
    # from a store round trip (the history log is JSON) — accept both
    reads = [(op.value[0], list(op.value[1])) for op in hist
             if op.type == "ok" and op.f == "read"
             and isinstance(op.value, (tuple, list))
             and len(op.value) == 2
             and isinstance(op.value[1], (tuple, list))]
    if not reads:
        return {"valid?": "unknown", "error": "No reads ever ran"}
    none = [r for r in reads if all(x is None for x in r[1])]
    some = [r for r in reads if any(x is None for x in r[1])]
    bad = [r for r in reads if _trailing_none(r[1])]
    all_ = [r for r in reads if all(x is not None for x in r[1])]
    return {
        "valid?": not bad,
        "all-count": len(all_),
        "some-count": len(some),
        "none-count": len(none),
        "bad-count": len(bad),
        "bad": bad[:8],
    }


class _Writes(gen.Generator):
    """Sequential write keys, FUNCTIONALLY: emitting returns a new
    generator holding k+1, so a probed-and-discarded branch (reserve
    races its sub-generators) can never burn a key the way a stateful
    counter closure would — readers must only ever see keys a write
    op was really dispatched for."""

    __slots__ = ("k",)

    def __init__(self, k: int = 0):
        self.k = k

    def op(self, test, ctx):
        o = gen.fill_in_op({"f": "write", "value": self.k}, ctx)
        if o is gen.PENDING:
            return gen.PENDING, self
        return o, _Writes(self.k + 1)

    def update(self, test, ctx, event):
        return self


def workload(opts: dict | None = None) -> dict:
    """n writers emitting sequential keys; readers sample a buffer of
    the 2n most recently *dispatched* writes (sequential.clj gen,
    107-133). The buffer fills from write INVOKE events via on_update,
    never from generator probing."""
    o = dict(opts or {})
    n_writers = o.get("writers", 5)
    rng = random.Random(o.get("seed"))
    last_written: collections.deque = collections.deque(
        maxlen=2 * n_writers)

    class _Reads(gen.Generator):
        """PENDING until some write has actually been dispatched,
        then reads a recently-written key."""

        __slots__ = ()

        def op(self, test, ctx):
            if not last_written:
                return gen.PENDING, self
            o = gen.fill_in_op(
                {"f": "read",
                 "value": rng.choice(list(last_written))}, ctx)
            if o is gen.PENDING:
                return gen.PENDING, self
            return o, self

        def update(self, test, ctx, event):
            return self

    def hook(this, test, ctx, event):
        if getattr(event, "type", None) == "invoke" \
                and getattr(event, "f", None) == "write":
            last_written.append(event.value)
        inner = gen.update(this.gen, test, ctx, event)
        return gen.OnUpdate(this.f, inner)

    g = gen.reserve(n_writers, _Writes(), _Reads())
    g = gen.on_update(hook, g)
    if o.get("ops"):
        g = gen.limit(o["ops"], g)
    return {
        "generator": g,
        "checker": chk.checker(
            lambda test, hist, _o: check_sequential(hist)),
        "key_count": o.get("key-count", 5),
    }
