"""Version-divergence probe: upsert unique integers into a row while
partitioning the cluster; every observed row _version must identify a
SINGLE value — two values under one version means divergent replicas
both claimed the same version.

Capability reference: crate/src/jepsen/crate/version_divergence.clj —
client (29-91: read returns {value, _version}; write upserts a unique
integer), multiversion-checker (93-107: group ok reads by _version,
each group must hold exactly one distinct value), test (109-137:
independent keys, reserve 5 readers vs writers, partition nemesis).

Client contract (per key, via independent tuples):
  {"f": "write", "value": (k, v)} -> ok when the upsert landed
  {"f": "read", "value": (k, None)} -> ok with value
      (k, {"value": v, "version": n}) or (k, None) for a missing row
"""

from __future__ import annotations

from .. import checker as chk
from .. import generator as gen
from .. import independent


def check_multiversion(hist) -> dict:
    """version_divergence.clj multiversion-checker (93-107)."""
    by_version: dict = {}
    for op in hist:
        if op.type != "ok" or op.f != "read":
            continue
        v = op.value
        if not isinstance(v, dict) or v.get("version") is None:
            continue
        by_version.setdefault(v["version"], set()).add(v.get("value"))
    multis = {ver: sorted(vals, key=repr)
              for ver, vals in by_version.items() if len(vals) > 1}
    return {
        "valid?": not multis,
        "versions-observed": len(by_version),
        "multis": multis,
    }


def multiversion_checker() -> chk.Checker:
    return chk.checker(
        lambda test, hist, opts: check_multiversion(hist))


class _UniqueWrites(gen.Generator):
    """0,1,2,... as write values; functional successor so probing
    wrappers can't skip integers."""

    def __init__(self, n: int = 0):
        self.n = n

    def op(self, test, ctx):
        m = gen.fill_in_op({"f": "write", "value": self.n}, ctx)
        if m is gen.PENDING:
            return gen.PENDING, self
        return m, _UniqueWrites(self.n + 1)

    def update(self, test, ctx, event):
        return self


def workload(opts: dict | None = None) -> dict:
    o = dict(opts or {})
    keys = o.get("keys", list(range(o.get("key_count", 4))))
    n_group = o.get("group-size", o.get("group_size", 6))
    # at least one thread must remain outside the reader reservation
    # or no writes ever run and the checker passes vacuously
    readers = min(o.get("readers", 3), max(n_group - 1, 1))
    ops_per_key = o.get("ops_per_key", 120)

    def key_gen(k):
        reads = gen.repeat({"f": "read", "value": None})
        return gen.limit(ops_per_key, gen.stagger(
            o.get("stagger", 0.001),
            gen.reserve(readers, reads, _UniqueWrites())))

    return {
        "generator": independent.concurrent_generator(
            n_group, keys, key_gen),
        "checker": independent.checker(multiversion_checker()),
    }
