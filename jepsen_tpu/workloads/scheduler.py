"""Job-scheduler run-coverage workload: submit periodic jobs, read back
the record of actual runs, and verify every *required* target
invocation was satisfied by a distinct run within its epsilon window.

Capability reference: chronos/src/jepsen/chronos/checker.clj —
job->targets (30-47: targets due strictly before
read_time - epsilon - duration, each forgiving epsilon +
epsilon-forgiveness seconds of lateness), job-solution (117-189: a
constraint solution assigning each target a distinct run; the
reference solves it with the loco CP solver), solution (191-213:
group jobs/runs by name, every job must be satisfied) and
chronos.clj's add-job generator (194-215: intervals sized so targets
never overlap). The CP solver is replaced by greedy interval matching
(targets sorted by deadline take the earliest usable run), which is an
exact maximum matching for points-in-intervals — no solver dependency.

Shapes (times are unix-epoch seconds, floats):
  job: {"name": int, "start": t, "interval": s, "count": n,
        "epsilon": s, "duration": s}
  run: {"name": int, "start": t, "end": t|None}  (end None = began
        but never completed; incomplete runs satisfy nothing)
  {"f": "add-job", "value": job} -> ok when the scheduler accepted it
  {"f": "read", "value": None} -> ok with value
        {"time": t, "runs": [run...]}
"""

from __future__ import annotations

from .. import util

from .. import checker as chk
from .. import generator as gen

EPSILON_FORGIVENESS = 5.0  # chronos misses deadlines by a few seconds


def job_targets(read_time: float, job: dict) -> list:
    """[(start, deadline)] for every invocation that MUST have begun by
    the read (checker.clj job->targets): targets stop epsilon+duration
    before the read (later ones may legally still be pending), and each
    forgives epsilon + EPSILON_FORGIVENESS of start lateness."""
    finish = read_time - job["epsilon"] - job["duration"]
    out = []
    t = job["start"]
    for _ in range(int(job["count"])):
        if t >= finish:
            break
        out.append((t, t + job["epsilon"] + EPSILON_FORGIVENESS))
        t += job["interval"]
    return out


def match_targets(targets: list, run_starts: list) -> tuple:
    """Greedy maximum matching of run start-times to target intervals:
    targets in deadline order take the earliest unused run inside
    their window. Returns (assignment, unsatisfied) where assignment
    maps target index -> run index."""
    order = sorted(range(len(targets)), key=lambda i: targets[i][1])
    runs = sorted(range(len(run_starts)), key=lambda j: run_starts[j])
    used = [False] * len(run_starts)
    assignment: dict = {}
    unsatisfied = []
    for i in order:
        lo, hi = targets[i]
        hit = None
        for j in runs:
            if used[j]:
                continue
            s = run_starts[j]
            if s < lo:
                continue
            if s > hi:
                break
            hit = j
            break
        if hit is None:
            unsatisfied.append(i)
        else:
            used[hit] = True
            assignment[i] = hit
    return assignment, unsatisfied


def job_solution(read_time: float, job: dict, runs: list) -> dict:
    """checker.clj job-solution: split complete/incomplete runs, match
    complete runs to targets, report extras and misses."""
    complete = sorted((r for r in runs if r.get("end") is not None),
                      key=lambda r: r["start"])
    incomplete = sorted((r for r in runs if r.get("end") is None),
                        key=lambda r: r["start"])
    targets = job_targets(read_time, job)
    assignment, unsatisfied = match_targets(
        targets, [r["start"] for r in complete])
    solution = [{"target": targets[i], "run": complete[j]}
                for i, j in sorted(assignment.items())]
    extra = [r for j, r in enumerate(complete)
             if j not in set(assignment.values())]
    return {
        "valid?": not unsatisfied,
        "job": job,
        "solution": solution,
        "unsatisfied-targets": [targets[i] for i in unsatisfied],
        "extra": extra,
        "complete": complete,
        "incomplete": incomplete,
    }


def check_schedule(read_time: float, jobs: list, runs: list) -> dict:
    """checker.clj solution: group by job name; valid iff every job's
    targets are all satisfied by distinct runs."""
    runs_by = {}
    for r in runs:
        runs_by.setdefault(r["name"], []).append(r)
    solns = {}
    for job in jobs:
        solns[job["name"]] = job_solution(
            read_time, job, runs_by.get(job["name"], []))
    unknown_runs = [r for r in runs
                    if r["name"] not in {j["name"] for j in jobs}]
    return {
        "valid?": all(s["valid?"] for s in solns.values()),
        "jobs": solns,
        "extra": [r for s in solns.values() for r in s["extra"]],
        "incomplete": [r for s in solns.values()
                       for r in s["incomplete"]],
        "unknown-job-runs": unknown_runs,
        "read-time": read_time,
    }


def run_coverage_checker() -> chk.Checker:
    """History-level checker: jobs are ok :add-job values; runs and the
    read time come from the last ok :read."""

    def run(test, hist, opts):
        jobs, final = [], None
        for op in hist:
            if op.type != "ok":
                continue
            if op.f == "add-job":
                jobs.append(op.value)
            elif op.f == "read":
                final = op.value
        if final is None:
            return {"valid?": "unknown",
                    "error": "runs were never read"}
        return check_schedule(final["time"], jobs,
                              list(final["runs"]))

    return chk.checker(run)


class _AddJobGen(gen.Generator):
    """Seeded job-spec generator (chronos.clj add-job, 194-215):
    intervals sized > duration + 2*epsilon + forgiveness so one
    scheduler never has to run two invocations of a job at once.
    Emission is FUNCTIONAL — op() returns a successor carrying n+1,
    and the spec is derived from (seed, n) — so probe-and-discard
    wrappers (reserve/any) can't leak job names."""

    def __init__(self, head_start: float = 10.0, seed=None, n: int = 0):
        self.head_start = head_start
        # (seed, n) -> spec must be stable across probe-and-discard
        # re-derivations, so an unseeded run draws ONE random seed here
        # and threads it through every successor.
        self.seed = (util.seeded_rng(None).randrange(2 ** 63)
                     if seed is None else seed)
        self.n = n

    def op(self, test, ctx):
        rng = util.seeded_rng(self.seed, self.n)
        duration = rng.randrange(10)
        epsilon = 10 + rng.randrange(20)
        interval = (1 + duration + epsilon + EPSILON_FORGIVENESS
                    + rng.randrange(30))
        job = {"name": self.n,
               "start": ctx.time / 1e9 + self.head_start,
               "interval": float(interval),
               "count": 10 + rng.randrange(20),
               "epsilon": float(epsilon),
               "duration": float(duration)}
        m = gen.fill_in_op({"f": "add-job", "value": job}, ctx)
        if m is gen.PENDING:
            # don't advance n on a probe: spec n must not be consumed
            # until the op is actually emitted
            return gen.PENDING, self
        return m, _AddJobGen(self.head_start, self.seed, self.n + 1)

    def update(self, test, ctx, event):
        return self


def workload(opts: dict | None = None) -> dict:
    """Add jobs under faults; after recovery, one final read of the
    run log (chronos.clj test, 240-266)."""
    o = dict(opts or {})
    return {
        "generator": gen.limit(
            o.get("jobs", 20),
            gen.stagger(o.get("stagger", 0.05),
                        _AddJobGen(seed=o.get("seed")))),
        "final_generator": gen.once(
            lambda: {"f": "read", "value": None}),
        "checker": run_coverage_checker(),
    }
