"""Distributed lock-service workloads: mutual exclusion under faults,
checked as linearizability against mutex models — plain, owner-aware,
reentrant, fenced (monotonic fencing tokens), and a permit semaphore.

Capability reference: hazelcast/src/jepsen/hazelcast.clj —
fenced-lock-client (334-360: tryLockAndGetFence, ok carries the fence,
IllegalMonitorState -> fail not-lock-owner, IO "not send to owner" ->
definite fail, other IO -> info), the model zoo (513-650: ReentrantMutex,
OwnerAwareMutex, FencedMutex, ReentrantFencedMutex, AcquiredPermitsModel)
and the workloads map (660-760: acquire/release cycled per thread).

Design notes (TPU-first reshape): the reference threads a mutable
client-uid->name atom through the test map because knossos models can
only see op values; here the interpreter's process IS the client
identity, so models read `op.process` directly and declare
`tabulable = False`, routing them to the object-model host search
(`tpu/wgl.search_host_model`). Lock histories are short (locks
serialize!), so the host path is the right engine; the device kernels
keep handling the high-volume register/queue families.

Client contract:
  {"f": "acquire"} -> ok with value {"fence": int} (or None when the
                      lock service has no fencing tokens); fail when
                      the lock was busy / the try timed out.
  {"f": "release"} -> ok; fail with error "not-lock-owner" when the
                      client did not hold the lock.
Crashed (:info) acquires/releases are handled by the search's
indeterminacy rules like any other op.
"""

from __future__ import annotations

from .. import checker as chk
from .. import generator as gen
from ..checker import models

INVALID_FENCE = -1


def _fence(op) -> int:
    v = op.value
    if isinstance(v, dict) and v.get("fence") is not None:
        return v["fence"]
    return INVALID_FENCE


class OwnerMutex(models.Model):
    """Non-reentrant mutex that tracks WHO holds it: a release by a
    non-owner is inconsistent even if the lock is held
    (hazelcast.clj OwnerAwareMutex, 539-556)."""

    tabulable = False  # steps on op.process

    def __init__(self, owner=None):
        self.owner = owner

    def step(self, op):
        if op.f == "acquire":
            if self.owner is None:
                return OwnerMutex(op.process)
            return models.inconsistent(
                f"process {op.process} acquired a lock held by "
                f"{self.owner}")
        if op.f == "release":
            if self.owner is None or self.owner != op.process:
                return models.inconsistent(
                    f"process {op.process} released a lock held by "
                    f"{self.owner}")
            return OwnerMutex(None)
        return models.inconsistent(f"unknown f {op.f!r}")

    def __repr__(self):
        return f"OwnerMutex<{self.owner}>"


class FencedMutex(models.Model):
    """Owner-aware mutex whose successful acquires carry fencing
    tokens that must be strictly monotonic across the lock's lifetime
    (hazelcast.clj FencedMutex, 564-585): a stale fence means two
    holders could order their writes inconsistently at a downstream
    resource even if mutual exclusion held."""

    tabulable = False

    def __init__(self, owner=None, max_fence=INVALID_FENCE):
        self.owner = owner
        self.max_fence = max_fence

    def step(self, op):
        if op.f == "acquire":
            if self.owner is not None:
                return models.inconsistent(
                    f"process {op.process} acquired a lock held by "
                    f"{self.owner}")
            fence = _fence(op)
            if fence == INVALID_FENCE:
                return FencedMutex(op.process, self.max_fence)
            if fence > self.max_fence:
                return FencedMutex(op.process, fence)
            return models.inconsistent(
                f"non-monotonic fence {fence} (max seen "
                f"{self.max_fence})")
        if op.f == "release":
            if self.owner is None or self.owner != op.process:
                return models.inconsistent(
                    f"process {op.process} released a lock held by "
                    f"{self.owner}")
            return FencedMutex(None, self.max_fence)
        return models.inconsistent(f"unknown f {op.f!r}")

    def __repr__(self):
        return f"FencedMutex<{self.owner}, fence={self.max_fence}>"


class ReentrantMutex(models.Model):
    """Reentrant mutex: the holder may re-acquire up to `limit` times
    total; each release pops one level; releases by non-holders are
    inconsistent (hazelcast.clj ReentrantMutex, 513-531)."""

    tabulable = False

    def __init__(self, owner=None, count=0, limit=2):
        self.owner = owner
        self.count = count
        self.limit = limit

    def step(self, op):
        if op.f == "acquire":
            if self.count < self.limit and (
                    self.owner is None or self.owner == op.process):
                return ReentrantMutex(op.process, self.count + 1,
                                      self.limit)
            return models.inconsistent(
                f"process {op.process} cannot acquire "
                f"(owner={self.owner}, count={self.count})")
        if op.f == "release":
            if self.owner is None or self.owner != op.process:
                return models.inconsistent(
                    f"process {op.process} released a lock held by "
                    f"{self.owner}")
            if self.count == 1:
                return ReentrantMutex(None, 0, self.limit)
            return ReentrantMutex(self.owner, self.count - 1, self.limit)
        return models.inconsistent(f"unknown f {op.f!r}")

    def __repr__(self):
        return (f"ReentrantMutex<{self.owner}, {self.count}/"
                f"{self.limit}>")


class Semaphore(models.Model):
    """`permits` permits shared across processes; over-acquisition or
    releasing more than held is inconsistent (hazelcast.clj
    AcquiredPermitsModel, 630-650)."""

    tabulable = False

    def __init__(self, permits=2, held=()):
        self.permits = permits
        # held is a sorted tuple of (process, count) — hashable state
        self.held = tuple(held)

    def _held_by(self, process) -> int:
        for p, c in self.held:
            if p == process:
                return c
        return 0

    def _with(self, process, count):
        items = [(p, c) for p, c in self.held
                 if p != process and c > 0]
        if count > 0:
            items.append((process, count))
        return Semaphore(self.permits, tuple(sorted(items, key=repr)))

    def step(self, op):
        total = sum(c for _, c in self.held)
        mine = self._held_by(op.process)
        if op.f == "acquire":
            if total < self.permits:
                return self._with(op.process, mine + 1)
            return models.inconsistent(
                f"all {self.permits} permits held, process "
                f"{op.process} acquired another")
        if op.f == "release":
            if mine > 0:
                return self._with(op.process, mine - 1)
            return models.inconsistent(
                f"process {op.process} released a permit it never "
                f"held")
        return models.inconsistent(f"unknown f {op.f!r}")

    def __repr__(self):
        return f"Semaphore<{self.permits}, {self.held}>"


# ---------------------------------------------------------------------------
# Workloads
# ---------------------------------------------------------------------------

def _acquire_release_gen(o: dict, repeats: int = 1):
    """Each thread cycles acquire^repeats, release^repeats — matching
    the reference's per-thread cycled [acquire release] generator
    (hazelcast.clj workloads map)."""
    ops = ([{"f": "acquire", "value": None}] * repeats
           + [{"f": "release", "value": None}] * repeats)
    g = gen.each_thread(gen.cycle(ops))
    n = o.get("ops", 200)
    return gen.limit(n, gen.stagger(o.get("stagger", 0.001), g))


def _workload(o, model, repeats=1) -> dict:
    return {
        "generator": _acquire_release_gen(o, repeats),
        "checker": chk.linearizable({"model": model}),
    }


def lock_workload(opts: dict | None = None) -> dict:
    """Plain mutex — only tracks held/free (model.mutex parity)."""
    return _workload(dict(opts or {}), models.mutex())


def owner_lock_workload(opts: dict | None = None) -> dict:
    """Owner-aware mutex: wrong-owner releases are violations."""
    return _workload(dict(opts or {}), OwnerMutex())


def fenced_lock_workload(opts: dict | None = None) -> dict:
    """Owner-aware mutex + strictly monotonic fencing tokens."""
    return _workload(dict(opts or {}), FencedMutex())


def reentrant_lock_workload(opts: dict | None = None) -> dict:
    """Reentrant owner-aware mutex, acquire/acquire/release/release."""
    o = dict(opts or {})
    return _workload(o, ReentrantMutex(limit=o.get("limit", 2)),
                     repeats=o.get("limit", 2))


def semaphore_workload(opts: dict | None = None) -> dict:
    """Permit semaphore: conservation of `permits` permits."""
    o = dict(opts or {})
    return _workload(o, Semaphore(permits=o.get("permits", 2)))
