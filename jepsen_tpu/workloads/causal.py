"""Causal consistency workload: a per-key causal order of reads and
writes that every site must observe in issue order.

Capability reference: jepsen/src/jepsen/tests/causal.clj — its own tiny
Model protocol with a CausalRegister (value, counter, last-pos) whose
step enforces position links and counter-sequenced writes (10-81), a
checker folding :ok ops through the model (87-108), the ri/cw1/r/cw2
generators (111-115), and the independent-keyed test bundle (117-131).
"""

from __future__ import annotations

from .. import checker as chk
from .. import independent
from ..checker import _Fn
# one Inconsistent type across model layers, so is_inconsistent checks
# agree wherever a causal model flows (round-3 review finding)
from ..checker.models import (Inconsistent, inconsistent,  # noqa: F401
                              is_inconsistent)


class CausalRegister:
    """Register whose writes are counter-sequenced and whose ops carry
    position/link causality tokens (causal.clj CausalRegister,
    32-81)."""

    __slots__ = ("value", "counter", "last_pos")

    def __init__(self, value=0, counter=0, last_pos=None):
        self.value = value
        self.counter = counter
        self.last_pos = last_pos

    def step(self, op):
        c = self.counter + 1
        v = op.value
        pos = op.get("position")
        link = op.get("link")
        if not (link == "init" or link == self.last_pos):
            return inconsistent(
                f"Cannot link {link!r} to last-seen position "
                f"{self.last_pos!r}")
        if op.f == "write":
            if v == c:
                return CausalRegister(v, c, pos)
            return inconsistent(
                f"expected value {c} attempting to write {v} instead")
        if op.f == "read-init":
            if self.counter == 0 and v not in (None, 0):
                return inconsistent(f"expected init value 0, read {v}")
            if v is None or v == self.value:
                return CausalRegister(self.value, self.counter, pos)
            return inconsistent(
                f"can't read {v} from register {self.value}")
        if op.f == "read":
            if v is None or v == self.value:
                return CausalRegister(self.value, self.counter, pos)
            return inconsistent(
                f"can't read {v} from register {self.value}")
        return inconsistent(f"unknown f {op.f!r}")


def causal_register() -> CausalRegister:
    return CausalRegister(0, 0, None)


def check(model=None) -> chk.Checker:
    """Folds :ok ops through the causal model (causal.clj check,
    87-108)."""
    model = model if model is not None else causal_register()

    def run(test, hist, opts):
        s = model
        for op in hist:
            if op.type != "ok":
                continue
            s = s.step(op)
            if is_inconsistent(s):
                return {"valid?": False, "error": s.msg}
        return {"valid?": True, "model": s}

    return _Fn(run)


def ri(*_):
    return {"type": "invoke", "f": "read-init"}


def r(*_):
    return {"type": "invoke", "f": "read"}


def cw1(*_):
    return {"type": "invoke", "f": "write", "value": 1}


def cw2(*_):
    return {"type": "invoke", "f": "write", "value": 2}


def workload(opts: dict | None = None) -> dict:
    """One causal order (ri w1 r w2 r) per key, checked per key
    (causal.clj test, 117-131)."""
    from .. import generator as gen

    o = dict(opts or {})
    keys = o.get("keys", list(range(o.get("key-count", 8))))
    # one-shot dict elements: the reference's [ri cw1 r cw2 r] fn
    # vector relies on an outer time-limit to stop its infinite fn
    # generators; the five-op causal order itself is the point
    g = independent.sequential_generator(
        keys, lambda k: [ri(), cw1(), r(), cw2(), r()])
    return {
        "generator": gen.stagger(o.get("stagger", 0.01), g),
        "checker": independent.checker(check(causal_register())),
    }
