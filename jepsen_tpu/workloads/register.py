"""Linearizable register workload: per-key read/write/cas ops checked
with the TPU linearizable checker over independent keys.

Capability reference: jepsen/src/jepsen/tests/linearizable_register.clj
(independent/checker over checker/linearizable with a cas-register
model, per-key generators r/w/cas).
"""

from __future__ import annotations

from .. import util

from .. import checker as chk
from .. import independent
from ..checker import models


def r(rng):
    return {"f": "read", "value": None}


def w(rng, n=5):
    return {"f": "write", "value": rng.randrange(n)}


def cas(rng, n=5):
    return {"f": "cas", "value": [rng.randrange(n), rng.randrange(n)]}


def key_gen(k, ops_per_key=100, seed=None):
    """Mixed r/w/cas ops for one key."""
    rng = util.seeded_rng(seed, k)

    def one():
        return rng.choice([r, w, cas])(rng)

    from .. import generator as gen

    return gen.limit(ops_per_key, one)


def workload(opts: dict | None = None) -> dict:
    o = dict(opts or {})
    keys = o.get("keys", list(range(8)))
    n_group = o.get("group-size", o.get(
        "group_size", o.get("concurrency_per_key", 5)))
    ops_per_key = o.get("ops_per_key", 100)
    seed = o.get("seed")
    return {
        "generator": independent.concurrent_generator(
            n_group, keys, lambda k: key_gen(k, ops_per_key, seed)),
        "checker": independent.checker(chk.linearizable(
            {"model": models.cas_register(o.get("initial"))})),
    }


def cas_op_mix(rng, n_values: int = 5):
    """One random read/write/cas op dict per call — the canonical
    cas-register op mix every register suite uses (etcd, zookeeper;
    zookeeper.clj:74-76)."""
    r = rng.random()
    if r < 0.4:
        return {"f": "read", "value": None}
    if r < 0.7:
        return {"f": "write", "value": rng.randrange(n_values)}
    return {"f": "cas", "value": [rng.randrange(n_values),
                                  rng.randrange(n_values)]}
