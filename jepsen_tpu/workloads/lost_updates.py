"""Lost-updates probe: per-key sets grown by version-guarded
read-modify-write; after quiescence a final read per key must contain
every acknowledged add.

Capability reference: crate/src/jepsen/crate/lost_updates.clj — client
(33-100: add = select elements+_version, write back the extended list
guarded by _version, 0 rows -> fail / 1 -> ok / else info; read =
final set), test (109-146: independent keys, adds under a partition
nemesis, quiescence sleep, then per-thread final reads, checked by
independent set checkers).

The checker IS the set checker — what this workload contributes is the
op contract exercising optimistic-concurrency version guards:
  {"f": "add", "value": (k, v)} -> ok iff the guarded update applied
  {"f": "read", "value": (k, None)} -> ok with value (k, [elements])
"""

from __future__ import annotations

import itertools

from .. import checker as chk
from .. import generator as gen
from .. import independent


def workload(opts: dict | None = None) -> dict:
    o = dict(opts or {})
    keys = o.get("keys", list(range(o.get("key_count", 4))))
    n_group = o.get("group-size", o.get("group_size", 5))
    ops_per_key = o.get("ops_per_key", 100)

    def key_gen(k):
        counter = itertools.count()
        adds = gen.limit(ops_per_key,
                         lambda: {"f": "add", "value": next(counter)})
        final = gen.each_thread(gen.once(
            lambda: {"f": "read", "value": None}))
        return gen.phases(gen.stagger(o.get("stagger", 0.001), adds),
                          final)

    return {
        "generator": independent.concurrent_generator(
            n_group, keys, key_gen),
        "checker": independent.checker(chk.set_checker()),
    }
