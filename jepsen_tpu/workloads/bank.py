"""Bank workload: concurrent transfers between accounts must conserve
the total balance at every read.

Capability reference: jepsen/src/jepsen/tests/bank.clj — generators
(19-43: transfer with random from/to/amount, read), checker (56-120:
every ok read sums to :total-amount, no negative balances unless
:negative-balances? is set), bundle (178-191).
"""

from __future__ import annotations

import random

from .. import checker as chk
from ..checker import _Fn


def generator(accounts=None, max_transfer: int = 5, seed=None):
    accounts = list(accounts if accounts is not None else range(8))
    rng = random.Random(seed)

    def one():
        if rng.random() < 0.5:
            return {"f": "read", "value": None}
        frm, to = rng.sample(accounts, 2)
        return {"f": "transfer",
                "value": {"from": frm, "to": to,
                          "amount": rng.randint(1, max_transfer)}}

    return one


def checker(opts: dict | None = None) -> chk.Checker:
    o = dict(opts or {})

    def run(test, hist, copts):
        total = (test.get("total-amount")
                 if isinstance(test, dict) else None)
        if total is None:
            total = o.get("total-amount", 0)
        negative_ok = o.get("negative-balances?", False)
        bad_reads = []
        read_count = 0
        for op in hist:
            if op.type != "ok" or op.f != "read" or op.value is None:
                continue
            read_count += 1
            balances = list(op.value.values())
            s = sum(balances)
            if s != total:
                bad_reads.append({"type": "wrong-total", "expected": total,
                                  "found": s, "op": op})
            elif not negative_ok and any(b < 0 for b in balances):
                bad_reads.append({"type": "negative-value",
                                  "found": [b for b in balances if b < 0],
                                  "op": op})
        return {"valid?": ("unknown" if read_count == 0
                           else not bad_reads),
                "read-count": read_count,
                "error-count": len(bad_reads),
                "first-error": bad_reads[0] if bad_reads else None}

    return _Fn(run)


def workload(opts: dict | None = None) -> dict:
    from .. import generator as gen

    o = dict(opts or {})
    accounts = o.get("accounts", list(range(8)))
    g = generator(accounts, o.get("max-transfer", 5), o.get("seed"))
    if o.get("ops"):
        g = gen.limit(o["ops"], g)
    return {
        "accounts": accounts,
        "total-amount": o.get("total-amount",
                              len(accounts) * o.get("initial", 10)),
        "generator": g,
        "checker": chk.compose({"bank": checker(o),
                                "stats": chk.stats()}),
    }
