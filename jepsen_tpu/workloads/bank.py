"""Bank workload: concurrent transfers between accounts must conserve
the total balance at every read.

Capability reference: jepsen/src/jepsen/tests/bank.clj — generators
(19-43: transfer with random from/to/amount, read), checker (56-120:
every ok read sums to :total-amount, no negative balances unless
:negative-balances? is set), bundle (178-191).
"""

from __future__ import annotations

import random

from .. import checker as chk
from ..checker import _Fn


def generator(accounts=None, max_transfer: int = 5, seed=None):
    accounts = list(accounts if accounts is not None else range(8))
    rng = random.Random(seed)

    def one():
        if rng.random() < 0.5:
            return {"f": "read", "value": None}
        frm, to = rng.sample(accounts, 2)
        return {"f": "transfer",
                "value": {"from": frm, "to": to,
                          "amount": rng.randint(1, max_transfer)}}

    return one


def check_fast(hist, total: int, negative_ok: bool = False,
               device: bool = True) -> dict:
    """Balance-conservation check (SURVEY P4: chunked-fold checkers
    become array folds). Narrow reads (few accounts) take a plain
    C-builtin fold — at width ~8 the per-op dict iteration is the
    floor and array building only adds overhead; wide reads gather
    into a dense [reads, accounts] matrix whose sum/negative scans run
    as array reductions (on device for large histories, where the
    matrix ships to HBM once)."""
    import numpy as np

    from itertools import chain

    narrow = None
    read_count = 0
    err = 0
    bad_op = None
    vals: list = []
    ops = []
    for op in hist:
        if op.type == "ok" and op.f == "read" and op.value is not None:
            v = op.value.values()
            if narrow is None:
                narrow = len(v) < 12
            if narrow:
                # single-pass fold, same cost as the naive reference
                # loop — array building only adds overhead this narrow
                read_count += 1
                if sum(v) != total or (not negative_ok and v
                                       and min(v) < 0):
                    err += 1
                    if bad_op is None:
                        bad_op = op
            else:
                vals.append(v)
                ops.append(op)
    if narrow:
        first = None
        if err:
            v = list(bad_op.value.values())
            s = sum(v)
            first = ({"type": "wrong-total", "expected": total,
                      "found": s, "op": bad_op} if s != total else
                     {"type": "negative-value",
                      "found": [b for b in v if b < 0], "op": bad_op})
        return {"valid?": not err, "read-count": read_count,
                "error-count": err, "first-error": first}
    read_count = len(ops)
    if read_count == 0:
        return {"valid?": "unknown", "read-count": 0, "error-count": 0,
                "first-error": None}
    widths = np.fromiter(map(len, vals), dtype=np.int64,
                         count=read_count)
    width = int(widths.max())
    total_elems = int(widths.sum())
    flat = np.fromiter(chain.from_iterable(vals), dtype=np.int64,
                       count=total_elems)
    if width * read_count == total_elems:
        # homogeneous account sets: one C-speed reshape, no per-row copy
        mat = flat.reshape(read_count, width)
    else:
        mat = np.zeros((read_count, width), dtype=np.int64)
        offs = np.concatenate([[0], np.cumsum(widths)])[:-1]
        cols = np.arange(total_elems) - np.repeat(offs, widths)
        mat[np.repeat(np.arange(read_count), widths), cols] = flat
    if device and read_count >= 10_000:
        import jax.numpy as jnp

        dmat = jnp.asarray(mat)
        sums = np.asarray(jnp.sum(dmat, axis=1))
        negs = np.asarray(jnp.any(dmat < 0, axis=1))
    else:
        sums = mat.sum(axis=1)
        negs = (mat < 0).any(axis=1)
    wrong = sums != total
    bad = wrong if negative_ok else (wrong | negs)
    err = int(bad.sum())
    first = None
    if err:
        i = int(np.flatnonzero(bad)[0])
        if wrong[i]:
            first = {"type": "wrong-total", "expected": total,
                     "found": int(sums[i]), "op": ops[i]}
        else:
            first = {"type": "negative-value",
                     "found": [int(b) for b in mat[i] if b < 0],
                     "op": ops[i]}
    return {"valid?": not err, "read-count": read_count,
            "error-count": err, "first-error": first}


def checker(opts: dict | None = None) -> chk.Checker:
    o = dict(opts or {})

    def run(test, hist, copts):
        total = (test.get("total-amount")
                 if isinstance(test, dict) else None)
        if total is None:
            total = o.get("total-amount", 0)
        out = check_fast(hist, total,
                         negative_ok=o.get("negative-balances?",
                                           False))
        # coverage taxonomy tag, explicit negative included
        return chk.anomaly_classes(
            out, bank_imbalance=bool(out.get("error-count")))

    return _Fn(run)


def workload(opts: dict | None = None) -> dict:
    from .. import generator as gen
    from ..reports.perf import balance_graph

    o = dict(opts or {})
    accounts = o.get("accounts", list(range(8)))
    g = generator(accounts, o.get("max-transfer", 5), o.get("seed"))
    if o.get("ops"):
        g = gen.limit(o["ops"], g)
    return {
        "accounts": accounts,
        "total-amount": o.get("total-amount",
                              len(accounts) * o.get("initial", 10)),
        "generator": g,
        # the balance-over-time plot rides next to the conservation
        # verdict (bank.clj:150-176's plot entry in the bundle)
        "checker": chk.compose({"bank": checker(o),
                                "balance-plot": balance_graph(),
                                "stats": chk.stats()}),
    }
