"""Monotonic-inserts workload: each add reads the current max value
and inserts max+1 with a database timestamp; in the final read,
sorted by timestamp, timestamps must never run backwards (ties are
legal — non-strict <=) and values must strictly increase (a duplicate
value IS a reorder) — monotonic.clj comparator semantics.

Capability reference: cockroachdb/src/jepsen/cockroach/monotonic.clj —
client (81-140: add = query max, insert max+1 with system timestamp,
node, process, table id; read = all rows ordered by timestamp),
checker (180-248: lost / duplicate / revived / recovered values, plus
off-order detection globally and per process / node / table).

Client contract: "add" completes with a row dict
{"val", "sts", "node", "process", "tb"}; "read" completes with the
list of row dicts sorted by sts.
"""

from __future__ import annotations

import numpy as np

from .. import checker as chk
from .. import generator as gen


def _non_monotonic(rows, field, strict: bool) -> list:
    """Adjacent pairs where the field fails to increase. Per
    monotonic.clj check-monotonic: timestamps use non-strict <=
    (ties are legal — two txns may share a commit timestamp), while
    values use strict < (a duplicate value IS a reorder)."""
    vals = np.asarray([r[field] for r in rows])
    if len(vals) < 2:
        return []
    ok = (vals[:-1] < vals[1:]) if strict else (vals[:-1] <= vals[1:])
    return [(rows[i], rows[i + 1]) for i in np.flatnonzero(~ok)]


def _non_monotonic_by(rows, group_field, field) -> dict:
    groups: dict = {}
    for r in rows:
        groups.setdefault(r[group_field], []).append(r)
    return {g: _non_monotonic(rs, field, strict=True)
            for g, rs in sorted(groups.items())}


def check_monotonic(hist, global_: bool = True) -> dict:
    """monotonic.clj check-monotonic (180-248)."""
    adds, fails, infos = [], set(), set()
    final_read = None
    for op in hist:
        if op.f == "add":
            if op.type == "ok" and isinstance(op.value, dict):
                adds.append(op.value["val"])
            elif op.type == "fail" and isinstance(op.value, dict):
                fails.add(op.value["val"])
            elif op.type == "info" and isinstance(op.value, dict):
                infos.add(op.value["val"])
        elif op.f == "read" and op.type == "ok":
            final_read = op.value
    if final_read is None:
        return {"valid?": "unknown", "error": "Set was never read"}
    rows = list(final_read)
    vals = [r["val"] for r in rows]
    counts: dict = {}
    for v in vals:
        counts[v] = counts.get(v, 0) + 1
    dups = {v for v, c in counts.items() if c > 1}
    read_set = set(vals)
    adds_set = set(adds)
    lost = adds_set - read_set
    revived = read_set & fails
    recovered = read_set & infos
    off_sts = _non_monotonic(rows, "sts", strict=False)
    off_vals = _non_monotonic(rows, "val", strict=True)
    by_process = _non_monotonic_by(rows, "process", "val")
    by_node = _non_monotonic_by(rows, "node", "val")
    by_table = _non_monotonic_by(rows, "tb", "val")
    valid = (not lost and not dups and not revived and not off_sts
             and (not global_ or not off_vals)
             and all(not v for v in by_process.values()))
    return {
        "valid?": valid,
        "lost": sorted(lost),
        "duplicates": sorted(dups),
        "revived": sorted(revived),
        "recovered": sorted(recovered),
        "order-by-errors": off_sts[:8],
        "value-reorders": off_vals[:8],
        "value-reorders-per-process": {
            g: v[:4] for g, v in by_process.items() if v},
        "value-reorders-per-node": {
            g: v[:4] for g, v in by_node.items() if v},
        "value-reorders-per-table": {
            g: v[:4] for g, v in by_table.items() if v},
        "add-count": len(adds),
        "read-count": len(rows),
    }


def workload(opts: dict | None = None) -> dict:
    """Adds under faults, then final reads after recovery
    (monotonic.clj test, 251-282)."""
    o = dict(opts or {})
    n = o.get("ops", 300)
    return {
        "generator": gen.limit(n, lambda: {"f": "add", "value": None}),
        "final_generator": gen.each_thread(gen.once(
            lambda: {"f": "read", "value": None})),
        "checker": chk.checker(
            lambda test, hist, _o:
            check_monotonic(hist, global_=o.get("global", True))),
    }
