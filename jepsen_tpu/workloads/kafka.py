"""Kafka/Redpanda-style queue workload: totally-ordered append-only
partitions, producers sending [offset value] messages, consumers
polling ranges, with the full anomaly analysis.

Capability reference: jepsen/src/jepsen/tests/kafka.clj (the
reference's largest workload, 2149 LoC) — operation encoding
(kafka.clj:24-97), version orders from send/poll offset agreement
(docstring §2, inconsistent-offsets), aborted reads (§1, G1a), lost
writes below the highest observed offset (§3, lost-write), unseen
messages, ww/wr dependency cycles via elle (§4), internal poll/send
contiguity, external poll contiguity, and nonmonotonic sends (§5-6;
external send-SKIPS are deliberately not detected, matching the
reference — "We don't even bother looking at external send skips",
kafka.clj:2022), duplicates, and the
allowed-error-type policy (kafka.clj:2019-2046: int-send-skip and G0
always allowed; poll-skip/nonmonotonic-poll allowed under subscribe;
G1c allowed when ww edges are inferred).

Operation encoding (mirrors the reference):
  {"f": "subscribe"|"assign", "value": [k, ...]}
  {"f": "send"|"poll"|"txn", "value": [mop, ...]}
    send mop: ["send", k, v] -> completed ["send", k, [offset, v]]
    poll mop: ["poll"] -> completed ["poll", {k: [[offset, v], ...]}]

The analysis interns values per key and leans on the elle engine's
cycle machinery (classification + witness extraction); version orders
and contiguity checks are array-friendly rank lookups.
"""

from __future__ import annotations

import random
from collections import defaultdict

from .. import checker as chk
from .. import generator as gen
from .. import history as h
from ..checker import _Fn
from ..history import History
from ..tpu import elle

# Error types allowed regardless of configuration
# (kafka.clj:2019-2035).
_ALWAYS_ALLOWED = {"int-send-skip", "G0", "G0-process", "G0-realtime"}

_TXN_FS = ("txn", "send", "poll")


# ---------------------------------------------------------------------------
# Generator
# ---------------------------------------------------------------------------

def generator(n_keys: int = 4, max_txn: int = 4, send_p: float = 0.5,
              subscribe_p: float = 0.05, seed=None):
    """Mix of send/poll txns with occasional subscribe ops re-assigning
    the consumer's partitions (kafka.clj txn-generator + interleave of
    subscribe ops)."""
    rng = random.Random(seed)
    next_val = [0]

    def one():
        if rng.random() < subscribe_p:
            ks = sorted(rng.sample(range(n_keys),
                                   rng.randint(1, n_keys)))
            return {"f": "subscribe", "value": ks}
        mops = []
        for _ in range(rng.randint(1, max_txn)):
            if rng.random() < send_p:
                next_val[0] += 1
                mops.append(["send", rng.randrange(n_keys),
                             next_val[0]])
            else:
                mops.append(["poll"])
        fs = {m[0] for m in mops}
        f = "send" if fs == {"send"} else (
            "poll" if fs == {"poll"} else "txn")
        return {"f": f, "value": mops}

    return one


# ---------------------------------------------------------------------------
# Analysis
# ---------------------------------------------------------------------------

def _collect(hist: History) -> list:
    """Pairs invocations with completions like elle.collect, but keeps
    the COMPLETION micro-ops for :info ops too — an indeterminate send
    may still report the offsets it wrote, and dropping them would hide
    e.g. offset conflicts it witnessed (round-3 review finding)."""
    txns = []
    open_inv: dict = {}
    for pos, o in enumerate(hist):
        if not h.is_client_op(o):
            continue
        if o.type == h.INVOKE:
            open_inv[o.process] = (pos, o)
        elif o.type in (h.OK, h.FAIL, h.INFO):
            pair = open_inv.pop(o.process, None)
            if pair is None:
                continue
            inv_pos, inv = pair
            mops = (o.value if (o.type in (h.OK, h.INFO)
                                and o.value is not None) else inv.value)
            txns.append(elle.Txn(len(txns), o, o.type, o.process,
                                 inv_pos, pos, mops or []))
    for inv_pos, inv in open_inv.values():
        txns.append(elle.Txn(len(txns), inv, h.INFO, inv.process,
                             inv_pos, 1 << 60, inv.value or []))
    return txns


def _mop_sends(mops):
    for m in mops or []:
        if m[0] == "send":
            yield m


def _mop_polls(mops):
    for m in mops or []:
        if m[0] == "poll":
            yield m


class Analysis:
    """Builds version orders and every anomaly class from a history
    (kafka.clj `analysis`, 1881-1984)."""

    def __init__(self, hist: History, ww_deps: bool = True,
                 sub_via=("subscribe",)):
        self.ww_deps = ww_deps
        self.sub_via = set(sub_via)
        self.errors: dict[str, list] = defaultdict(list)
        # one paired stream: txn/send/poll ops carry micro-ops,
        # subscribe/assign ops mark consumer resets
        self.stream = _collect(hist)
        self.obs = list(self._observations())
        self._version_orders()
        self._writers_readers()
        self._g1a()
        self._duplicates()
        self._lost_and_unseen()
        self._contiguity()
        self._cycles()

    # -- version orders ----------------------------------------------------

    def _observations(self):
        """Yields (txn, key, offset, value, kind) for every offset
        observation: kind 'send' (ok/info send completions that carry
        offsets) or 'poll' (ok poll reads)."""
        for t in self.stream:
            f = t.op.f
            if f not in _TXN_FS:
                continue
            if t.type == h.OK or (t.type == h.INFO and f != "poll"):
                for m in _mop_sends(t.mops):
                    v = m[2]
                    if isinstance(v, list) and len(v) == 2:
                        off, val = v
                        if off is not None:
                            yield t, m[1], off, val, "send"
            if t.type == h.OK:
                for m in _mop_polls(t.mops):
                    if len(m) > 1 and isinstance(m[1], dict):
                        for k, pairs in m[1].items():
                            for off, val in pairs:
                                if off is not None:
                                    yield t, k, off, val, "poll"

    def _version_orders(self):
        """offset -> value per key; conflicting values at one offset
        are inconsistent-offsets errors. The per-key version order is
        the offset-sorted value list (rank order: gaps in offsets are
        transaction-metadata slots and carry no meaning)."""
        by_key: dict = defaultdict(dict)  # k -> off -> set(values)
        for _t, k, off, val, _kind in self.obs:
            by_key[k].setdefault(off, set()).add(val)
        self.orders: dict = {}       # k -> [v in offset order]
        self.rank: dict = {}         # (k, v) -> rank
        self.offset_of: dict = {}    # (k, v) -> offset
        for k, offs in by_key.items():
            bad = {o: sorted(vs, key=repr) for o, vs in offs.items()
                   if len(vs) > 1}
            if bad:
                self.errors["inconsistent-offsets"].append(
                    {"key": k, "values": bad})
            order = []
            for o in sorted(offs):
                v = next(iter(offs[o]))
                self.offset_of[(k, v)] = o
                self.rank[(k, v)] = len(order)
                order.append(v)
            self.orders[k] = order

    # -- writers / readers -------------------------------------------------

    def _writers_readers(self):
        self.writer_of: dict = {}     # (k, v) -> txn
        self.readers_of: dict = defaultdict(list)
        for t in self.stream:
            if t.op.f not in _TXN_FS:
                continue
            for m in _mop_sends(t.mops):
                v = m[2]
                val = (v[1] if isinstance(v, list) and len(v) == 2
                       else v)
                if val is None:
                    continue
                prev = self.writer_of.get((m[1], val))
                if (prev is not None and prev is not t
                        and prev.type != h.FAIL and t.type != h.FAIL):
                    self.errors["duplicate"].append(
                        {"key": m[1], "value": val,
                         "writers": [prev.op, t.op]})
                if t.type != h.FAIL or prev is None:
                    self.writer_of[(m[1], val)] = t
            if t.type == h.OK:
                for m in _mop_polls(t.mops):
                    if len(m) > 1 and isinstance(m[1], dict):
                        for k, pairs in m[1].items():
                            for _off, val in pairs:
                                self.readers_of[(k, val)].append(t)

    def _g1a(self):
        """Reads of values whose writer :failed (kafka.clj docstring
        §1)."""
        for (k, v), readers in self.readers_of.items():
            w = self.writer_of.get((k, v))
            if w is not None and w.type == h.FAIL:
                self.errors["G1a"].append(
                    {"key": k, "value": v, "writer": w.op,
                     "readers": [r.op for r in readers[:4]]})

    def _duplicates(self):
        """A value at more than one offset in a key's log (kafka.clj
        duplicate-cases)."""
        seen: dict = defaultdict(set)
        for _t, k, off, val, _kind in self.obs:
            seen[(k, val)].add(off)
        for (k, val), offs in seen.items():
            if len(offs) > 1:
                self.errors["duplicate-offsets"].append(
                    {"key": k, "value": val, "offsets": sorted(offs)})

    def _lost_and_unseen(self):
        """§3: every ok send at or below a key's highest *polled*
        offset must have been polled by someone (else: lost-write);
        acknowledged sends above it that nobody ever polled are
        'unseen' — an error at history end (check() flags any
        leftover unseen; the workload's final drain phase exists so
        healthy runs come back clean)."""
        highest_polled: dict = {}
        for t, k, off, _val, kind in self.obs:
            if kind == "poll":
                highest_polled[k] = max(highest_polled.get(k, -1), off)
        unseen: dict = defaultdict(list)
        for t in self.stream:
            if t.type != h.OK or t.op.f not in _TXN_FS:
                continue
            for m in _mop_sends(t.mops):
                v = m[2]
                if not (isinstance(v, list) and len(v) == 2):
                    continue
                off, val = v
                k = m[1]
                if self.readers_of.get((k, val)):
                    continue
                if off is not None and off <= highest_polled.get(k, -1):
                    self.errors["lost-write"].append(
                        {"key": k, "value": val, "offset": off,
                         "writer": t.op,
                         "highest-polled": highest_polled.get(k)})
                else:
                    unseen[k].append(val)
        self.unseen = dict(unseen)

    # -- contiguity --------------------------------------------------------

    def _contiguity(self):
        """§5-6: poll/send offset-rank contiguity, both within a txn
        (int-*) and across txns per process (external). Assignment
        changes reset external poll tracking (a rebalance legitimately
        moves the consumer)."""
        last_polled: dict = {}   # (process, k) -> rank
        last_sent: dict = {}     # (process, k) -> rank
        for t in self.stream:
            f = t.op.f
            p = t.process
            if f in ("subscribe", "assign"):
                if t.type != h.FAIL:  # failed re-assignment changes nothing
                    for key in list(last_polled):
                        if key[0] == p:
                            del last_polled[key]
                continue
            if f not in _TXN_FS or t.type != h.OK:
                continue
            int_polled: dict = {}
            int_sent: dict = {}
            for m in t.mops:
                if m[0] == "poll" and len(m) > 1 and isinstance(
                        m[1], dict):
                    for k, pairs in m[1].items():
                        for _off, val in pairs:
                            r = self.rank.get((k, val))
                            if r is None:
                                continue
                            for scope, store, ext in (
                                    ("int", int_polled, False),
                                    ("ext", last_polled, True)):
                                key = (p, k) if ext else k
                                prev = store.get(key)
                                if prev is not None:
                                    delta = r - prev
                                    if delta <= 0:
                                        name = ("nonmonotonic-poll"
                                                if ext else
                                                "int-nonmonotonic-poll")
                                        self.errors[name].append(
                                            {"key": k, "delta": delta,
                                             "op": t.op})
                                    elif delta > 1 and not ext:
                                        self.errors[
                                            "int-poll-skip"].append(
                                            {"key": k, "delta": delta,
                                             "op": t.op})
                                    elif delta > 1 and ext:
                                        self.errors["poll-skip"].append(
                                            {"key": k, "delta": delta,
                                             "op": t.op})
                                store[key] = r
                elif m[0] == "send":
                    v = m[2]
                    if not (isinstance(v, list) and len(v) == 2):
                        continue
                    k = m[1]
                    r = self.rank.get((k, v[1]))
                    if r is None:
                        continue
                    for scope, store, ext in (
                            ("int", int_sent, False),
                            ("ext", last_sent, True)):
                        key = (p, k) if ext else k
                        prev = store.get(key)
                        if prev is not None:
                            delta = r - prev
                            if delta <= 0:
                                name = ("nonmonotonic-send" if ext
                                        else "int-nonmonotonic-send")
                                self.errors[name].append(
                                    {"key": k, "delta": delta,
                                     "op": t.op})
                            elif delta > 1 and not ext:
                                self.errors["int-send-skip"].append(
                                    {"key": k, "delta": delta,
                                     "op": t.op})
                        store[key] = r

    # -- realtime lag ------------------------------------------------------

    def realtime_lag(self) -> dict:
        """How far each consumer ran behind the log, in wall time
        (kafka.clj realtime-lag, 1359): at every ok poll completion,
        the lag is the age of the oldest acknowledged-but-not-yet-
        polled message on that consumer's keys — 0 when caught up.
        Returns the stats tail: worst observation plus per-(process,
        key) final lags. Shares _LagTracker with the live lag_probe so
        post-hoc and streamed numbers can't diverge."""
        tracker = _LagTracker()
        worst = None
        final: dict = {}                  # (process, k) -> last lag ms
        for t in self.stream:             # completion order
            if t.op.f not in _TXN_FS:
                continue
            if t.type == h.OK or (t.type == h.INFO
                                  and t.op.f != "poll"):
                for m in _mop_sends(t.mops):
                    tracker.ack_send(m, t.op.time)
            if t.type != h.OK:
                continue
            for m in _mop_polls(t.mops):
                if not (len(m) > 1 and isinstance(m[1], dict)):
                    continue
                for k, lag_ms in tracker.poll_lags(
                        t.process, m[1], t.op.time):
                    final[(t.process, k)] = lag_ms
                    if worst is None or lag_ms > worst["lag-ms"]:
                        worst = {"process": t.process, "key": k,
                                 "time": t.op.time, "lag-ms": lag_ms}
        return {
            "worst-realtime-lag": worst or {"lag-ms": 0.0},
            "max-lag-ms": worst["lag-ms"] if worst else 0.0,
            "final-lags-ms": {f"{p}:{k}": v
                              for (p, k), v in sorted(
                                  final.items(), key=repr)},
        }

    # -- dependency cycles -------------------------------------------------

    def _cycles(self):
        """§4: ww (adjacent versions, when ww_deps) and wr (highest
        read of a key reads-from its writer), plus session/realtime
        order, classified through the elle engine's cycle machinery.
        No rw anti-dependency edges: a consumer legitimately lags the
        log, so reading version r while r+1 exists implies nothing —
        the reference leaves its rw-graph commented out for the same
        reason (kafka.clj:1859)."""
        txns = [t for t in self.stream if t.op.f in _TXN_FS]
        index = {id(t): i for i, t in enumerate(txns)}
        edges: list[tuple[int, int, int]] = []
        if self.ww_deps:
            for k, order in self.orders.items():
                prev = None
                for v in order:
                    w = self.writer_of.get((k, v))
                    if w is None or w.type == h.FAIL:
                        continue
                    if prev is not None and prev is not w:
                        edges.append((index[id(prev)], index[id(w)],
                                      elle.WW))
                    prev = w
        for t in txns:
            if t.type != h.OK:
                continue
            # wr-graph (kafka.clj:1840-1852): writer of v -> EVERY txn
            # that polled v, for every polled value (a highest-only
            # link misses cycles closed through older reads)
            linked: set = set()
            for m in _mop_polls(t.mops):
                if len(m) > 1 and isinstance(m[1], dict):
                    for k, pairs in m[1].items():
                        for _off, val in pairs:
                            w = self.writer_of.get((k, val))
                            if (w is not None and w is not t
                                    and w.type != h.FAIL
                                    and id(w) not in linked):
                                linked.add(id(w))
                                edges.append((index[id(w)],
                                              index[id(t)], elle.WR))
        committed = []
        for i, t in enumerate(txns):
            if t.type == h.OK:
                t2 = elle.Txn(i, t.op, t.type, t.process, t.invoke_pos,
                              t.complete_pos, t.mops)
                committed.append(t2)
        src, dst, ty = elle.order_edge_arrays(committed)
        edges.extend(zip(src.tolist(), dst.tolist(), ty.tolist()))
        for name, ws in elle.cycle_anomalies(
                len(txns), list(dict.fromkeys(edges)), txns).items():
            self.errors[name] = ws


def check(hist, opts: dict | None = None) -> dict:
    """kafka.clj `checker`: runs the analysis, then filters error
    types through the allowed-error policy."""
    o = dict(opts or {})
    if not isinstance(hist, History):
        hist = History(hist)
    a = Analysis(hist, ww_deps=o.get("ww-deps", True),
                 sub_via=o.get("sub-via", ("subscribe",)))
    allowed = set(_ALWAYS_ALLOWED)
    if "subscribe" in a.sub_via:
        allowed |= {"poll-skip", "nonmonotonic-poll"}
    if a.ww_deps:
        allowed |= {"G1c", "G1c-process", "G1c-realtime"}
    errors = {k: v for k, v in a.errors.items() if v}
    if a.unseen:
        # kafka.clj's last-unseen: acked sends nobody ever polled are
        # an error at history end (the workload's final polls drain,
        # so healthy runs come back clean)
        errors["unseen"] = [
            {"key": k, "count": len(vs), "messages": sorted(vs)[:32]}
            for k, vs in sorted(a.unseen.items())]
    bad = sorted(k for k in errors if k not in allowed)
    # condense-error ordering: skip/nonmonotonic families sort by how
    # far the offset jumped (worst first)
    _DELTA = {"poll-skip", "int-poll-skip", "int-send-skip"}
    _NEG_DELTA = {"nonmonotonic-poll", "nonmonotonic-send",
                  "int-nonmonotonic-poll", "int-nonmonotonic-send"}
    out_errors = {}
    for k, v in errors.items():
        if k in _DELTA:
            v = sorted(v, key=lambda e: -e.get("delta", 0))
        elif k in _NEG_DELTA:
            v = sorted(v, key=lambda e: e.get("delta", 0))
        out_errors[k] = v[:8]
    return {
        "valid?": not bad,
        "error-types": sorted(errors.keys()),
        "bad-error-types": bad,
        "errors": out_errors,
        "unseen": {k: len(v) for k, v in a.unseen.items()},
        # the stats tail (kafka.clj realtime-lag + unseen recovery):
        # how far consumers ran behind, and what never surfaced
        "realtime-lag": dict(
            a.realtime_lag(),
            **{"unseen-at-end": {k: len(v) for k, v in sorted(
                a.unseen.items())}}),
    }


def checker(opts: dict | None = None) -> chk.Checker:
    o = dict(opts or {})

    def run(test, hist, copts):
        merged = dict(o)
        if isinstance(test, dict):
            for key in ("ww-deps", "sub-via"):
                if key in test:
                    merged[key] = test[key]
        return check(hist, merged)

    return _Fn(run)


class _LagTracker:
    """The realtime-lag bookkeeping shared by the post-hoc analysis
    (Analysis.realtime_lag) and the live monitor probe (lag_probe):
    acked sends per key, each consumer's highest polled offset, and
    the age of the oldest acked-but-unpolled message at a poll.

    Memory/scan cost is bounded by pruning acked entries every
    consumer known to poll a key has passed: a long run holds only the
    slowest consumer's backlog per key, not every send ever acked.
    (Tradeoff: a consumer that starts polling a key only late in the
    run can't be charged for messages pruned before its first poll —
    the lag it reports from then on is still exact.)"""

    def __init__(self):
        self.acked: dict = defaultdict(list)  # k -> [(off, ack t ns)]
        self.hp: dict = defaultdict(dict)     # k -> {process: off}

    def ack_send(self, mop, now: int) -> None:
        """Records one completed send mop carrying [offset, value]."""
        v = mop[2]
        if isinstance(v, list) and len(v) == 2 and v[0] is not None:
            self.acked[mop[1]].append((v[0], now))

    def poll_lags(self, process, reads: dict, now: int):
        """Yields (key, lag_ms) for one ok poll's {k: pairs} reads."""
        for k, pairs in reads.items():
            frontiers = self.hp[k]
            seen = frontiers.get(process, -1)
            for off, _val in pairs:
                if off is not None and off > seen:
                    seen = off
            frontiers[process] = seen
            acked = self.acked[k]
            oldest = min((at for off, at in acked if off > seen),
                         default=None)
            floor = min(frontiers.values())
            if floor >= 0:
                self.acked[k] = [e for e in acked if e[0] > floor]
            yield k, (round((now - oldest) / 1e6, 3)
                      if oldest is not None else 0.0)


def lag_probe():
    """A live-monitor probe (jepsen_tpu.monitor probe protocol: a
    factory returning `probe(op, monitor)`) streaming consumer
    realtime lag into the run's time-series — the online counterpart
    of Analysis.realtime_lag, over the same _LagTracker."""
    tracker = _LagTracker()

    def probe(op, monitor):
        # ok AND info completions ack their sends (an indeterminate
        # send that reported offsets still landed — same rule as the
        # post-hoc path, so live and stored lag numbers agree); polls
        # only count when ok
        if op.type not in (h.OK, h.INFO) or op.f not in _TXN_FS \
                or not isinstance(op.value, list):
            return
        max_lag = None
        for m in op.value:
            if m[0] == "send":
                tracker.ack_send(m, op.time)
            elif (op.type == h.OK and m[0] == "poll" and len(m) > 1
                    and isinstance(m[1], dict)):
                for _k, lag in tracker.poll_lags(
                        op.process, m[1], op.time):
                    if max_lag is None or lag > max_lag:
                        max_lag = lag
        if max_lag is not None:
            monitor.probe_gauge("kafka.realtime-lag-ms", max_lag)

    return probe


class _DrainGen(gen.Generator):
    """Emits polls until this thread's LAST completed poll returned
    no pairs (caught up with the tail). Functional: state advances on
    completion events via update(), never on probes."""

    def __init__(self, done: bool = False):
        self.done = done

    def op(self, test, ctx):
        if self.done:
            return None  # exhausted (the op() protocol's bare None)
        m = gen.fill_in_op({"f": "poll", "value": [["poll"]]}, ctx)
        if m is gen.PENDING:
            return gen.PENDING, self
        return m, self

    def update(self, test, ctx, event):
        if (event.type == h.OK and event.f == "poll"
                and isinstance(event.value, list)):
            polled = any(
                m[0] == "poll" and len(m) > 1
                and isinstance(m[1], dict) and any(m[1].values())
                for m in event.value)
            if not polled:
                return _DrainGen(done=True)
        return self


def workload(opts: dict | None = None) -> dict:
    from .. import generator as gen

    o = dict(opts or {})
    n_keys = o.get("n-keys", 4)
    g = generator(n_keys=n_keys,
                  max_txn=o.get("max-txn-length", 4),
                  seed=o.get("seed"))
    if o.get("ops"):
        g = gen.limit(o["ops"], g)
    # final drain (the reference's final-polls loop, kafka.clj
    # 405-432: repeat assign+poll until caught up): every thread takes
    # ownership of all keys, then polls until a poll comes back EMPTY
    # (the log tail), bounded by final-polls as a safety cap — so
    # acked-but-unpolled sends don't read as 'unseen' errors. Clients
    # with bounded poll batches drain across iterations.
    keys = list(range(n_keys))
    final = gen.each_thread(gen.phases(
        gen.once(lambda: {"f": "assign", "value": keys}),
        gen.limit(o.get("final-polls", 32), _DrainGen())))
    return {
        "generator": g,
        "final_generator": final,
        "checker": chk.compose({"kafka": checker(o),
                                "stats": chk.stats()}),
        # consumer lag streams into timeseries.jsonl while the test
        # runs (core.run hands these factories to the Monitor)
        "monitor_probes": [lag_probe],
    }
