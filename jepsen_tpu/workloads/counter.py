"""Counter workload: concurrent increments + reads; every read must lie
between acknowledged and attempted sums.

Capability reference: jepsen/src/jepsen/checker.clj counter (749-819);
generator shape from suite counter tests (aerospike/cockroach).
"""

from __future__ import annotations

import random

from .. import checker as chk
from .. import generator as gen


def workload(opts: dict | None = None) -> dict:
    o = dict(opts or {})
    n = o.get("ops", 300)
    rng = random.Random(o.get("seed"))

    def add():
        return {"f": "add", "value": rng.randint(1, 5)}

    def read():
        return {"f": "read", "value": None}

    return {
        "generator": gen.limit(n, gen.mix([add, add, read])),
        "checker": chk.counter(),
    }
