"""Upsert-uniqueness workload: many clients concurrently upsert the
same key; at most ONE upsert may succeed per key, and every read must
see at most one record id.

Capability reference: dgraph/src/jepsen/dgraph/upsert.clj — client
(upsert by indexed key -> ok iff inserted, value carries the created
uid; read -> sorted uids for the key), checker (54-69: at most one ok
upsert, no read returns more than one uid), workload (71-81:
independent keys, phases of each-thread upsert then each-thread read).

Client contract (per key, via independent tuples):
  {"f": "upsert", "value": (k, None)} -> ok with value (k, uid) iff
      this client created the record; fail if it already existed
      (or the transaction conflicted).
  {"f": "read", "value": (k, None)} -> ok with value (k, [uids...]),
      sorted.
"""

from __future__ import annotations

from .. import checker as chk
from .. import generator as gen
from .. import independent


def check_upsert(hist) -> dict:
    """upsert.clj checker (54-69): at most one ok upsert per key; no
    ok read observes >1 record."""
    ok_upserts = []
    bad_reads = []
    for op in hist:
        if op.type != "ok":
            continue
        if op.f == "upsert":
            ok_upserts.append(op)
        elif op.f == "read":
            v = op.value
            if isinstance(v, (list, tuple)) and len(v) > 1:
                bad_reads.append(op)
    return {
        "valid?": not bad_reads and len(ok_upserts) <= 1,
        "ok-upsert-count": len(ok_upserts),
        "ok-upserts": [{"process": o.process, "value": o.value}
                       for o in ok_upserts[:8]],
        "bad-reads": [{"process": o.process, "value": o.value}
                      for o in bad_reads[:8]],
    }


def checker() -> chk.Checker:
    return chk.checker(lambda test, hist, opts: check_upsert(hist))


def workload(opts: dict | None = None) -> dict:
    """Per-key: every thread upserts the key once, then every thread
    reads it back (upsert.clj workload, 71-81)."""
    o = dict(opts or {})
    keys = o.get("keys", list(range(o.get("key_count", 16))))
    n_group = o.get("group-size", o.get("group_size", 4))

    def key_gen(k):
        return gen.phases(
            gen.each_thread(gen.once(
                lambda: {"f": "upsert", "value": None})),
            gen.each_thread(gen.once(
                lambda: {"f": "read", "value": None})))

    return {
        "generator": independent.concurrent_generator(
            n_group, keys, key_gen),
        "checker": independent.checker(checker()),
    }
