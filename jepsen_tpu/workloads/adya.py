"""Adya anomaly probes: G2 (anti-dependency cycles through predicate
reads).

Capability reference: jepsen/src/jepsen/tests/adya.clj — g2-gen emits,
per concurrent unique key, exactly two :insert ops [key [a-id b-id]]
(one with a-id, one with b-id); clients run predicate reads over two
tables and insert only if both come back empty, so under
serializability at most one insert per key can commit (11-57);
g2-checker counts successful inserts per key and flags keys with more
than one (59-86).
"""

from __future__ import annotations

import itertools

from .. import checker as chk
from .. import independent
from ..checker import _Fn


def g2_gen(keys=None):
    """Two racing inserts per key: [k [None b]] and [k [a None]]
    (adya.clj g2-gen, 11-57). keys must be finite (the reference's
    infinite (range) relies on an outer time-limit; our concurrent
    generator materializes the key sequence)."""
    ids = itertools.count(1)
    keys = list(keys) if keys is not None else list(range(1, 65))

    def per_key(k):
        return [{"type": "invoke", "f": "insert",
                 "value": [None, next(ids)]},
                {"type": "invoke", "f": "insert",
                 "value": [next(ids), None]}]

    return independent.concurrent_generator(2, keys, per_key)


def g2_checker() -> chk.Checker:
    """At most one successful insert per key (adya.clj g2-checker,
    59-86)."""

    def run(test, hist, opts):
        keys: dict = {}
        for op in hist:
            if op.f != "insert" or op.type == "invoke":
                continue
            k = independent.key_(op.value)
            keys.setdefault(k, 0)
            if op.type == "ok":
                keys[k] += 1
        illegal = {k: n for k, n in sorted(keys.items(), key=str)
                   if n > 1}
        insert_count = sum(1 for n in keys.values() if n > 0)
        return {
            "valid?": not illegal,
            "key-count": len(keys),
            "legal-count": insert_count - len(illegal),
            "illegal-count": len(illegal),
            "illegal": illegal,
        }

    return _Fn(run)


def workload(opts: dict | None = None) -> dict:
    o = dict(opts or {})
    keys = o.get("keys", list(range(1, o.get("key-count", 16) + 1)))
    return {
        "generator": g2_gen(keys),
        "checker": g2_checker(),
    }
