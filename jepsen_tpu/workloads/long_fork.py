"""Long-fork (PSI) anomaly workload: single-key writes plus group reads;
two reads that observe a pair of writes in incompatible orders are a
long fork.

Capability reference: jepsen/src/jepsen/tests/long_fork.clj (docstring
1-95: groups of n keys, one write per key, reads over whole groups;
detection = incomparable read pairs). The reference builds a read
adjacency by Hamming-like distance; here the pairwise incomparability
test is two boolean matmuls over the read-presence matrix (fork(i,j) iff
(R @ ~R.T)[i,j] and [j,i]) — the same formulation the device kernel
batches on the MXU for big histories.
"""

from __future__ import annotations

import itertools

import numpy as np

from .. import checker as chk
from .. import generator as gen
from ..checker import _Fn


def generator(group_size: int = 3, ops: int = 300):
    """Writes each key once (value 1); reads whole key groups."""
    counter = itertools.count()

    def one():
        i = next(counter)
        group = (i // (group_size * 4)) * group_size
        keys = list(range(group, group + group_size))
        if i % 4 == 0:  # one write slot per key round-robin
            k = keys[(i // 4) % group_size]
            return {"f": "txn", "value": [["w", k, 1]]}
        return {"f": "txn", "value": [["r", k, None] for k in keys]}

    return gen.limit(ops, one)


def checker(group_size: int = 3) -> chk.Checker:
    def run(test, hist, opts):
        # group reads by their key set
        reads: dict = {}
        for op in hist:
            if op.type != "ok" or not op.value:
                continue
            mops = op.value
            if all(m[0] == "r" for m in mops):
                ks = tuple(sorted(m[1] for m in mops))
                vals = {m[1]: m[2] for m in mops}
                reads.setdefault(ks, []).append((op, vals))
        forks = []
        for ks, rs in reads.items():
            if len(rs) < 2:
                continue
            r_mat = np.array([[1.0 if vals.get(k) is not None else 0.0
                               for k in ks] for _op, vals in rs],
                             dtype=np.float32)
            a = (r_mat @ (1.0 - r_mat).T) > 0
            fork = a & a.T
            for i, j in zip(*np.nonzero(np.triu(fork, 1))):
                forks.append({"read1": rs[i][0], "read2": rs[j][0]})
        return {"valid?": not forks,
                "fork-count": len(forks),
                "forks": forks[:8]}

    return _Fn(run)


def workload(opts: dict | None = None) -> dict:
    o = dict(opts or {})
    gsize = o.get("group-size", 3)
    return {
        "generator": generator(gsize, o.get("ops", 300)),
        "checker": checker(gsize),
    }
