"""Clusterless test fixtures: noop test, in-memory CAS register DB.

Capability reference: jepsen/src/jepsen/tests.clj (noop-test 11-24,
atom-db 26-32, atom-client 34-66). These power the reference's own
end-to-end tests (core_test.clj:69-120) and ours.
"""

from __future__ import annotations

import threading
import time

from . import client as jclient
from . import db as jdb
from . import nemesis as jnemesis
from . import net as jnet
from . import os_setup


def noop_test() -> dict:
    """A boring test stub, basis for writing real tests
    (tests.clj:11-24)."""
    return {
        "nodes": ["n1", "n2", "n3", "n4", "n5"],
        "name": None,  # no store dir by default in unit tests
        "os": os_setup.noop,
        "db": jdb.noop,
        "net": jnet.iptables,
        "ssh": {"dummy": True},
        "client": jclient.noop,
        "nemesis": jnemesis.noop,
        "generator": None,
    }


class AtomState:
    """A lock-guarded in-memory register (the reference's atom)."""

    def __init__(self, value=None):
        self.lock = threading.Lock()
        self.value = value


class AtomDB(jdb.DB):
    def __init__(self, state: AtomState):
        self.state = state

    def setup(self, test, node):
        with self.state.lock:
            self.state.value = 0

    def teardown(self, test, node):
        with self.state.lock:
            self.state.value = "done"


class AtomClient(jclient.Client):
    """A CAS register client over shared in-memory state
    (tests.clj:34-66)."""

    def __init__(self, state: AtomState, meta_log: list | None = None,
                 latency_s: float = 0.001):
        self.state = state
        self.meta_log = meta_log if meta_log is not None else []
        self.latency_s = latency_s

    def open(self, test, node):
        self.meta_log.append("open")
        return self

    def setup(self, test):
        self.meta_log.append("setup")
        return self

    def teardown(self, test):
        self.meta_log.append("teardown")

    def close(self, test):
        self.meta_log.append("close")

    def invoke(self, test, op):
        # Sleep to create actual concurrency, like the reference's
        # (Thread/sleep 1).
        if self.latency_s:
            time.sleep(self.latency_s)
        if op.f == "write":
            with self.state.lock:
                self.state.value = op.value
            return op.copy(type="ok")
        if op.f == "cas":
            cur, new = op.value
            with self.state.lock:
                if self.state.value == cur:
                    self.state.value = new
                    return op.copy(type="ok")
            return op.copy(type="fail")
        if op.f == "read":
            with self.state.lock:
                v = self.state.value
            return op.copy(type="ok", value=v)
        raise ValueError(f"unknown f {op.f!r}")


class ListAppendState:
    """In-memory strict-serializable list-append store for elle-style
    workloads (mirrors core_test.clj's atom database for txns)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.data: dict = {}

    def apply_txn(self, txn):
        out = []
        with self.lock:
            for f, k, v in txn:
                if f == "r":
                    out.append([f, k, list(self.data.get(k, []))])
                elif f == "append":
                    self.data.setdefault(k, []).append(v)
                    out.append([f, k, v])
                else:
                    raise ValueError(f"unknown mop {f!r}")
        return out


class ListAppendClient(jclient.Client):
    def __init__(self, state: ListAppendState, latency_s: float = 0.0005):
        self.state = state
        self.latency_s = latency_s

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        if self.latency_s:
            time.sleep(self.latency_s)
        return op.copy(type="ok", value=self.state.apply_txn(op.value))


class KVState:
    """Lock-guarded keyed CAS registers for independent-key workloads."""

    def __init__(self):
        self.lock = threading.Lock()
        self.data: dict = {}


class KVClient(jclient.Client):
    """Register client over keyed state; op values are (key, v) tuples
    (the independent.clj tuple convention)."""

    def __init__(self, state: KVState, latency_s: float = 0.0005):
        self.state = state
        self.latency_s = latency_s

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        if self.latency_s:
            time.sleep(self.latency_s)
        k, v = op.value
        with self.state.lock:
            if op.f == "write":
                self.state.data[k] = v
                return op.copy(type="ok")
            if op.f == "cas":
                cur, new = v
                if self.state.data.get(k) == cur:
                    self.state.data[k] = new
                    return op.copy(type="ok")
                return op.copy(type="fail")
            if op.f == "read":
                return op.copy(type="ok",
                               value=(k, self.state.data.get(k)))
        raise ValueError(f"unknown f {op.f!r}")


class BankState:
    def __init__(self, accounts, initial=10):
        self.lock = threading.Lock()
        self.balances = {a: initial for a in accounts}


class BankClient(jclient.Client):
    """Serializable in-memory bank (tests/bank.clj semantics)."""

    def __init__(self, state: BankState, latency_s: float = 0.0005):
        self.state = state
        self.latency_s = latency_s

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        if self.latency_s:
            time.sleep(self.latency_s)
        with self.state.lock:
            if op.f == "read":
                return op.copy(type="ok", value=dict(self.state.balances))
            v = op.value
            frm, to, amt = v["from"], v["to"], v["amount"]
            if self.state.balances.get(frm, 0) < amt:
                return op.copy(type="fail")
            self.state.balances[frm] -= amt
            self.state.balances[to] = self.state.balances.get(to, 0) + amt
            return op.copy(type="ok")


class SetClient(jclient.Client):
    """In-memory grow-only set; drop_every simulates lost adds."""

    def __init__(self, state=None, drop_every: int = 0,
                 latency_s: float = 0.0003):
        self.state = state if state is not None else {"set": set(),
                                                      "n": 0}
        self.lock = threading.Lock()
        self.drop_every = drop_every
        self.latency_s = latency_s

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        if self.latency_s:
            time.sleep(self.latency_s)
        with self.lock:
            if op.f == "add":
                self.state["n"] += 1
                if self.drop_every and \
                        self.state["n"] % self.drop_every == 0:
                    return op.copy(type="ok")  # ack but drop: lost add
                self.state["set"].add(op.value)
                return op.copy(type="ok")
            if op.f == "read":
                return op.copy(type="ok",
                               value=sorted(self.state["set"]))
        raise ValueError(f"unknown f {op.f!r}")


class QueueClient(jclient.Client):
    """In-memory queue with optional message loss."""

    def __init__(self, state=None, drop_every: int = 0,
                 latency_s: float = 0.0003):
        self.state = state if state is not None else {"q": [], "n": 0}
        self.lock = threading.Lock()
        self.drop_every = drop_every
        self.latency_s = latency_s

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        if self.latency_s:
            time.sleep(self.latency_s)
        with self.lock:
            if op.f == "enqueue":
                self.state["n"] += 1
                if self.drop_every and \
                        self.state["n"] % self.drop_every == 0:
                    return op.copy(type="ok")
                self.state["q"].append(op.value)
                return op.copy(type="ok")
            if op.f == "dequeue":
                if self.state["q"]:
                    return op.copy(type="ok",
                                   value=self.state["q"].pop(0))
                return op.copy(type="fail")
            if op.f == "drain":
                got, self.state["q"] = self.state["q"], []
                return op.copy(type="ok", value=got)
        raise ValueError(f"unknown f {op.f!r}")


class CounterClient(jclient.Client):
    def __init__(self, state=None, latency_s: float = 0.0003):
        self.state = state if state is not None else {"v": 0}
        self.lock = threading.Lock()
        self.latency_s = latency_s

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        if self.latency_s:
            time.sleep(self.latency_s)
        with self.lock:
            if op.f == "add":
                self.state["v"] += op.value
                return op.copy(type="ok")
            if op.f == "read":
                return op.copy(type="ok", value=self.state["v"])
        raise ValueError(f"unknown f {op.f!r}")


class UniqueIdsClient(jclient.Client):
    def __init__(self, state=None, dup_every: int = 0,
                 latency_s: float = 0.0003):
        self.state = state if state is not None else {"n": 0}
        self.lock = threading.Lock()
        self.dup_every = dup_every
        self.latency_s = latency_s

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        if self.latency_s:
            time.sleep(self.latency_s)
        with self.lock:
            self.state["n"] += 1
            n = self.state["n"]
            if self.dup_every and n % self.dup_every == 0:
                n = 1  # duplicate id
            return op.copy(type="ok", value=n)


class TxnClient(jclient.Client):
    """Strict-serializable txn client over keyed lists/registers: handles
    append/r (list-append) and w/r (rw-register) micro-ops."""

    def __init__(self, state: "ListAppendState" = None,
                 latency_s: float = 0.0003):
        self.state = state if state is not None else ListAppendState()
        self.latency_s = latency_s

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        if self.latency_s:
            time.sleep(self.latency_s)
        out = []
        with self.state.lock:
            for f, k, v in op.value:
                if f == "r":
                    cur = self.state.data.get(k)
                    out.append([f, k, list(cur) if isinstance(cur, list)
                                else cur])
                elif f == "append":
                    self.state.data.setdefault(k, []).append(v)
                    out.append([f, k, v])
                elif f == "w":
                    self.state.data[k] = v
                    out.append([f, k, v])
                else:
                    raise ValueError(f"unknown mop {f!r}")
        return op.copy(type="ok", value=out)


class CausalClient(jclient.Client):
    """Single-site causal register per key: positions increase
    monotonically, links chain per key; lose_write makes later reads
    stale (the anomaly the causal checker catches). Mirrors the
    reference's in-memory fixtures in jepsen.tests (tests.clj:26-66
    pattern, applied to tests/causal.clj semantics)."""

    def __init__(self, state=None, lose_write=False):
        self.state = state if state is not None else {
            "lock": threading.Lock(), "regs": {}, "pos": 0}
        self.lose_write = lose_write

    def open(self, test, node):
        return CausalClient(self.state, self.lose_write)

    def invoke(self, test, o):
        from . import independent

        k = independent.key_(o.value)
        v = independent.value_(o.value)
        with self.state["lock"]:
            reg = self.state["regs"].setdefault(
                k, {"value": 0, "counter": 0, "last": "init"})
            self.state["pos"] += 1
            pos = self.state["pos"]
            link = reg["last"]
            reg["last"] = pos
            if o.f == "write":
                if not (self.lose_write and v == 1):
                    reg["value"] = v
                reg["counter"] += 1
                out = v
            else:
                out = reg["value"]
            return o.copy(type="ok",
                          value=independent.ktuple(k, out),
                          position=pos,
                          link="init" if o.f == "read-init" else link)


class PerKeySetClient(jclient.Client):
    """Blind writes into a per-key list; reads return it (the
    causal-reverse workload's client shape). hide_first drops the
    oldest acked write from later reads — the T2-without-T1 anomaly."""

    def __init__(self, state=None, hide_first=False):
        self.state = state if state is not None else {
            "lock": threading.Lock(), "sets": {}}
        self.hide_first = hide_first

    def open(self, test, node):
        return PerKeySetClient(self.state, self.hide_first)

    def invoke(self, test, o):
        from . import independent

        k = independent.key_(o.value)
        v = independent.value_(o.value)
        with self.state["lock"]:
            s = self.state["sets"].setdefault(k, [])
            if o.f == "write":
                s.append(v)
                return o.copy(type="ok")
            vals = list(s)
            if self.hide_first and len(vals) > 2:
                vals = vals[1:]
            return o.copy(type="ok",
                          value=independent.ktuple(k, vals))


class G2Client(jclient.Client):
    """Predicate-read-then-insert: under the lock at most one insert
    per key commits (serializable); broken=True lets both commit — the
    adya G2 anomaly."""

    def __init__(self, state=None, broken=False):
        self.state = state if state is not None else {
            "lock": threading.Lock(), "rows": {}}
        self.broken = broken

    def open(self, test, node):
        return G2Client(self.state, self.broken)

    def invoke(self, test, o):
        from . import independent

        k = independent.key_(o.value)
        with self.state["lock"]:
            if self.state["rows"].get(k) and not self.broken:
                return o.copy(type="fail")
            self.state["rows"].setdefault(k, []).append(
                independent.value_(o.value))
            return o.copy(type="ok")


class KafkaState:
    """Shared in-memory partitioned log with per-(client, key)
    consumer positions."""

    def __init__(self):
        self.lock = threading.Lock()
        self.logs: dict = {}

    def append(self, k, v) -> int:
        with self.lock:
            self.logs.setdefault(k, []).append(v)
            return len(self.logs[k]) - 1


class KafkaClient(jclient.Client):
    """Drives the kafka workload's send/poll/txn + subscribe/assign op
    encoding against KafkaState; lose_offset makes one committed send
    invisible to every consumer (a lost write)."""

    def __init__(self, state=None, lose_offset=None):
        self.state = state if state is not None else KafkaState()
        self.lose_offset = lose_offset  # (key, offset) to hide
        self.positions: dict = {}

    def open(self, test, node):
        c = KafkaClient(self.state, self.lose_offset)
        return c

    def invoke(self, test, o):
        if o.f in ("subscribe", "assign"):
            for k in o.value or []:
                self.positions.setdefault(k, 0)
            return o.copy(type="ok")
        done = []
        for m in o.value:
            if m[0] == "send":
                _f, k, v = m
                off = self.state.append(k, v)
                done.append(["send", k, [off, v]])
            else:
                reads: dict = {}
                with self.state.lock:
                    logs = {k: list(vs)
                            for k, vs in self.state.logs.items()}
                for k, log in logs.items():
                    pos = self.positions.get(k, 0)
                    pairs = []
                    for i in range(pos, len(log)):
                        if self.lose_offset == (k, i):
                            continue
                        pairs.append([i, log[i]])
                    if pairs:
                        reads[k] = pairs
                    self.positions[k] = len(log)
                done.append(["poll", reads])
        return o.copy(type="ok", value=done)


class MonotonicState:
    """Rows for the monotonic workload, with a perfect (or skewed)
    logical clock."""

    def __init__(self):
        self.lock = threading.Lock()
        self.rows: list = []
        self.clock = 0


class MonotonicClient(jclient.Client):
    """In-memory monotonic-inserts client (mirrors cockroach
    monotonic.clj semantics): add reads the max, inserts max+1 with a
    db timestamp; read returns rows sorted by timestamp.
    `skew_every` makes every Nth timestamp run backwards (an ordering
    violation); `dup_every` re-inserts an existing value."""

    def __init__(self, state=None, skew_every: int = 0,
                 dup_every: int = 0, node_index: int = 0):
        self.state = state if state is not None else MonotonicState()
        self.skew_every = skew_every
        self.dup_every = dup_every
        self.node_index = node_index

    def open(self, test, node):
        idx = list(test.get("nodes", ())).index(node) \
            if node in test.get("nodes", ()) else 0
        return MonotonicClient(self.state, self.skew_every,
                               self.dup_every, idx)

    def invoke(self, test, op):
        s = self.state
        with s.lock:
            if op.f == "add":
                cur_max = max((r["val"] for r in s.rows), default=0)
                val = cur_max + 1
                if self.dup_every and len(s.rows) and \
                        len(s.rows) % self.dup_every == 0:
                    val = s.rows[-1]["val"]  # duplicate insert
                s.clock += 1
                sts = s.clock
                if self.skew_every and \
                        len(s.rows) % self.skew_every == (
                            self.skew_every - 1):
                    sts = max(s.clock - 3, 0)  # clock ran backwards
                row = {"val": val, "sts": sts,
                       "node": self.node_index,
                       "process": op.process,
                       "tb": len(s.rows) % 2}
                s.rows.append(row)
                return op.copy(type="ok", value=row)
            if op.f == "read":
                rows = sorted(s.rows, key=lambda r: r["sts"])
                return op.copy(type="ok", value=rows)
        raise ValueError(f"unknown f {op.f!r}")


class SequentialState:
    def __init__(self):
        self.lock = threading.Lock()
        self.present: set = set()


class SequentialClient(jclient.Client):
    """In-memory sequential-consistency client: writes insert a key's
    subkeys in order (each its own 'txn'); reads probe them reversed.
    `hide_first_every` makes every Nth write skip its FIRST subkey (a
    later subkey visible without the earlier one -> violation)."""

    def __init__(self, state=None, key_count: int = 5,
                 hide_first_every: int = 0):
        self.state = state if state is not None else SequentialState()
        self.key_count = key_count
        self.hide_first_every = hide_first_every
        self._writes = 0

    def open(self, test, node):
        c = SequentialClient(self.state,
                             test.get("key_count", self.key_count),
                             self.hide_first_every)
        return c

    def invoke(self, test, op):
        from .workloads import sequential as seq

        s = self.state
        ks = seq.subkeys(self.key_count, op.value)
        if op.f == "write":
            self._writes += 1
            skip_first = (self.hide_first_every
                          and self._writes % self.hide_first_every
                          == 0)
            for i, k in enumerate(ks):
                if skip_first and i == 0:
                    continue
                with s.lock:
                    s.present.add(k)
            return op.copy(type="ok")
        if op.f == "read":
            obs = []
            for k in reversed(ks):
                with s.lock:
                    obs.append(k if k in s.present else None)
            return op.copy(type="ok", value=(op.value, obs))
        raise ValueError(f"unknown f {op.f!r}")


class DirtyReadState:
    """Visible vs committed value sets, for the dirty-read workload.
    Healthy behavior keeps them identical."""

    def __init__(self):
        self.lock = threading.Lock()
        self.visible: set = set()
        self.committed: set = set()


class DirtyReadClient(jclient.Client):
    """In-memory dirty-read client. `dirty_every` makes every Nth
    write visible-but-never-committed (its ack crashes): readers can
    observe it, strong reads won't — a dirty read. `lose_every` acks
    every Nth write but drops it from the committed set — a lost
    write."""

    def __init__(self, state=None, dirty_every: int = 0,
                 lose_every: int = 0):
        self.state = state if state is not None else DirtyReadState()
        self.dirty_every = dirty_every
        self.lose_every = lose_every
        self._writes = 0

    def open(self, test, node):
        c = DirtyReadClient(self.state, self.dirty_every,
                            self.lose_every)
        return c

    def invoke(self, test, op):
        s = self.state
        if op.f == "write":
            self._writes += 1
            with s.lock:
                s.visible.add(op.value)
                if self.dirty_every and \
                        self._writes % self.dirty_every == 0:
                    # crashes un-acked; never commits, stays visible
                    # for a while so a racing read can catch it
                    return op.copy(type="info", error="conn lost")
                if self.lose_every and \
                        self._writes % self.lose_every == 0:
                    s.visible.discard(op.value)  # acked yet gone
                    return op.copy(type="ok")
                s.committed.add(op.value)
            return op.copy(type="ok")
        if op.f == "read":
            with s.lock:
                found = op.value in s.visible
            return op.copy(type="ok" if found else "fail")
        if op.f == "refresh":
            with s.lock:
                # convergence: uncommitted in-flight values vanish
                s.visible = set(s.committed)
            return op.copy(type="ok")
        if op.f == "strong-read":
            with s.lock:
                return op.copy(type="ok", value=sorted(s.visible))
        raise ValueError(f"unknown f {op.f!r}")
