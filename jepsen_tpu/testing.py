"""Clusterless test fixtures: noop test, in-memory CAS register DB.

Capability reference: jepsen/src/jepsen/tests.clj (noop-test 11-24,
atom-db 26-32, atom-client 34-66). These power the reference's own
end-to-end tests (core_test.clj:69-120) and ours.
"""

from __future__ import annotations

import threading
import time

from . import client as jclient
from . import db as jdb
from . import nemesis as jnemesis
from . import net as jnet
from . import os_setup


def noop_test() -> dict:
    """A boring test stub, basis for writing real tests
    (tests.clj:11-24)."""
    return {
        "nodes": ["n1", "n2", "n3", "n4", "n5"],
        "name": None,  # no store dir by default in unit tests
        "os": os_setup.noop,
        "db": jdb.noop,
        "net": jnet.iptables,
        "ssh": {"dummy": True},
        "client": jclient.noop,
        "nemesis": jnemesis.noop,
        "generator": None,
    }


class AtomState:
    """A lock-guarded in-memory register (the reference's atom)."""

    def __init__(self, value=None):
        self.lock = threading.Lock()
        self.value = value


class AtomDB(jdb.DB):
    def __init__(self, state: AtomState):
        self.state = state

    def setup(self, test, node):
        with self.state.lock:
            self.state.value = 0

    def teardown(self, test, node):
        with self.state.lock:
            self.state.value = "done"


class AtomClient(jclient.Client):
    """A CAS register client over shared in-memory state
    (tests.clj:34-66)."""

    def __init__(self, state: AtomState, meta_log: list | None = None,
                 latency_s: float = 0.001):
        self.state = state
        self.meta_log = meta_log if meta_log is not None else []
        self.latency_s = latency_s

    def open(self, test, node):
        self.meta_log.append("open")
        return self

    def setup(self, test):
        self.meta_log.append("setup")
        return self

    def teardown(self, test):
        self.meta_log.append("teardown")

    def close(self, test):
        self.meta_log.append("close")

    def invoke(self, test, op):
        # Sleep to create actual concurrency, like the reference's
        # (Thread/sleep 1).
        if self.latency_s:
            time.sleep(self.latency_s)
        if op.f == "write":
            with self.state.lock:
                self.state.value = op.value
            return op.copy(type="ok")
        if op.f == "cas":
            cur, new = op.value
            with self.state.lock:
                if self.state.value == cur:
                    self.state.value = new
                    return op.copy(type="ok")
            return op.copy(type="fail")
        if op.f == "read":
            with self.state.lock:
                v = self.state.value
            return op.copy(type="ok", value=v)
        raise ValueError(f"unknown f {op.f!r}")


class ListAppendState:
    """In-memory strict-serializable list-append store for elle-style
    workloads (mirrors core_test.clj's atom database for txns)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.data: dict = {}

    def apply_txn(self, txn):
        out = []
        with self.lock:
            for f, k, v in txn:
                if f == "r":
                    out.append([f, k, list(self.data.get(k, []))])
                elif f == "append":
                    self.data.setdefault(k, []).append(v)
                    out.append([f, k, v])
                else:
                    raise ValueError(f"unknown mop {f!r}")
        return out


class ListAppendClient(jclient.Client):
    def __init__(self, state: ListAppendState, latency_s: float = 0.0005):
        self.state = state
        self.latency_s = latency_s

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        if self.latency_s:
            time.sleep(self.latency_s)
        return op.copy(type="ok", value=self.state.apply_txn(op.value))


class KVState:
    """Lock-guarded keyed CAS registers for independent-key workloads."""

    def __init__(self):
        self.lock = threading.Lock()
        self.data: dict = {}


class KVClient(jclient.Client):
    """Register client over keyed state; op values are (key, v) tuples
    (the independent.clj tuple convention)."""

    def __init__(self, state: KVState, latency_s: float = 0.0005):
        self.state = state
        self.latency_s = latency_s

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        if self.latency_s:
            time.sleep(self.latency_s)
        k, v = op.value
        with self.state.lock:
            if op.f == "write":
                self.state.data[k] = v
                return op.copy(type="ok")
            if op.f == "cas":
                cur, new = v
                if self.state.data.get(k) == cur:
                    self.state.data[k] = new
                    return op.copy(type="ok")
                return op.copy(type="fail")
            if op.f == "read":
                return op.copy(type="ok",
                               value=(k, self.state.data.get(k)))
        raise ValueError(f"unknown f {op.f!r}")


class BankState:
    def __init__(self, accounts, initial=10):
        self.lock = threading.Lock()
        self.balances = {a: initial for a in accounts}


class BankClient(jclient.Client):
    """Serializable in-memory bank (tests/bank.clj semantics)."""

    def __init__(self, state: BankState, latency_s: float = 0.0005):
        self.state = state
        self.latency_s = latency_s

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        if self.latency_s:
            time.sleep(self.latency_s)
        with self.state.lock:
            if op.f == "read":
                return op.copy(type="ok", value=dict(self.state.balances))
            v = op.value
            frm, to, amt = v["from"], v["to"], v["amount"]
            if self.state.balances.get(frm, 0) < amt:
                return op.copy(type="fail")
            self.state.balances[frm] -= amt
            self.state.balances[to] = self.state.balances.get(to, 0) + amt
            return op.copy(type="ok")


class SetClient(jclient.Client):
    """In-memory grow-only set; drop_every simulates lost adds."""

    def __init__(self, state=None, drop_every: int = 0,
                 latency_s: float = 0.0003):
        self.state = state if state is not None else {"set": set(),
                                                      "n": 0}
        self.lock = threading.Lock()
        self.drop_every = drop_every
        self.latency_s = latency_s

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        if self.latency_s:
            time.sleep(self.latency_s)
        with self.lock:
            if op.f == "add":
                self.state["n"] += 1
                if self.drop_every and \
                        self.state["n"] % self.drop_every == 0:
                    return op.copy(type="ok")  # ack but drop: lost add
                self.state["set"].add(op.value)
                return op.copy(type="ok")
            if op.f == "read":
                return op.copy(type="ok",
                               value=sorted(self.state["set"]))
        raise ValueError(f"unknown f {op.f!r}")


class QueueClient(jclient.Client):
    """In-memory queue with optional message loss."""

    def __init__(self, state=None, drop_every: int = 0,
                 latency_s: float = 0.0003):
        self.state = state if state is not None else {"q": [], "n": 0}
        self.lock = threading.Lock()
        self.drop_every = drop_every
        self.latency_s = latency_s

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        if self.latency_s:
            time.sleep(self.latency_s)
        with self.lock:
            if op.f == "enqueue":
                self.state["n"] += 1
                if self.drop_every and \
                        self.state["n"] % self.drop_every == 0:
                    return op.copy(type="ok")
                self.state["q"].append(op.value)
                return op.copy(type="ok")
            if op.f == "dequeue":
                if self.state["q"]:
                    return op.copy(type="ok",
                                   value=self.state["q"].pop(0))
                return op.copy(type="fail")
            if op.f == "drain":
                got, self.state["q"] = self.state["q"], []
                return op.copy(type="ok", value=got)
        raise ValueError(f"unknown f {op.f!r}")


class CounterClient(jclient.Client):
    def __init__(self, state=None, latency_s: float = 0.0003):
        self.state = state if state is not None else {"v": 0}
        self.lock = threading.Lock()
        self.latency_s = latency_s

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        if self.latency_s:
            time.sleep(self.latency_s)
        with self.lock:
            if op.f == "add":
                self.state["v"] += op.value
                return op.copy(type="ok")
            if op.f == "read":
                return op.copy(type="ok", value=self.state["v"])
        raise ValueError(f"unknown f {op.f!r}")


class UniqueIdsClient(jclient.Client):
    def __init__(self, state=None, dup_every: int = 0,
                 latency_s: float = 0.0003):
        self.state = state if state is not None else {"n": 0}
        self.lock = threading.Lock()
        self.dup_every = dup_every
        self.latency_s = latency_s

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        if self.latency_s:
            time.sleep(self.latency_s)
        with self.lock:
            self.state["n"] += 1
            n = self.state["n"]
            if self.dup_every and n % self.dup_every == 0:
                n = 1  # duplicate id
            return op.copy(type="ok", value=n)


class TxnClient(jclient.Client):
    """Strict-serializable txn client over keyed lists/registers: handles
    append/r (list-append) and w/r (rw-register) micro-ops."""

    def __init__(self, state: "ListAppendState" = None,
                 latency_s: float = 0.0003):
        self.state = state if state is not None else ListAppendState()
        self.latency_s = latency_s

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        if self.latency_s:
            time.sleep(self.latency_s)
        out = []
        with self.state.lock:
            for f, k, v in op.value:
                if f == "r":
                    cur = self.state.data.get(k)
                    out.append([f, k, list(cur) if isinstance(cur, list)
                                else cur])
                elif f == "append":
                    self.state.data.setdefault(k, []).append(v)
                    out.append([f, k, v])
                elif f == "w":
                    self.state.data[k] = v
                    out.append([f, k, v])
                else:
                    raise ValueError(f"unknown mop {f!r}")
        return op.copy(type="ok", value=out)


class CausalClient(jclient.Client):
    """Single-site causal register per key: positions increase
    monotonically, links chain per key; lose_write makes later reads
    stale (the anomaly the causal checker catches). Mirrors the
    reference's in-memory fixtures in jepsen.tests (tests.clj:26-66
    pattern, applied to tests/causal.clj semantics)."""

    def __init__(self, state=None, lose_write=False):
        self.state = state if state is not None else {
            "lock": threading.Lock(), "regs": {}, "pos": 0}
        self.lose_write = lose_write

    def open(self, test, node):
        return CausalClient(self.state, self.lose_write)

    def invoke(self, test, o):
        from . import independent

        k = independent.key_(o.value)
        v = independent.value_(o.value)
        with self.state["lock"]:
            reg = self.state["regs"].setdefault(
                k, {"value": 0, "counter": 0, "last": "init"})
            self.state["pos"] += 1
            pos = self.state["pos"]
            link = reg["last"]
            reg["last"] = pos
            if o.f == "write":
                if not (self.lose_write and v == 1):
                    reg["value"] = v
                reg["counter"] += 1
                out = v
            else:
                out = reg["value"]
            return o.copy(type="ok",
                          value=independent.ktuple(k, out),
                          position=pos,
                          link="init" if o.f == "read-init" else link)


class PerKeySetClient(jclient.Client):
    """Blind writes into a per-key list; reads return it (the
    causal-reverse workload's client shape). hide_first drops the
    oldest acked write from later reads — the T2-without-T1 anomaly."""

    def __init__(self, state=None, hide_first=False):
        self.state = state if state is not None else {
            "lock": threading.Lock(), "sets": {}}
        self.hide_first = hide_first

    def open(self, test, node):
        return PerKeySetClient(self.state, self.hide_first)

    def invoke(self, test, o):
        from . import independent

        k = independent.key_(o.value)
        v = independent.value_(o.value)
        with self.state["lock"]:
            s = self.state["sets"].setdefault(k, [])
            if o.f == "write":
                s.append(v)
                return o.copy(type="ok")
            vals = list(s)
            if self.hide_first and len(vals) > 2:
                vals = vals[1:]
            return o.copy(type="ok",
                          value=independent.ktuple(k, vals))


class G2Client(jclient.Client):
    """Predicate-read-then-insert: under the lock at most one insert
    per key commits (serializable); broken=True lets both commit — the
    adya G2 anomaly."""

    def __init__(self, state=None, broken=False):
        self.state = state if state is not None else {
            "lock": threading.Lock(), "rows": {}}
        self.broken = broken

    def open(self, test, node):
        return G2Client(self.state, self.broken)

    def invoke(self, test, o):
        from . import independent

        k = independent.key_(o.value)
        with self.state["lock"]:
            if self.state["rows"].get(k) and not self.broken:
                return o.copy(type="fail")
            self.state["rows"].setdefault(k, []).append(
                independent.value_(o.value))
            return o.copy(type="ok")


class KafkaState:
    """Shared in-memory partitioned log with per-(client, key)
    consumer positions."""

    def __init__(self):
        self.lock = threading.Lock()
        self.logs: dict = {}

    def append(self, k, v) -> int:
        with self.lock:
            self.logs.setdefault(k, []).append(v)
            return len(self.logs[k]) - 1


class KafkaClient(jclient.Client):
    """Drives the kafka workload's send/poll/txn + subscribe/assign op
    encoding against KafkaState; lose_offset makes one committed send
    invisible to every consumer (a lost write)."""

    def __init__(self, state=None, lose_offset=None):
        self.state = state if state is not None else KafkaState()
        self.lose_offset = lose_offset  # (key, offset) to hide
        self.positions: dict = {}

    def open(self, test, node):
        c = KafkaClient(self.state, self.lose_offset)
        return c

    def invoke(self, test, o):
        if o.f in ("subscribe", "assign"):
            for k in o.value or []:
                self.positions.setdefault(k, 0)
            return o.copy(type="ok")
        done = []
        for m in o.value:
            if m[0] == "send":
                _f, k, v = m
                off = self.state.append(k, v)
                done.append(["send", k, [off, v]])
            else:
                reads: dict = {}
                with self.state.lock:
                    logs = {k: list(vs)
                            for k, vs in self.state.logs.items()}
                for k, log in logs.items():
                    pos = self.positions.get(k, 0)
                    pairs = []
                    for i in range(pos, len(log)):
                        if self.lose_offset == (k, i):
                            continue
                        pairs.append([i, log[i]])
                    if pairs:
                        reads[k] = pairs
                    self.positions[k] = len(log)
                done.append(["poll", reads])
        return o.copy(type="ok", value=done)


class MonotonicState:
    """Rows for the monotonic workload, with a perfect (or skewed)
    logical clock."""

    def __init__(self):
        self.lock = threading.Lock()
        self.rows: list = []
        self.clock = 0


class MonotonicClient(jclient.Client):
    """In-memory monotonic-inserts client (mirrors cockroach
    monotonic.clj semantics): add reads the max, inserts max+1 with a
    db timestamp; read returns rows sorted by timestamp.
    `skew_every` makes every Nth timestamp run backwards (an ordering
    violation); `dup_every` re-inserts an existing value."""

    def __init__(self, state=None, skew_every: int = 0,
                 dup_every: int = 0, node_index: int = 0):
        self.state = state if state is not None else MonotonicState()
        self.skew_every = skew_every
        self.dup_every = dup_every
        self.node_index = node_index

    def open(self, test, node):
        idx = list(test.get("nodes", ())).index(node) \
            if node in test.get("nodes", ()) else 0
        return MonotonicClient(self.state, self.skew_every,
                               self.dup_every, idx)

    def invoke(self, test, op):
        s = self.state
        with s.lock:
            if op.f == "add":
                cur_max = max((r["val"] for r in s.rows), default=0)
                val = cur_max + 1
                if self.dup_every and len(s.rows) and \
                        len(s.rows) % self.dup_every == 0:
                    val = s.rows[-1]["val"]  # duplicate insert
                s.clock += 1
                sts = s.clock
                if self.skew_every and \
                        len(s.rows) % self.skew_every == (
                            self.skew_every - 1):
                    sts = max(s.clock - 3, 0)  # clock ran backwards
                row = {"val": val, "sts": sts,
                       "node": self.node_index,
                       "process": op.process,
                       "tb": len(s.rows) % 2}
                s.rows.append(row)
                return op.copy(type="ok", value=row)
            if op.f == "read":
                rows = sorted(s.rows, key=lambda r: r["sts"])
                return op.copy(type="ok", value=rows)
        raise ValueError(f"unknown f {op.f!r}")


class SequentialState:
    def __init__(self):
        self.lock = threading.Lock()
        self.present: set = set()


class SequentialClient(jclient.Client):
    """In-memory sequential-consistency client: writes insert a key's
    subkeys in order (each its own 'txn'); reads probe them reversed.
    `hide_first_every` makes every Nth write skip its FIRST subkey (a
    later subkey visible without the earlier one -> violation)."""

    def __init__(self, state=None, key_count: int = 5,
                 hide_first_every: int = 0):
        self.state = state if state is not None else SequentialState()
        self.key_count = key_count
        self.hide_first_every = hide_first_every
        self._writes = 0

    def open(self, test, node):
        c = SequentialClient(self.state,
                             test.get("key_count", self.key_count),
                             self.hide_first_every)
        return c

    def invoke(self, test, op):
        from .workloads import sequential as seq

        s = self.state
        ks = seq.subkeys(self.key_count, op.value)
        if op.f == "write":
            self._writes += 1
            skip_first = (self.hide_first_every
                          and self._writes % self.hide_first_every
                          == 0)
            for i, k in enumerate(ks):
                if skip_first and i == 0:
                    continue
                with s.lock:
                    s.present.add(k)
            return op.copy(type="ok")
        if op.f == "read":
            obs = []
            for k in reversed(ks):
                with s.lock:
                    obs.append(k if k in s.present else None)
            return op.copy(type="ok", value=(op.value, obs))
        raise ValueError(f"unknown f {op.f!r}")


class DirtyReadState:
    """Visible vs committed value sets, for the dirty-read workload.
    Healthy behavior keeps them identical."""

    def __init__(self):
        self.lock = threading.Lock()
        self.visible: set = set()
        self.committed: set = set()


class DirtyReadClient(jclient.Client):
    """In-memory dirty-read client. `dirty_every` makes every Nth
    write visible-but-never-committed (its ack crashes): readers can
    observe it, strong reads won't — a dirty read. `lose_every` acks
    every Nth write but drops it from the committed set — a lost
    write."""

    def __init__(self, state=None, dirty_every: int = 0,
                 lose_every: int = 0):
        self.state = state if state is not None else DirtyReadState()
        self.dirty_every = dirty_every
        self.lose_every = lose_every
        self._writes = 0

    def open(self, test, node):
        c = DirtyReadClient(self.state, self.dirty_every,
                            self.lose_every)
        return c

    def invoke(self, test, op):
        s = self.state
        if op.f == "write":
            self._writes += 1
            with s.lock:
                s.visible.add(op.value)
                if self.dirty_every and \
                        self._writes % self.dirty_every == 0:
                    # crashes un-acked; never commits, stays visible
                    # for a while so a racing read can catch it
                    return op.copy(type="info", error="conn lost")
                if self.lose_every and \
                        self._writes % self.lose_every == 0:
                    s.visible.discard(op.value)  # acked yet gone
                    return op.copy(type="ok")
                s.committed.add(op.value)
            return op.copy(type="ok")
        if op.f == "read":
            with s.lock:
                found = op.value in s.visible
            return op.copy(type="ok" if found else "fail")
        if op.f == "refresh":
            with s.lock:
                # convergence: uncommitted in-flight values vanish
                s.visible = set(s.committed)
            return op.copy(type="ok")
        if op.f == "strong-read":
            with s.lock:
                return op.copy(type="ok", value=sorted(s.visible))
        raise ValueError(f"unknown f {op.f!r}")


class LockState:
    """Shared in-memory lock service: owner, reentrancy count, and a
    monotonic fencing-token counter."""

    def __init__(self, permits: int = 1):
        self.lock = threading.Lock()
        self.owner = None
        self.count = 0
        self.fence = 0
        self.permits = permits
        self.held: dict = {}  # process -> permits held (semaphore mode)


class LockClient(jclient.Client):
    """In-memory fenced lock / semaphore client (the hazelcast.clj
    client families). `reentrant_limit` > 1 allows nested acquires;
    `semaphore=True` switches to permit semantics; `steal_every`
    grants every Nth busy acquire anyway WITHOUT a fresh fence — a
    mutual-exclusion violation with a stale token, the classic
    fencing failure."""

    def __init__(self, state=None, reentrant_limit: int = 1,
                 semaphore: bool = False, steal_every: int = 0,
                 fences: bool = True):
        self.state = state if state is not None else LockState()
        self.reentrant_limit = reentrant_limit
        self.semaphore = semaphore
        self.steal_every = steal_every
        self.fences = fences
        self._attempts = 0

    def open(self, test, node):
        c = LockClient(self.state, self.reentrant_limit,
                       self.semaphore, self.steal_every, self.fences)
        return c

    def _sem_invoke(self, s: LockState, op):
        total = sum(s.held.values())
        mine = s.held.get(op.process, 0)
        if op.f == "acquire":
            if total < s.permits:
                s.held[op.process] = mine + 1
                return op.copy(type="ok")
            return op.copy(type="fail", error="no permits")
        if mine > 0:
            s.held[op.process] = mine - 1
            return op.copy(type="ok")
        return op.copy(type="fail", error="not-permit-owner")

    def invoke(self, test, op):
        s = self.state
        with s.lock:
            if self.semaphore:
                return self._sem_invoke(s, op)
            if op.f == "acquire":
                self._attempts += 1
                if s.owner is None or (s.owner == op.process
                                       and s.count
                                       < self.reentrant_limit):
                    first = s.owner is None
                    s.owner = op.process
                    s.count += 1
                    if first and self.fences:
                        s.fence += 1
                    val = {"fence": s.fence} if self.fences else None
                    return op.copy(type="ok", value=val)
                if self.steal_every and \
                        self._attempts % self.steal_every == 0:
                    # grants despite a holder, reusing a stale fence
                    s.owner = op.process
                    s.count = 1
                    val = {"fence": s.fence} if self.fences else None
                    return op.copy(type="ok", value=val)
                return op.copy(type="fail", error="busy")
            if op.f == "release":
                if s.owner != op.process:
                    return op.copy(type="fail",
                                   error="not-lock-owner")
                s.count -= 1
                if s.count == 0:
                    s.owner = None
                return op.copy(type="ok")
        raise ValueError(f"unknown f {op.f!r}")


class UpsertClient(jclient.Client):
    """Per-key insert-unless-exists returning a fresh uid on creation
    (dgraph upsert.clj client). race_every lets every Nth contended
    upsert create a SECOND record for the key — the uniqueness
    violation the checker must catch."""

    def __init__(self, state=None, race_every: int = 0):
        self.state = state if state is not None else {
            "lock": threading.Lock(), "rows": {}, "next_uid": 1,
            "attempts": 0}
        self.race_every = race_every

    def open(self, test, node):
        return UpsertClient(self.state, self.race_every)

    def invoke(self, test, op):
        from . import independent

        s = self.state
        k = independent.key_(op.value)
        with s["lock"]:
            rows = s["rows"].setdefault(k, [])
            if op.f == "upsert":
                s["attempts"] += 1
                racing = self.race_every and \
                    s["attempts"] % self.race_every == 0
                if rows and not racing:
                    return op.copy(type="fail", error="exists")
                uid = s["next_uid"]
                s["next_uid"] += 1
                rows.append(uid)
                return op.copy(
                    type="ok", value=independent.ktuple(k, uid))
            if op.f == "read":
                return op.copy(
                    type="ok",
                    value=independent.ktuple(k, sorted(rows)))
        raise ValueError(f"unknown f {op.f!r}")


class SchedulerClient(jclient.Client):
    """In-memory job scheduler (chronos.clj shape): add-job records
    the spec; the final read synthesizes the runs a faithful scheduler
    would have produced for every due target (start jittered within
    epsilon, completed after `duration`). miss_every drops every Nth
    target's run — the lost-invocation bug run-coverage must flag."""

    def __init__(self, state=None, miss_every: int = 0,
                 late_every: int = 0):
        self.state = state if state is not None else {
            "lock": threading.Lock(), "jobs": []}
        self.miss_every = miss_every
        self.late_every = late_every

    def open(self, test, node):
        return SchedulerClient(self.state, self.miss_every,
                               self.late_every)

    def invoke(self, test, op):
        from .workloads import scheduler as sched

        s = self.state
        with s["lock"]:
            if op.f == "add-job":
                s["jobs"].append(dict(op.value))
                return op.copy(type="ok")
            if op.f == "read":
                read_time = max(
                    [j["start"] + j["interval"] * j["count"]
                     for j in s["jobs"]] + [0.0]) + 60.0
                runs, n = [], 0
                for job in s["jobs"]:
                    for (t0, _dl) in sched.job_targets(
                            read_time, job):
                        n += 1
                        if self.miss_every and \
                                n % self.miss_every == 0:
                            continue
                        start = t0 + (job["epsilon"] + 30.0
                                      if self.late_every
                                      and n % self.late_every == 0
                                      else 0.5)
                        runs.append({"name": job["name"],
                                     "start": start,
                                     "end": start + job["duration"]})
                return op.copy(type="ok", value={"time": read_time,
                                                 "runs": runs})
        raise ValueError(f"unknown f {op.f!r}")


class PagesClient(jclient.Client):
    """Per-key element store with atomic group inserts (faunadb
    pages.clj client). tear_every serves every Nth read while a group
    is half-applied — the pagination-isolation anomaly."""

    def __init__(self, state=None, tear_every: int = 0):
        self.state = state if state is not None else {
            "lock": threading.Lock(), "rows": {}, "reads": 0}
        self.tear_every = tear_every

    def open(self, test, node):
        return PagesClient(self.state, self.tear_every)

    def invoke(self, test, op):
        from . import independent

        s = self.state
        k = independent.key_(op.value)
        v = independent.value_(op.value)
        with s["lock"]:
            rows = s["rows"].setdefault(k, [])
            if op.f == "add":
                rows.extend(v)
                return op.copy(type="ok")
            if op.f == "read":
                s["reads"] += 1
                vals = list(rows)
                if self.tear_every and \
                        s["reads"] % self.tear_every == 0 and \
                        len(vals) > 2:
                    vals = vals[:-2]  # half of the last group missing
                return op.copy(
                    type="ok", value=independent.ktuple(k, vals))
        raise ValueError(f"unknown f {op.f!r}")


class MultiRegClient(jclient.Client):
    """Increment-only multi-register store with a logical read
    timestamp (faunadb multimonotonic.clj client). stale_every serves
    every Nth read from an old snapshot with a CURRENT timestamp —
    the ts-order violation."""

    def __init__(self, state=None, stale_every: int = 0):
        self.state = state if state is not None else {
            "lock": threading.Lock(), "regs": {}, "ts": 0,
            "reads": 0, "snapshots": []}
        self.stale_every = stale_every

    def open(self, test, node):
        return MultiRegClient(self.state, self.stale_every)

    def invoke(self, test, op):
        s = self.state
        with s["lock"]:
            if op.f == "write":
                for k, v in (op.value or {}).items():
                    s["regs"][k] = v
                s["ts"] += 1
                return op.copy(type="ok")
            if op.f == "read":
                s["reads"] += 1
                s["ts"] += 1
                regs = dict(s["regs"])
                stale = (self.stale_every
                         and s["reads"] % self.stale_every == 0
                         and s["snapshots"])
                if stale:
                    # a lagging replica: values from several reads
                    # ago served under a CURRENT timestamp
                    regs = dict(s["snapshots"][0])
                else:
                    s["snapshots"].append(dict(regs))
                    if len(s["snapshots"]) > 8:
                        s["snapshots"].pop(0)
                return op.copy(type="ok", value={"ts": s["ts"],
                                                 "registers": regs})
        raise ValueError(f"unknown f {op.f!r}")


class VersionedSetClient(jclient.Client):
    """Per-key element list guarded by a row version (crate
    lost_updates.clj client shape): add re-reads and writes back iff
    the version is unchanged. lose_every makes every Nth guarded
    update ack WITHOUT applying — a lost update."""

    def __init__(self, state=None, lose_every: int = 0):
        self.state = state if state is not None else {
            "lock": threading.Lock(), "rows": {}, "adds": 0}
        self.lose_every = lose_every

    def open(self, test, node):
        return VersionedSetClient(self.state, self.lose_every)

    def invoke(self, test, op):
        from . import independent

        s = self.state
        k = independent.key_(op.value)
        v = independent.value_(op.value)
        with s["lock"]:
            row = s["rows"].setdefault(k, {"els": [], "version": 0})
            if op.f == "add":
                s["adds"] += 1
                if self.lose_every and \
                        s["adds"] % self.lose_every == 0:
                    return op.copy(type="ok")  # acked, never applied
                row["els"].append(v)
                row["version"] += 1
                return op.copy(type="ok")
            if op.f == "read":
                return op.copy(type="ok", value=independent.ktuple(
                    k, sorted(row["els"])))
        raise ValueError(f"unknown f {op.f!r}")


class VersionRegClient(jclient.Client):
    """Versioned register (crate version_divergence.clj client):
    writes bump _version; reads return {value, version}.
    diverge_every makes every Nth read report a DIFFERENT value under
    the same version — replica divergence."""

    def __init__(self, state=None, diverge_every: int = 0):
        self.state = state if state is not None else {
            "lock": threading.Lock(), "rows": {}, "reads": 0}
        self.diverge_every = diverge_every

    def open(self, test, node):
        return VersionRegClient(self.state, self.diverge_every)

    def invoke(self, test, op):
        from . import independent

        s = self.state
        k = independent.key_(op.value)
        v = independent.value_(op.value)
        with s["lock"]:
            row = s["rows"].get(k)
            if op.f == "write":
                if row is None:
                    s["rows"][k] = {"value": v, "version": 1}
                else:
                    row["value"] = v
                    row["version"] += 1
                return op.copy(type="ok")
            if op.f == "read":
                s["reads"] += 1
                if row is None:
                    return op.copy(
                        type="ok", value=independent.ktuple(k, None))
                out = {"value": row["value"],
                       "version": row["version"]}
                if self.diverge_every and \
                        s["reads"] % self.diverge_every == 0:
                    out = {"value": (row["value"] or 0) + 100000,
                           "version": row["version"]}
                return op.copy(
                    type="ok", value=independent.ktuple(k, out))
        raise ValueError(f"unknown f {op.f!r}")
