"""Immutable generator contexts.

A context tells generators what time it is, which threads exist, which are
free, and which process each thread is currently executing. Contexts are
persistent values: every mutation returns a new context.

Capability reference: jepsen/src/jepsen/generator/context.clj (IContext ops
context.clj:49-93, Context record 95-114, thread filters 300-360) and
generator/translation_table.clj. The reference uses Java BitSets and
Bifurcan maps; here thread sets are arbitrary-precision Python ints used
as bitsets (bit i set = thread index i present), which makes
intersection/filtering single `&` ops.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Iterable

NEMESIS = "nemesis"


class TranslationTable:
    """Interns thread names (ints 0..n-1 plus named threads like 'nemesis')
    to dense indices. Mirrors generator/translation_table.clj."""

    __slots__ = ("int_thread_count", "named_threads", "named_to_index")

    def __init__(self, int_thread_count: int, named_threads: Iterable[Any]):
        self.int_thread_count = int_thread_count
        self.named_threads = tuple(named_threads)
        self.named_to_index = {
            name: int_thread_count + i
            for i, name in enumerate(self.named_threads)
        }

    def thread_count(self) -> int:
        return self.int_thread_count + len(self.named_threads)

    def name_to_index(self, thread) -> int:
        if isinstance(thread, int):
            return thread
        return self.named_to_index[thread]

    def index_to_name(self, i: int):
        if i < self.int_thread_count:
            return i
        return self.named_threads[i - self.int_thread_count]

    def all_names(self):
        return list(range(self.int_thread_count)) + list(self.named_threads)


def _bits(indices: Iterable[int]) -> int:
    b = 0
    for i in indices:
        b |= 1 << i
    return b


def _iter_bits(bitset: int):
    i = 0
    while bitset:
        tz = (bitset & -bitset).bit_length() - 1
        yield tz
        bitset &= bitset - 1
        i += 1


def _popcount(bitset: int) -> int:
    return bitset.bit_count()


class Context:
    """Immutable context. See module docstring.

    Thread *names* are ints 0..concurrency-1 plus 'nemesis'; thread
    *indices* are dense ints from the translation table. Processes start
    equal to their thread names; crashed client threads move to process
    (process + int_thread_count) — mirrors with-next-process
    (context.clj:240-258).
    """

    __slots__ = ("time", "next_thread_index", "tt", "all_threads",
                 "free_threads", "thread_index_to_process",
                 "process_to_thread", "ext")

    def __init__(self, time, next_thread_index, tt, all_threads, free_threads,
                 thread_index_to_process, process_to_thread, ext=None):
        self.time = time
        self.next_thread_index = next_thread_index
        self.tt = tt
        self.all_threads = all_threads          # bitset of thread indices
        self.free_threads = free_threads        # bitset of thread indices
        self.thread_index_to_process = thread_index_to_process  # tuple
        self.process_to_thread = process_to_thread              # dict proc→thread name
        self.ext = ext or {}

    # -- construction -------------------------------------------------------

    @classmethod
    def for_test(cls, test: dict) -> "Context":
        """Fresh context: threads 0..concurrency-1 plus nemesis, all free,
        each executing itself (context.clj `context`, 262-296)."""
        concurrency = int(test.get("concurrency", 1))
        named = [NEMESIS]
        tt = TranslationTable(concurrency, named)
        n = tt.thread_count()
        all_bits = (1 << n) - 1
        names = tt.all_names()
        return cls(
            time=0,
            next_thread_index=0,
            tt=tt,
            all_threads=all_bits,
            free_threads=all_bits,
            thread_index_to_process=tuple(names),
            process_to_thread={name: name for name in names},
            # per-test scheduling RNG, only when the test asks for one:
            # two seeded tests in one process keep independent
            # deterministic schedules, while seedless tests keep using
            # the module fallback (which set_seed controls)
            ext=({"rng": random.Random(test["seed"])}
                 if test.get("seed") is not None else {}),
        )

    def _clone(self, **kw) -> "Context":
        return Context(
            kw.get("time", self.time),
            kw.get("next_thread_index", self.next_thread_index),
            self.tt,
            kw.get("all_threads", self.all_threads),
            kw.get("free_threads", self.free_threads),
            kw.get("thread_index_to_process", self.thread_index_to_process),
            kw.get("process_to_thread", self.process_to_thread),
            kw.get("ext", self.ext),
        )

    # -- map-ish ------------------------------------------------------------

    def with_time(self, time: int) -> "Context":
        return self._clone(time=time)

    def get(self, k, default=None):
        if k == "time":
            return self.time
        return self.ext.get(k, default)

    def assoc(self, k, v) -> "Context":
        if k == "time":
            return self.with_time(v)
        ext = dict(self.ext)
        ext[k] = v
        return self._clone(ext=ext)

    # -- IContext -----------------------------------------------------------

    def all_thread_names(self) -> list:
        return [self.tt.index_to_name(i) for i in _iter_bits(self.all_threads)]

    def all_thread_count(self) -> int:
        return _popcount(self.all_threads)

    def free_thread_count(self) -> int:
        return _popcount(self.free_threads)

    def free_thread_names(self) -> list:
        return [self.tt.index_to_name(i) for i in _iter_bits(self.free_threads)]

    def all_processes(self) -> list:
        return [self.thread_index_to_process[i]
                for i in _iter_bits(self.all_threads)]

    def free_processes(self) -> list:
        return [self.thread_index_to_process[i]
                for i in _iter_bits(self.free_threads)]

    def process_to_thread_name(self, process):
        return self.process_to_thread.get(process)

    def thread_to_process(self, thread):
        return self.thread_index_to_process[self.tt.name_to_index(thread)]

    def thread_free(self, thread) -> bool:
        return bool(self.free_threads >> self.tt.name_to_index(thread) & 1)

    def some_free_process(self):
        """A free process, rotating fairly from next_thread_index
        (context.clj:203-220)."""
        free = self.free_threads
        if free == 0:
            return None
        # Bits at or above next_thread_index:
        hi = free >> self.next_thread_index
        if hi:
            i = self.next_thread_index + ((hi & -hi).bit_length() - 1)
        else:
            i = (free & -free).bit_length() - 1
        return self.thread_index_to_process[i]

    def busy_thread(self, time, thread) -> "Context":
        """Marks thread busy at the given time, bumping the fairness
        rotation (context.clj:229-238)."""
        i = self.tt.name_to_index(thread)
        return self._clone(
            time=time,
            next_thread_index=(self.next_thread_index + 1)
            % self.tt.thread_count(),
            free_threads=self.free_threads & ~(1 << i),
        )

    def free_thread(self, time, thread) -> "Context":
        i = self.tt.name_to_index(thread)
        return self._clone(time=time, free_threads=self.free_threads | (1 << i))

    def with_next_process(self, thread) -> "Context":
        """Replaces the thread's process with a fresh one: integer process p
        becomes p + int_thread_count (context.clj:240-258)."""
        process = self.thread_to_process(thread)
        if isinstance(process, int):
            process2 = process + self.tt.int_thread_count
        else:
            process2 = process
        i = self.tt.name_to_index(thread)
        tip = list(self.thread_index_to_process)
        tip[i] = process2
        p2t = dict(self.process_to_thread)
        p2t.pop(process, None)
        p2t[process2] = thread
        return self._clone(thread_index_to_process=tuple(tip),
                           process_to_thread=p2t)


class AllBut:
    """Predicate matching every thread except one (context.clj:300-312)."""

    __slots__ = ("element",)

    def __init__(self, element):
        self.element = element

    def __call__(self, x):
        return None if x == self.element else x


def all_but(x) -> AllBut:
    return AllBut(x)


def truthy(x) -> bool:
    """Clojure truthiness: everything except None/False is truthy. Needed
    because thread name 0 must count as a match from predicates like
    AllBut that return the name itself."""
    return x is not None and x is not False


def make_thread_filter(pred: Callable, ctx: Context | None = None):
    """Precomputes a context-restriction function keeping only threads whose
    *name* satisfies pred (context.clj:322-360). Returns a fn ctx→ctx'."""
    if ctx is None:
        cache: dict = {}

        def lazy_filter(c: Context) -> Context:
            f = cache.get("f")
            if f is None:
                f = make_thread_filter(pred, c)
                cache["f"] = f
            return f(c)

        return lazy_filter

    mask = 0
    for i in _iter_bits(ctx.all_threads):
        if truthy(pred(ctx.tt.index_to_name(i))):
            mask |= 1 << i

    def by_bitset(c: Context) -> Context:
        return c._clone(all_threads=c.all_threads & mask,
                        free_threads=c.free_threads & mask)

    return by_bitset
